"""Native fast-I/O engine (storage/fastio.py): alignment edges, the
fallback ladder, buffer-pool backpressure, digest-fusion equivalence,
and chaos cleanliness on the direct path.

The bitwise contract under test: for ANY size/offset/knob combination,
the engine's bytes and (crc32, adler32) digests are identical to the
pure-Python path's — O_DIRECT, bounce-buffer heads/tails, pwritev
batching and fadvise fallbacks are pure transport details that may
never leak into stored content.
"""

import glob
import os
import threading
import zlib

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict, knobs, obs
from torchsnapshot_tpu.io_types import ReadIO, WriteIO
from torchsnapshot_tpu.resilience import reset_breakers
from torchsnapshot_tpu.storage import fastio as fastio_mod
from torchsnapshot_tpu.storage.fs import FSStoragePlugin

_LIB_OK = None


def _engine_available() -> bool:
    global _LIB_OK
    if _LIB_OK is None:
        from torchsnapshot_tpu import _csrc

        lib = _csrc.load()
        _LIB_OK = lib is not None and hasattr(lib, "tsnp_part_pwrite")
    return _LIB_OK


def _direct_supported(root) -> bool:
    return fastio_mod.probe_direct(str(root))


needs_engine = pytest.mark.skipif(
    not _engine_available(), reason="no C++ toolchain / engine symbols"
)


@pytest.fixture(autouse=True)
def _fast_backoff():
    reset_breakers()
    with knobs.override_retry_backoff_cap_s(0.01):
        yield
    reset_breakers()


# the interesting sizes: zero-length, sub-sector, exactly one sector,
# sector+1 (head-only tail), multi-sector with ragged tail, and a span
# big enough to cross several bounce fills when the bounce is shrunk
_EDGE_SIZES = [0, 1, 511, 4096, 4097, 65536 + 17, (1 << 20) + 4095]


@needs_engine
@pytest.mark.parametrize("size", _EDGE_SIZES)
@pytest.mark.parametrize("direct", [False, True])
def test_write_read_roundtrip_alignment_edges(tmp_path, size, direct, monkeypatch):
    if direct and not _direct_supported(tmp_path):
        pytest.skip("filesystem lacks O_DIRECT")
    # force the direct leg onto small spans so sub-sector head/tail
    # bounce handling is exercised at test-sized payloads
    monkeypatch.setattr(fastio_mod, "DIRECT_MIN_BYTES", 1)
    data = np.random.default_rng(size or 1).integers(
        0, 256, size=size, dtype=np.uint8
    )
    with knobs.override_fastio_direct(direct):
        plugin = FSStoragePlugin(root=str(tmp_path / "r"))
    assert plugin._fastio is not None
    assert plugin._fastio.direct == direct
    wio = WriteIO(path="a/b", buf=data, want_digest=True)
    plugin.sync_write(wio)
    assert wio.digests == (
        zlib.crc32(data.tobytes()),
        zlib.adler32(data.tobytes()),
    )
    with open(tmp_path / "r" / "a" / "b", "rb") as f:
        assert f.read() == data.tobytes()
    rio = ReadIO(path="a/b")
    plugin.sync_read(rio)
    assert bytes(memoryview(rio.buf)) == data.tobytes()
    # ranged read at a deliberately unaligned offset
    if size > 600:
        rio = ReadIO(path="a/b", byte_range=[513, size - 7])
        plugin.sync_read(rio)
        assert bytes(memoryview(rio.buf)) == data.tobytes()[513 : size - 7]
    # read-into honors the destination hint through the engine
    dst = np.empty(size, np.uint8)
    rio = ReadIO(path="a/b", into=dst)
    plugin.sync_read(rio)
    assert rio.buf is dst
    assert dst.tobytes() == data.tobytes()


@needs_engine
@pytest.mark.parametrize("part_size", [4096 - 7, 65536 + 13])
def test_striped_parts_unaligned_offsets_fuse_digests(
    tmp_path, part_size, monkeypatch
):
    """Part sizes that are NOT sector multiples give every later part
    an unaligned offset — heads/tails go through the bounce while the
    aligned body goes direct, and each part's fused digest must equal
    zlib's."""
    direct = _direct_supported(tmp_path)
    if direct:
        monkeypatch.setattr(fastio_mod, "DIRECT_MIN_BYTES", 1)
    total = part_size * 4 + 1234
    data = np.random.default_rng(7).integers(0, 256, size=total, dtype=np.uint8)
    with knobs.override_fastio_direct(direct):
        plugin = FSStoragePlugin(root=str(tmp_path / "r"))

    async def go():
        handle = await plugin.begin_striped_write("obj", total)
        assert handle.supports_fused_digest
        lo = 0
        idx = 0
        try:
            while lo < total:
                hi = min(lo + part_size, total)
                d = await handle.write_part(
                    idx, lo, data[lo:hi], want_digest=True
                )
                assert d == (
                    zlib.crc32(data[lo:hi].tobytes()),
                    zlib.adler32(data[lo:hi].tobytes()),
                )
                lo = hi
                idx += 1
        except BaseException:
            await handle.abort()
            raise
        await handle.complete()

    import asyncio

    asyncio.new_event_loop().run_until_complete(go())
    with open(tmp_path / "r" / "obj", "rb") as f:
        assert f.read() == data.tobytes()
    # every direct-path bounce buffer went back to the pool (no pool
    # exists at all on a buffered-only engine)
    pool = plugin._fastio._pool
    assert plugin._fastio.pool_free_count() == (pool.count if pool else 0)
    assert (pool is not None) == direct


@needs_engine
def test_direct_unsupported_degrades_to_buffered_with_dontneed(
    tmp_path, monkeypatch
):
    """FASTIO_DIRECT on a filesystem without O_DIRECT: the engine takes
    the fadvise(DONTNEED) rung — bytes and digests stay identical, and
    the fallback is visible in storage.fastio.dontneed_reads."""
    monkeypatch.setattr(fastio_mod, "probe_direct", lambda root: False)
    from torchsnapshot_tpu import _csrc

    with knobs.override_fastio_direct(True):
        plugin = FSStoragePlugin(root=str(tmp_path / "r"))
    eng = plugin._fastio
    assert eng is not None and not eng.direct and eng.dontneed
    data = np.random.default_rng(3).integers(0, 256, size=123457, dtype=np.uint8)
    wio = WriteIO(path="x", buf=data, want_digest=True)
    plugin.sync_write(wio)
    assert wio.digests == (
        zlib.crc32(data.tobytes()),
        zlib.adler32(data.tobytes()),
    )
    c0 = obs.counter(obs.FASTIO_DONTNEED_READS).value
    rio = ReadIO(path="x")
    plugin.sync_read(rio)
    assert bytes(memoryview(rio.buf)) == data.tobytes()
    assert obs.counter(obs.FASTIO_DONTNEED_READS).value == c0 + 1


@needs_engine
def test_probe_direct_readonly_rung(tmp_path, monkeypatch):
    """A root that refuses file CREATION (read-only serving mount) must
    still probe direct-capable via O_RDONLY|O_DIRECT on an existing
    payload file — the restore side is the bypass's primary customer."""
    if not _direct_supported(tmp_path):
        pytest.skip("filesystem lacks O_DIRECT")
    (tmp_path / "payload").write_bytes(b"x" * 8192)
    real_open = os.open

    def deny_create(path, flags, *a, **k):
        if flags & os.O_CREAT:
            raise OSError(30, "Read-only file system", path)
        return real_open(path, flags, *a, **k)

    monkeypatch.setattr(os, "open", deny_create)
    assert fastio_mod.probe_direct(str(tmp_path))
    monkeypatch.undo()
    # an empty read-only root has nothing to probe against: unsupported
    empty = tmp_path / "empty"
    empty.mkdir()
    assert fastio_mod._probe_direct_readonly(str(empty), os.O_DIRECT) is False


@needs_engine
def test_fastio_zero_knob_and_probe_failure_keep_pre_engine_paths(tmp_path):
    """FASTIO=0 (and a lib without the engine symbols) must yield the
    pre-engine native path — same bytes, plugin still functional."""
    data = np.random.default_rng(5).integers(0, 256, size=70001, dtype=np.uint8)
    with knobs.override_fastio(False):
        plugin = FSStoragePlugin(root=str(tmp_path / "off"))
    assert plugin._fastio is None
    plugin.sync_write(WriteIO(path="x", buf=data))
    rio = ReadIO(path="x")
    plugin.sync_read(rio)
    assert bytes(memoryview(rio.buf)) == data.tobytes()
    # a lib that predates the engine symbols degrades the same way
    class _Stale:
        pass

    assert fastio_mod.create_engine(_Stale(), str(tmp_path)) is None
    assert fastio_mod.create_engine(None, str(tmp_path)) is None


@needs_engine
def test_pool_exhaustion_backpressures_and_recovers(tmp_path, monkeypatch):
    """A 1-buffer pool under concurrent direct part writes: later parts
    WAIT for a bounce buffer instead of allocating (pool_waits counts
    them), everything completes bitwise-correct, and the pool is whole
    afterwards."""
    if not _direct_supported(tmp_path):
        pytest.skip("filesystem lacks O_DIRECT")
    monkeypatch.setattr(fastio_mod, "DIRECT_MIN_BYTES", 1)
    with knobs.override_fastio_direct(True):
        plugin = FSStoragePlugin(root=str(tmp_path / "r"))
    eng = plugin._fastio
    assert eng is not None and eng.direct
    eng._pool = fastio_mod._AlignedPool(1, buf_bytes=1 << 20)  # ONE buffer
    assert eng._pool.count == 1
    part = 2 << 20
    nparts = 6
    data = np.random.default_rng(9).integers(
        0, 256, size=part * nparts, dtype=np.uint8
    )
    full = str(tmp_path / "r" / "obj")
    fd = os.open(full, os.O_RDWR | os.O_CREAT, 0o644)
    os.ftruncate(fd, part * nparts)
    fdd = eng.open_direct(full)
    assert fdd >= 0
    w0 = obs.counter(obs.FASTIO_POOL_WAITS).value
    errors = []

    def worker(i):
        try:
            d = eng.pwrite_part(
                fd, fdd, i * part, data[i * part : (i + 1) * part], True
            )
            assert d == (
                zlib.crc32(data[i * part : (i + 1) * part].tobytes()),
                zlib.adler32(data[i * part : (i + 1) * part].tobytes()),
            )
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(nparts)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    os.close(fdd)
    os.close(fd)
    assert errors == []
    with open(full, "rb") as f:
        assert f.read() == data.tobytes()
    assert obs.counter(obs.FASTIO_POOL_WAITS).value > w0
    assert eng.pool_free_count() == 1


# --------------------------------------------------- whole-stack legs


def _tree(rng):
    # the corruption-fuzz payload shape: mixed dtypes/sizes + scalars
    dtypes = [np.float32, np.float64, np.int32, np.uint8, np.int16]
    t = {}
    for i in range(int(rng.integers(2, 6))):
        dt = dtypes[int(rng.integers(len(dtypes)))]
        n = int(rng.integers(1, 60000))
        t[f"w{i}"] = (rng.standard_normal(n) * 8).astype(dt)
    t["s"] = "a string leaf"
    t["k"] = int(rng.integers(0, 1000))
    return t


def _payload_bytes(root):
    out = {}
    for dirpath, _dirs, files in os.walk(root):
        for f in files:
            if f in (".snapshot_metadata", ".snapshot_obsrecord"):
                continue
            p = os.path.join(dirpath, f)
            with open(p, "rb") as fh:
                out[os.path.relpath(p, root)] = fh.read()
    return out


@needs_engine
@pytest.mark.parametrize("striped", [False, True])
@pytest.mark.parametrize("codec", ["raw", "zlib"])
def test_snapshot_bitwise_equivalence_vs_pure_python(
    tmp_path, striped, codec, monkeypatch
):
    """The acceptance contract: engine on (direct where supported) and
    the pure-Python path produce byte-identical snapshots — across
    striped/unstriped × codec-on/off — and each restores the other's
    bytes bitwise."""
    direct = _direct_supported(tmp_path)
    if direct:
        monkeypatch.setattr(fastio_mod, "DIRECT_MIN_BYTES", 1)
    rng = np.random.default_rng(42)
    tree = _tree(rng)
    import contextlib

    ctx = contextlib.ExitStack()
    ctx.enter_context(knobs.override_codec(codec))
    if striped:
        ctx.enter_context(knobs.override_stripe_part_size_bytes(1 << 16))
        ctx.enter_context(knobs.override_stripe_min_object_size_bytes(1 << 16))
    with ctx:
        with knobs.override_fastio_direct(direct):
            snap_native = Snapshot.take(
                str(tmp_path / "native"), {"m": StateDict(**tree)}
            )
        with knobs.override_enable_native_ext(False):
            snap_py = Snapshot.take(
                str(tmp_path / "py"), {"m": StateDict(**tree)}
            )
        assert snap_native.verify(deep=True).ok
        assert snap_py.verify(deep=True).ok
        native_files = _payload_bytes(str(tmp_path / "native"))
        py_files = _payload_bytes(str(tmp_path / "py"))
        assert native_files == py_files
        # both directions: each path restores the OTHER's snapshot
        for src, reader_native in (("py", True), ("native", False)):
            dest = {
                "m": StateDict(
                    **{
                        k: np.zeros_like(v)
                        if isinstance(v, np.ndarray)
                        else type(v)()
                        for k, v in tree.items()
                    }
                )
            }
            with knobs.override_enable_native_ext(reader_native):
                Snapshot(str(tmp_path / src)).restore(dest)
            for k, v in tree.items():
                if isinstance(v, np.ndarray):
                    np.testing.assert_array_equal(dest["m"][k], v)
                else:
                    assert dest["m"][k] == v


@needs_engine
def test_scheduler_defers_digest_to_fused_striped_parts(tmp_path):
    """Stripe-eligible fs writes defer checksum work to the write: the
    folded per-part fused digests land in the manifest and deep-verify
    agrees with them."""
    f0 = obs.counter(obs.FASTIO_FUSED_DIGESTS).value
    with knobs.override_stripe_part_size_bytes(1 << 16), (
        knobs.override_stripe_min_object_size_bytes(1 << 16)
    ), knobs.override_disable_batching(True):
        data = np.arange(1 << 16, dtype=np.float32)  # 256KB -> 4 parts
        snap = Snapshot.take(
            str(tmp_path / "s"), {"m": StateDict(w=data)}
        )
    assert obs.counter(obs.FASTIO_FUSED_DIGESTS).value - f0 >= 4
    assert snap.verify(deep=True).ok
    out = snap.read_object("0/m/w")
    np.testing.assert_array_equal(np.asarray(out), data)


# ------------------------------------------------------------ chaos


@needs_engine
def test_chaos_fatal_part_fault_on_direct_path_aborts_clean(
    tmp_path, monkeypatch
):
    """A fatal mid-stripe failure on the DIRECT path: abort leaves zero
    .tsnp-tmp-* files, no commit marker, and every pool buffer back —
    exactly as clean as the buffered path."""
    direct = _direct_supported(tmp_path)
    if direct:
        monkeypatch.setattr(fastio_mod, "DIRECT_MIN_BYTES", 1)
    path = str(tmp_path / "s")
    state = {"app": StateDict(w=np.arange(1 << 17, dtype=np.float32))}
    with knobs.override_stripe_part_size_bytes(1 << 16), (
        knobs.override_stripe_min_object_size_bytes(1 << 16)
    ), knobs.override_fastio_direct(direct), (
        knobs.override_failpoints("storage.fs.part.write=io")
    ):
        with pytest.raises(OSError):
            Snapshot.take(path, state)
    assert not os.path.exists(os.path.join(path, ".snapshot_metadata"))
    assert (
        glob.glob(os.path.join(path, "**", "*tsnp-tmp*"), recursive=True)
        == []
    )
    reset_breakers()
    # the same plugin config takes cleanly once the fault clears, and
    # the pool is whole (no orphaned bounce buffers from the abort)
    with knobs.override_stripe_part_size_bytes(1 << 16), (
        knobs.override_stripe_min_object_size_bytes(1 << 16)
    ), knobs.override_fastio_direct(direct):
        Snapshot.take(path, state)
        plugin = FSStoragePlugin(root=path)
        eng = plugin._fastio
        assert eng is not None
        assert eng.pool_free_count() == (
            eng._pool.count if eng._pool is not None else 0
        )
    dest = {"app": StateDict(w=np.zeros(1 << 17, np.float32))}
    Snapshot(path).restore(dest)
    np.testing.assert_array_equal(
        dest["app"]["w"], np.arange(1 << 17, dtype=np.float32)
    )


@needs_engine
def test_chaos_transient_part_faults_on_engine_path_retry_clean(tmp_path):
    """Transient EINTR on engine part writes: parts retry independently
    and the take commits with fused digests that deep-verify."""
    path = str(tmp_path / "s")
    r0 = obs.counter(obs.RESILIENCE_RETRIES).value
    with knobs.override_stripe_part_size_bytes(1 << 16), (
        knobs.override_stripe_min_object_size_bytes(1 << 16)
    ), knobs.override_failpoints("storage.fs.part.write=eintr:1:3"):
        snap = Snapshot.take(
            path, {"app": StateDict(w=np.arange(1 << 17, dtype=np.float32))}
        )
    assert obs.counter(obs.RESILIENCE_RETRIES).value - r0 >= 3
    assert snap.verify(deep=True).ok
    assert (
        glob.glob(os.path.join(path, "**", "*tsnp-tmp*"), recursive=True)
        == []
    )
