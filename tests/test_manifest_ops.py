"""Per-rank manifest views, shard merging, elasticity, consolidation
(reference tests/test_manifest.py planner-level cases)."""

from torchsnapshot_tpu.manifest import (
    ArrayEntry,
    DictEntry,
    PrimitiveEntry,
    Shard,
    ShardedArrayEntry,
    SnapshotMetadata,
)
from torchsnapshot_tpu.manifest_ops import (
    consolidate_manifests,
    get_manifest_for_rank,
    merge_sharded_entries,
)


def _arr(location, replicated=False):
    return ArrayEntry(location, "buffer_protocol", "float32", [4], replicated)


def _sharded(rows):
    return ShardedArrayEntry(
        dtype="float32",
        shape=[8, 4],
        shards=[
            Shard(offsets=[r, 0], sizes=[n, 4], location=f"sharded/w.{r}")
            for r, n in rows
        ],
    )


def test_per_rank_view_basic():
    md = SnapshotMetadata(
        version="0.1.0",
        world_size=2,
        manifest={
            "0/app": DictEntry(keys=["w", "s"]),
            "0/app/w": _arr("0/app/w"),
            "0/app/s": PrimitiveEntry("int", "1", replicated=False),
            "1/app": DictEntry(keys=["w", "s"]),
            "1/app/w": _arr("1/app/w"),
            "1/app/s": PrimitiveEntry("int", "2", replicated=False),
        },
    )
    v0 = get_manifest_for_rank(md, 0)
    assert v0["app/w"].location == "0/app/w"
    assert v0["app/s"].get_value() == 1
    v1 = get_manifest_for_rank(md, 1)
    assert v1["app/w"].location == "1/app/w"


def test_replicated_visible_to_all_ranks():
    md = SnapshotMetadata(
        version="0.1.0",
        world_size=2,
        manifest={
            "0/app": DictEntry(keys=["w"]),
            "0/app/w": _arr("replicated/app/w", replicated=True),
            "1/app": DictEntry(keys=["w"]),
        },
    )
    v1 = get_manifest_for_rank(md, 1)
    assert v1["app/w"].location == "replicated/app/w"


def test_world_growth_new_rank_gets_rank0_view():
    md = SnapshotMetadata(
        version="0.1.0",
        world_size=2,
        manifest={
            "0/app": DictEntry(keys=["w", "x", "local"]),
            "0/app/w": _arr("replicated/app/w", replicated=True),
            "0/app/x": _sharded([(0, 4)]),
            "0/app/local": _arr("0/app/local"),
            "1/app": DictEntry(keys=["w", "x", "local"]),
            "1/app/x": _sharded([(4, 4)]),
            "1/app/local": _arr("1/app/local"),
        },
    )
    v5 = get_manifest_for_rank(md, 5)  # rank beyond saved world size
    assert v5["app/w"].location == "replicated/app/w"
    # merged shards from all ranks
    assert len(v5["app/x"].shards) == 2
    # rank-local non-replicated state is not inherited
    assert "app/local" not in v5
    # containers available for inflate
    assert v5["app"].keys == ["w", "x", "local"]


def test_merge_dedups_replica_boxes():
    a = _sharded([(0, 4)])
    b = _sharded([(0, 4), (4, 4)])
    merged = merge_sharded_entries([a, b])
    assert [tuple(s.offsets) for s in merged.shards] == [(0, 0), (4, 0)]


def _twisted(rows, **twist):
    e = _sharded(rows)
    for k, v in twist.items():
        setattr(e, k, v)
    return e


def test_merge_rejects_divergent_metadata():
    # A dtype swap with equal itemsize would pass verify.py's extent
    # checks and silently misinterpret every other rank's payload under
    # entries[0]'s metadata — merging must refuse instead.
    import pytest

    a = _sharded([(0, 4)])
    for twist in (
        {"dtype": "int32"},  # same itemsize as float32
        {"shape": [8, 5]},
        {"spec": [["dp"], None]},
        {"mesh_shape": [4, 2]},  # replica sets derive from the mesh
        {"mesh_axis_names": ["dp", "tp"]},
    ):
        with pytest.raises(ValueError, match="disagree"):
            merge_sharded_entries([a, _twisted([(4, 4)], **twist)])
    # identical metadata still merges fine
    assert len(merge_sharded_entries([a, _sharded([(4, 4)])]).shards) == 2


def test_corrupt_two_rank_manifest_fails_view_build():
    import pytest

    md = SnapshotMetadata(
        version="0.1.0",
        world_size=2,
        manifest={
            "0/app": DictEntry(keys=["x"]),
            "0/app/x": _sharded([(0, 4)]),
            "1/app": DictEntry(keys=["x"]),
            "1/app/x": _twisted([(4, 4)], dtype="int32"),
        },
    )
    with pytest.raises(ValueError, match="disagree"):
        get_manifest_for_rank(md, 0)


def test_sharded_merge_across_ranks_on_restore_view():
    md = SnapshotMetadata(
        version="0.1.0",
        world_size=2,
        manifest={
            "0/app": DictEntry(keys=["x"]),
            "0/app/x": _sharded([(0, 4)]),
            "1/app": DictEntry(keys=["x"]),
            "1/app/x": _sharded([(4, 4)]),
        },
    )
    for rank in (0, 1):
        v = get_manifest_for_rank(md, rank)
        assert len(v["app/x"].shards) == 2


def test_consolidate_keeps_one_replicated_copy():
    m0 = {"app/w": _arr("replicated/app/w", replicated=True), "app/l": _arr("0/app/l")}
    m1 = {"app/w": _arr("replicated/app/w", replicated=True), "app/l": _arr("1/app/l")}
    g = consolidate_manifests([m0, m1])
    assert "0/app/w" in g and "1/app/w" not in g
    assert "0/app/l" in g and "1/app/l" in g


def test_consolidate_respects_writer_rank_for_batched_replicated():
    # rank 1 wrote the replicated entry (possibly re-pointed at its slab);
    # rank 0 dropped its copy — the global manifest must carry rank 1's
    m0 = {"app": DictEntry(keys=["w"])}
    m1 = {
        "app": DictEntry(keys=["w"]),
        "app/w": ArrayEntry(
            "1/batched.0", "buffer_protocol", "float32", [4], True, [0, 16]
        ),
    }
    g = consolidate_manifests([m0, m1])
    assert g["1/app/w"].location == "1/batched.0"
    md = SnapshotMetadata(version="0.1.0", world_size=2, manifest=g)
    v0 = get_manifest_for_rank(md, 0)
    assert v0["app/w"].location == "1/batched.0"
