"""Payload transport (transport/): engine selection matrix, the
uint32-lane pack/chunk codec, KV-vs-collective bitwise equivalence,
the kv_publish_blob orphan-sweep regression, the continuous
replication device-move leg, publish/ subscriber chunk fan-in, and a
4-process jax.distributed acceptance run (fan-out restore bytes moving
over real collectives with the KV demoted to control plane)."""

import json
import os
import socket
import subprocess
import sys
import textwrap
import zlib

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict, knobs, obs
from torchsnapshot_tpu import transport as transport_mod
from torchsnapshot_tpu.coordination import LocalCoordinator
from torchsnapshot_tpu.scheduler import sync_execute_buffer_writes
from torchsnapshot_tpu.storage.memory import (
    _NAMESPACES,
    MemoryStoragePlugin,
    reset_namespace,
)
from torchsnapshot_tpu.transport import (
    TransportUnavailable,
    current_engine,
    resolve_transport,
)
from torchsnapshot_tpu.transport import collective as collective_mod
from torchsnapshot_tpu.transport.collective import (
    _LANE,
    _pack_parts,
    _plan_parts,
    _unpack_parts,
)
from torchsnapshot_tpu.transport.kv import KVTransport

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _counters():
    return obs.metrics_snapshot()["counters"]


def _counter(name):
    return _counters().get(name, 0)


# ======================================================== codec helpers


@pytest.mark.parametrize(
    "nbytes", [0, 1, 3, 127, 128, 129, 4096, 8191, 100_001]
)
@pytest.mark.parametrize("part_bytes", [200, 4096, 8 << 20])
def test_pack_unpack_bitwise_roundtrip(nbytes, part_bytes):
    """The uint32-lane codec is bitwise lossless for every payload
    size × chunking combination, including empty and odd tails."""
    rng = np.random.default_rng(nbytes * 7919 + part_bytes)
    data = rng.integers(0, 256, size=nbytes, dtype=np.uint8).tobytes()
    nparts, ppad = _plan_parts(nbytes, part_bytes)
    assert nparts >= 1 and ppad % _LANE == 0 and ppad >= _LANE
    assert nparts * ppad >= nbytes
    parts = _pack_parts(memoryview(data), nparts, ppad)
    assert len(parts) == nparts
    # every part is lane-identical: same uint32 word count everywhere,
    # the broadcast shape contract
    assert all(p.dtype == np.uint32 and p.shape == (ppad // 4,) for p in parts)
    assert _unpack_parts(parts, nbytes) == data


def test_plan_parts_chunks_large_payloads():
    nparts, ppad = _plan_parts(10 << 20, 1 << 20)
    assert nparts == 10 and nparts * ppad >= 10 << 20
    # floor: part size never goes below one lane
    nparts_tiny, ppad_tiny = _plan_parts(1024, 1)
    assert ppad_tiny >= _LANE and nparts_tiny * ppad_tiny >= 1024


# ===================================================== engine selection


def test_engine_selection_kv_knob_short_circuits():
    with knobs.override_transport("kv"):
        t = resolve_transport(LocalCoordinator())
    assert t.engine == "kv" and current_engine() == "kv"
    t.close()


def test_engine_selection_auto_single_process_is_quiet_kv():
    """auto + no multi-process jax session → KV, and the miss is NOT a
    degrade: transport.fallbacks must not advance for a world that
    never could have used collectives."""
    before = _counter("transport.fallbacks")
    with knobs.override_transport("auto"):
        t = resolve_transport(LocalCoordinator())
    assert t.engine == "kv"
    assert _counter("transport.fallbacks") == before
    t.close()


def test_engine_selection_forced_collective_local_mode():
    with knobs.override_transport("collective"):
        t = resolve_transport(LocalCoordinator())
    try:
        assert t.engine == "collective" and t.mode == "local"
        assert current_engine() == "collective"
    finally:
        t.close()


def test_forced_collective_broken_runtime_degrades_counted(monkeypatch):
    """An explicit TRANSPORT=collective the runtime cannot honor lands
    on KV with transport.fallbacks advancing — observable, never
    wedged."""

    def boom():
        raise RuntimeError("no devices in this fixture")

    monkeypatch.setattr(collective_mod, "_devices", boom)
    before = _counter("transport.fallbacks")
    with knobs.override_transport("collective"):
        t = resolve_transport(LocalCoordinator())
    assert t.engine == "kv"
    assert _counter("transport.fallbacks") == before + 1
    t.close()


# ==================================== publish/fetch engine equivalence


def _payloads():
    rng = np.random.default_rng(42)
    return {
        "a": rng.integers(0, 256, size=70_001, dtype=np.uint8).tobytes(),
        "b": b"x" * _LANE,
        "c": rng.integers(0, 256, size=13, dtype=np.uint8).tobytes(),
    }


def test_collective_local_publish_fetch_bitwise_and_cleanup():
    coord = LocalCoordinator()
    with knobs.override_transport("collective"):
        t = resolve_transport(coord)
    assert t.engine == "collective"
    ops0 = _counter("transport.collective_ops")
    try:
        with knobs.override_transport_part_bytes(16384):
            for name, data in _payloads().items():
                nparts = t.publish(f"x/{name}", data)
                assert nparts >= 1
                assert t.try_fetch(f"x/{name}") == data
        assert _counter("transport.collective_ops") > ops0
        for name, data in _payloads().items():
            t.cleanup(f"x/{name}", 8)
            # announce gone → a fresh probe sees nothing (not an error)
            assert t.try_fetch(f"x/{name}") is None
        assert collective_mod._REGISTRY == {}
    finally:
        t.close()


def test_kv_transport_publish_fetch_bitwise_and_metered():
    coord = LocalCoordinator()
    t = KVTransport(coord)
    ops0, bytes0 = _counter("transport.kv_ops"), _counter("transport.kv_bytes")
    for name, data in _payloads().items():
        t.publish(f"x/{name}", data)
        assert t.try_fetch(f"x/{name}") == data
    assert _counter("transport.kv_ops") >= ops0 + 3
    assert _counter("transport.kv_bytes") >= bytes0 + sum(
        len(d) for d in _payloads().values()
    )
    t.cleanup("x/a", 64)
    assert t.try_fetch("x/a") is None
    t.close()


def test_collective_registry_miss_is_unavailable_not_error():
    """Announce present but payload published by ANOTHER process (no
    registry entry here) → TransportUnavailable, so the caller's KV
    ladder takes over; never a silent None, never a crash."""
    coord = LocalCoordinator()
    with knobs.override_transport("collective"):
        t = resolve_transport(coord)
    try:
        t.publish("x/m", b"payload-bytes")
        with collective_mod._registry_lock:
            collective_mod._REGISTRY.pop("x/m")
        with pytest.raises(TransportUnavailable):
            t.try_fetch("x/m")
    finally:
        t.close()


def test_collective_fetch_rejects_digest_mismatch():
    coord = LocalCoordinator()
    with knobs.override_transport("collective"):
        t = resolve_transport(coord)
    try:
        t.publish("x/d", b"trust-but-verify")
        meta = coord.kv_try_get("x/d/xmeta")
        nparts, ppad, n, _crc, adler = meta.split(":")
        coord.kv_set("x/d/xmeta", f"{nparts}:{ppad}:{n}:12345:{adler}")
        with pytest.raises(ValueError):
            t.try_fetch("x/d")
    finally:
        t.close()


# ==================================== kv blob orphan-sweep regression


def test_kv_publish_blob_reclaims_orphans_on_prefix_reuse():
    """Regression: a publisher killed between the cleanup path's
    meta delete and its part deletes used to strand {prefix}/p{i}
    keys forever.  The next publish under the same prefix must
    overwrite the live indices AND tail-sweep every contiguous
    leftover, with transport.swept_parts advancing."""
    coord = LocalCoordinator()
    big = b"A" * 4000
    coord.kv_publish_blob("fan/reuse", big, part_bytes=1000)  # p0..p3
    # simulate the killed publisher: meta deleted, parts stranded
    coord.kv_try_delete("fan/reuse/meta")
    assert coord.kv_try_get("fan/reuse/p3") is not None
    swept0 = _counter("transport.swept_parts")
    small = b"B" * 1500
    coord.kv_publish_blob("fan/reuse", small, part_bytes=1000)  # p0..p1
    assert _counter("transport.swept_parts") == swept0 + 2  # p2, p3
    for i in (2, 3):
        assert coord.kv_try_get(f"fan/reuse/p{i}") is None
    assert coord.kv_try_fetch_blob("fan/reuse", timeout_s=1.0) == small


def test_kv_sweep_blob_full_sweep_deletes_meta_first():
    coord = LocalCoordinator()
    coord.kv_publish_blob("fan/gone", b"C" * 2500, part_bytes=1000)
    swept = coord.kv_sweep_blob("fan/gone")
    assert swept == 3
    assert coord.kv_try_get("fan/gone/meta") is None
    assert coord.kv_try_fetch_blob("fan/gone", timeout_s=0.2) is None


# ============================== continuous replication device-move leg


def _staged_items(k=3, n=50_000):
    rng = np.random.default_rng(7)
    return [
        (f"replica/part{i}", rng.integers(0, 256, n, dtype=np.uint8).tobytes())
        for i in range(k)
    ]


def test_buffer_writes_device_move_preserves_bytes():
    """The peer-replication fabric leg: payloads routed through
    Transport.device_move land bitwise identical, with
    transport.device_moves advancing."""
    reset_namespace("xdev")
    storage = MemoryStoragePlugin("xdev")
    with knobs.override_transport("collective"):
        t = resolve_transport(LocalCoordinator())
    assert t.engine == "collective"
    moves0 = _counter("transport.device_moves")
    items = _staged_items()
    try:
        written = sync_execute_buffer_writes(
            items,
            storage,
            memory_budget_bytes=1 << 20,
            counter_name="continuous.replicated_bytes",
            transport=t,
        )
    finally:
        t.close()
    assert written == sum(len(b) for _, b in items)
    assert _counter("transport.device_moves") >= moves0 + len(items)
    for path, buf in items:
        assert bytes(_NAMESPACES["xdev"][path]) == buf


def test_buffer_writes_raising_transport_degrades_to_staged_bytes():
    """A fabric-leg failure costs speed, never the replica: the
    original staged bytes are written and transport.fallbacks
    advances once per degraded payload."""

    class _Broken(transport_mod.Transport):
        engine = "collective"

        def device_move(self, buf):
            raise RuntimeError("fabric down")

    reset_namespace("xdeg")
    storage = MemoryStoragePlugin("xdeg")
    items = _staged_items(k=2)
    fb0 = _counter("transport.fallbacks")
    written = sync_execute_buffer_writes(
        items,
        storage,
        memory_budget_bytes=1 << 20,
        counter_name="continuous.replicated_bytes",
        transport=_Broken(),
    )
    assert written == sum(len(b) for _, b in items)
    assert _counter("transport.fallbacks") == fb0 + 2
    for path, buf in items:
        assert bytes(_NAMESPACES["xdeg"][path]) == buf


# ======================================= publish/ subscriber chunk fan-in


def test_subscriber_fanin_over_collective_registry(tmp_path):
    """Two co-resident subscribers with a forced-collective transport:
    the first durable fetch publishes each chunk into the device
    registry, the second subscriber's poll consumes from it, and both
    land bitwise on the published weights."""
    from torchsnapshot_tpu.publish import Publisher, Subscriber

    root = str(tmp_path / "pub")
    n = 4096
    w = np.arange(n, dtype=np.float32)
    pub = Publisher(root, chunk_size_bytes=1024)
    coord = LocalCoordinator()
    s1 = {"app": StateDict(w=np.zeros(n, np.float32))}
    s2 = {"app": StateDict(w=np.zeros(n, np.float32))}
    sub1 = Subscriber(root, s1, coordinator=coord, sub_id="sub-one")
    sub2 = Subscriber(root, s2, coordinator=coord, sub_id="sub-two")
    try:
        with knobs.override_transport("collective"):
            pub.publish_state({"app": StateDict(w=w.copy())}, 1)
            ops0 = _counter("transport.collective_ops")
            assert sub1.poll_once() == 1  # durable fetch + fan-in publish
            assert _counter("transport.collective_ops") > ops0
            assert sub2.poll_once() == 1  # consumes from the registry
        assert np.array_equal(s1["app"]["w"], w)
        assert np.array_equal(s2["app"]["w"], w)
    finally:
        sub1.close()
        sub2.close()
        pub.close()
    # content-keyed registry entries are swept at close, not accreted
    assert collective_mod._REGISTRY == {}


# =========================== 4-process jax.distributed acceptance run


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_XACC_WORKER = """
import json, os, sys, zlib
sys.path.insert(0, {repo!r})
import numpy as np

rank = int(sys.argv[1])
world = int(sys.argv[2])

import jax
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address="localhost:" + str({port}),
    num_processes=world,
    process_id=rank,
)

from torchsnapshot_tpu import Snapshot, StateDict, knobs, obs
from torchsnapshot_tpu.coordination import FileCoordinator
from torchsnapshot_tpu.transport import current_engine

coord = FileCoordinator({kv_dir!r}, rank, world)
snap_dir = {snap_dir!r}
K, N = 3, 100_000

state = {{"m": StateDict(**{{
    f"w{{i}}": np.arange(N, dtype=np.float32) * (i + 1) for i in range(K)
}})}}
Snapshot.take(snap_dir, state, replicated=["**"], coordinator=coord)

dest = {{"m": StateDict(**{{
    f"w{{i}}": np.zeros(N, np.float32) for i in range(K)
}})}}
Snapshot(snap_dir, coordinator=coord).restore(dest)

crcs = {{
    f"w{{i}}": zlib.crc32(np.ascontiguousarray(dest["m"][f"w{{i}}"]))
    for i in range(K)
}}
c = obs.metrics_snapshot()["counters"]
print("RESULT " + json.dumps({{
    "rank": rank,
    "engine": current_engine(),
    "crcs": crcs,
    "collective_ops": c.get("transport.collective_ops", 0),
    "collective_bytes": c.get("transport.collective_bytes", 0),
    "fallbacks": c.get("transport.fallbacks", 0),
    "fanout_fallbacks": c.get("topology.fanout_fallbacks", 0),
    "durable": c.get("topology.fanout_durable_reads", 0),
}}))
"""


def test_multiprocess_collective_fanout_restore_acceptance(tmp_path):
    """THE tentpole acceptance test: 4 jax.distributed processes
    (gloo), topology 2 slices × 2 ranks, TRANSPORT=collective — the
    fan-out restore moves every redistribution byte over real
    broadcast collectives (engine=collective on all ranks, zero
    fallbacks), durable GETs stay K per slice (only the designated
    readers touch durable storage), every rank restores bitwise the
    ground-truth bytes, and the KV holds no fan/transport keys after
    the fleet exits."""
    port = _free_port()
    kv_dir = os.path.join(str(tmp_path), "kv")
    snap_dir = os.path.join(str(tmp_path), "snap")
    script = os.path.join(str(tmp_path), "xacc_worker.py")
    with open(script, "w") as f:
        f.write(
            textwrap.dedent(
                _XACC_WORKER.format(
                    repo=_REPO, port=port, kv_dir=kv_dir, snap_dir=snap_dir
                )
            )
        )
    K, N = 3, 100_000
    truth = {
        f"w{i}": zlib.crc32(
            np.ascontiguousarray(np.arange(N, dtype=np.float32) * (i + 1))
        )
        for i in range(K)
    }
    env = {
        **os.environ,
        "PYTHONPATH": "",
        "JAX_PLATFORMS": "cpu",
        # one device per process: the collective session spans
        # processes, not a forced virtual mesh
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "TORCHSNAPSHOT_TPU_TOPOLOGY": "0,0,1,1",
        "TORCHSNAPSHOT_TPU_TRANSPORT": "collective",
        "TORCHSNAPSHOT_TPU_DISABLE_BATCHING": "1",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, script, str(r), "4"],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for r in range(4)
    ]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=240)[0].decode())
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise AssertionError("transport acceptance fleet wedged")

    slice_of = (0, 0, 1, 1)
    per_slice_gets = {0: 0, 1: 0}
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        res = None
        for line in out.splitlines():
            if line.startswith("RESULT "):
                res = json.loads(line[len("RESULT "):])
        assert res is not None, f"no RESULT from rank {r}:\n{out}"
        assert res["engine"] == "collective", out
        assert {k: int(v) for k, v in res["crcs"].items()} == truth, (
            f"rank {r} restored different bytes"
        )
        # one collective broadcast per shared object, payload bytes
        # off the KV
        assert res["collective_ops"] == K, out
        assert res["collective_bytes"] >= K * N * 4, out
        assert res["fallbacks"] == 0 and res["fanout_fallbacks"] == 0, out
        per_slice_gets[slice_of[r]] += res["durable"]
    # collectives changed WHERE bytes travel, not the durable contract:
    # still O(objects) per slice, NOT O(objects × ranks)
    assert per_slice_gets == {0: K, 1: K}
    # control-plane hygiene, checked after every worker has exited
    # (mid-run observation races on gate keys are expected)
    leftover = [
        name
        for name in os.listdir(kv_dir)
        if "%2Ffan%2F" in name or "%2Fxfan%2F" in name
    ]
    assert leftover == [], leftover
