"""Tier-1 wiring for tools/check_instrumentation.py: the repo's
Snapshot/SnapshotManager public methods must all carry a
log_event/span bracket, and the checker itself must actually detect
violations (a checker that can't fail is no check)."""

import importlib.util
import os
import textwrap

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_instrumentation",
        os.path.join(_REPO_ROOT, "tools", "check_instrumentation.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_public_methods_are_instrumented():
    checker = _load_checker()
    assert checker.check_repo(_REPO_ROOT) == []


def test_checker_flags_uninstrumented_method():
    checker = _load_checker()
    src = textwrap.dedent(
        """
        class Snapshot:
            def covered(self):
                with log_event(Event("covered")):
                    return 1

            def covered_by_span(self):
                with obs.span("x"):
                    return 2

            async def covered_async(self):
                async with thing:
                    with span("y", bytes=3):
                        return 3

            def naked(self):
                return 4

            def _private_is_fine(self):
                return 5
        """
    )
    violations = checker.check_source(src, {"Snapshot": set()}, "x.py")
    assert len(violations) == 1
    assert "Snapshot.naked" in violations[0]


def test_checker_honors_allowlist():
    checker = _load_checker()
    src = "class Snapshot:\n    def naked(self):\n        return 1\n"
    assert checker.check_source(src, {"Snapshot": {"naked"}}, "x.py") == []


def test_checker_covers_module_level_functions():
    """GC-path coverage: delete_snapshot (module-level) is required to
    carry a bracket, and the checker detects a naked one."""
    checker = _load_checker()
    import os

    assert "delete_snapshot" in checker.MODULE_FUNCTIONS[
        os.path.join("torchsnapshot_tpu", "manager.py")
    ]
    src = textwrap.dedent(
        """
        def delete_snapshot(path):
            return path

        def helper_is_fine(path):
            return path
        """
    )
    violations = checker.check_source(
        src, {}, "x.py", module_functions={"delete_snapshot"}
    )
    assert len(violations) == 1 and "delete_snapshot" in violations[0]
    src_ok = textwrap.dedent(
        """
        def delete_snapshot(path):
            with log_event(Event("delete_snapshot")):
                return path
        """
    )
    assert (
        checker.check_source(
            src_ok, {}, "x.py", module_functions={"delete_snapshot"}
        )
        == []
    )


def test_checker_main_exit_codes(capsys):
    checker = _load_checker()
    assert checker.main([_REPO_ROOT]) == 0
    assert "OK" in capsys.readouterr().out
