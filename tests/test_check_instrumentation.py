"""Tier-1 wiring for tools/check_instrumentation.py: the repo's
Snapshot/SnapshotManager public methods must all carry a
log_event/span bracket, and the checker itself must actually detect
violations (a checker that can't fail is no check)."""

import importlib.util
import os
import textwrap

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_instrumentation",
        os.path.join(_REPO_ROOT, "tools", "check_instrumentation.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_public_methods_are_instrumented():
    checker = _load_checker()
    assert checker.check_repo(_REPO_ROOT) == []


def test_checker_flags_uninstrumented_method():
    checker = _load_checker()
    src = textwrap.dedent(
        """
        class Snapshot:
            def covered(self):
                with log_event(Event("covered")):
                    return 1

            def covered_by_span(self):
                with obs.span("x"):
                    return 2

            async def covered_async(self):
                async with thing:
                    with span("y", bytes=3):
                        return 3

            def naked(self):
                return 4

            def _private_is_fine(self):
                return 5
        """
    )
    violations = checker.check_source(src, {"Snapshot": set()}, "x.py")
    assert len(violations) == 1
    assert "Snapshot.naked" in violations[0]


def test_checker_honors_allowlist():
    checker = _load_checker()
    src = "class Snapshot:\n    def naked(self):\n        return 1\n"
    assert checker.check_source(src, {"Snapshot": {"naked"}}, "x.py") == []


def test_checker_main_exit_codes(capsys):
    checker = _load_checker()
    assert checker.main([_REPO_ROOT]) == 0
    assert "OK" in capsys.readouterr().out
