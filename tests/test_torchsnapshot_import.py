"""Import checkpoints written by the REAL reference library.

The strongest possible fixture: when facebookresearch/torchsnapshot and
torch are importable, the reference itself writes the snapshot and our
reader must reproduce every leaf bit-exactly.  A synthetic-manifest
suite (no torch, no reference) pins the format rules — %-escaped keys,
list reconstruction, primitive codecs, sharded-union merging — so the
reader stays covered everywhere.
"""

import base64
import json
import os
import struct
import sys

import numpy as np
import pytest

from torchsnapshot_tpu.tricks.torchsnapshot_reader import read_torchsnapshot

from reference_oracle import REFERENCE as _REFERENCE, \
    reference_available as _reference_available


@pytest.fixture()
def reference_snapshot(tmp_path):
    if not _reference_available():
        pytest.skip("reference library / torch not available")
    sys.path.insert(0, _REFERENCE)
    try:
        import torch
        from torchsnapshot import Snapshot as RefSnapshot, StateDict
        from torchsnapshot.knobs import override_max_chunk_size_bytes

        torch.manual_seed(7)
        state = StateDict(
            w=torch.arange(8, dtype=torch.float32),
            b=torch.randn(4, 4).to(torch.bfloat16),
            half=torch.randn(3).to(torch.float16),
            flags=torch.tensor([True, False, True]),
            i8=torch.arange(-3, 3, dtype=torch.int8),
            n=3,
            name="hi",
            pi=3.25,
            blob=b"\x00\x01\xff",
            yes=True,
            nested={"a/b": 1, "items": [10, "x", {"deep": 2}]},
        )
        big = torch.randn(300, 100)
        with override_max_chunk_size_bytes(32_000):  # force chunking
            RefSnapshot.take(
                str(tmp_path / "snap"), {"app": state, "big": StateDict(t=big)}
            )
        yield str(tmp_path / "snap"), state, big
    finally:
        sys.path.remove(_REFERENCE)


def test_reads_real_reference_snapshot(reference_snapshot):
    path, state, big = reference_snapshot
    import torch

    got = read_torchsnapshot(path)
    app = got["app"]
    for key in ("w", "b", "half", "flags", "i8"):
        want = state[key]
        have = app[key]
        assert tuple(have.shape) == tuple(want.shape)
        # bit-exact: compare raw little-endian bytes via numpy views
        want_np = want.view(torch.int16).numpy() if want.dtype == torch.bfloat16 else want.numpy()
        have_cmp = have.view(np.int16) if key == "b" else have
        np.testing.assert_array_equal(np.asarray(have_cmp), want_np)
    assert app["n"] == 3 and app["name"] == "hi" and app["yes"] is True
    assert app["pi"] == 3.25
    assert app["blob"] == b"\x00\x01\xff"
    assert app["nested"]["a/b"] == 1
    assert app["nested"]["items"][0] == 10
    assert app["nested"]["items"][1] == "x"
    assert app["nested"]["items"][2]["deep"] == 2
    # chunked tensor reassembled bit-exactly
    np.testing.assert_array_equal(got["big"]["t"], big.numpy())


def test_imported_state_restores_into_jax(reference_snapshot):
    path, state, _ = reference_snapshot
    import jax.numpy as jnp

    got = read_torchsnapshot(path)
    arr = jnp.asarray(got["app"]["w"])
    np.testing.assert_array_equal(np.asarray(arr), np.arange(8, dtype=np.float32))
    bf = jnp.asarray(got["app"]["b"])
    assert bf.dtype == jnp.bfloat16


def test_reads_real_dtensor_snapshot(tmp_path):
    """A DTensor checkpoint written by the actual reference through
    torch.distributed (gloo, world=1) imports as the dense array."""
    if not _reference_available():
        pytest.skip("reference library / torch not available")
    sys.path.insert(0, _REFERENCE)
    try:
        import torch
        import torch.distributed as dist

        os.environ.setdefault("MASTER_ADDR", "127.0.0.1")
        os.environ.setdefault("MASTER_PORT", "29519")
        dist.init_process_group("gloo", rank=0, world_size=1)
        try:
            from torch.distributed.device_mesh import init_device_mesh
            from torch.distributed.tensor import Shard, distribute_tensor

            from torchsnapshot import Snapshot as RefSnapshot, StateDict

            mesh = init_device_mesh("cpu", (1,))
            big = torch.arange(64, dtype=torch.float32).reshape(8, 8)
            dt = distribute_tensor(big, mesh, [Shard(0)])
            RefSnapshot.take(str(tmp_path / "snap"), {"app": StateDict(dt=dt)})
        finally:
            dist.destroy_process_group()
    finally:
        sys.path.remove(_REFERENCE)

    got = read_torchsnapshot(str(tmp_path / "snap"))
    np.testing.assert_array_equal(
        got["app"]["dt"], np.arange(64, dtype=np.float32).reshape(8, 8)
    )


# ------------------------- synthetic-manifest suite (runs everywhere)


def _write_snapshot(tmp_path, manifest, blobs):
    snap = tmp_path / "snap"
    for loc, data in blobs.items():
        full = snap / loc
        full.parent.mkdir(parents=True, exist_ok=True)
        full.write_bytes(data)
    snap.mkdir(parents=True, exist_ok=True)
    (snap / ".snapshot_metadata").write_text(
        json.dumps({"version": "0.1.0", "world_size": 2, "manifest": manifest})
    )
    return str(snap)


def _tensor_entry(loc, dtype, shape, byte_range=None):
    e = {
        "type": "Tensor",
        "location": loc,
        "serializer": "buffer_protocol",
        "dtype": dtype,
        "shape": list(shape),
        "replicated": False,
    }
    if byte_range is not None:
        e["byte_range"] = list(byte_range)
    return e


def test_synthetic_primitives_and_escaped_keys(tmp_path):
    manifest = {
        "0/app": {"type": "dict", "keys": ["a/b", "f", "raw"]},
        "0/app/a%2Fb": {
            "type": "int", "serialized_value": "42",
            "replicated": False, "readable": None,
        },
        "0/app/f": {
            "type": "float",
            "serialized_value": base64.b64encode(struct.pack("d", 1.5)).decode(),
            "replicated": False, "readable": None,
        },
        "0/app/raw": {
            "type": "bytes",
            "serialized_value": base64.b64encode(b"xyz").decode(),
            "replicated": False, "readable": None,
        },
    }
    got = read_torchsnapshot(_write_snapshot(tmp_path, manifest, {}))
    assert got == {"app": {"a/b": 42, "f": 1.5, "raw": b"xyz"}}


def test_synthetic_byte_range_and_list_order(tmp_path):
    payload = np.arange(12, dtype=np.float32).tobytes()
    manifest = {
        "0/app": {"type": "dict", "keys": ["xs"]},
        "0/app/xs": {"type": "list"},
        # deliberately exercise >9 indices: reconstruction must be by
        # integer index, not lexicographic path order
        **{
            f"0/app/xs/{i}": _tensor_entry(
                "0/blob", "torch.float32", (1,), (4 * i, 4 * i + 4)
            )
            for i in range(11)
        },
    }
    got = read_torchsnapshot(
        _write_snapshot(tmp_path, manifest, {"0/blob": payload})
    )
    xs = got["app"]["xs"]
    assert len(xs) == 11
    for i in range(11):
        np.testing.assert_array_equal(xs[i], np.asarray([i], np.float32))


def test_synthetic_sharded_union_across_ranks(tmp_path):
    # rank 0's manifest lists rows 0-1, rank 1's lists rows 2-3; the
    # rank-0 view must assemble the FULL tensor from the union
    full = np.arange(4 * 3, dtype=np.float32).reshape(4, 3)
    blobs = {
        "sharded/top": full[:2].tobytes(),
        "sharded/bot": full[2:].tobytes(),
    }

    def shard(loc, row0):
        return {
            "offsets": [row0, 0],
            "sizes": [2, 3],
            "tensor": _tensor_entry(loc, "torch.float32", (2, 3)),
        }

    manifest = {
        "0/app": {"type": "dict", "keys": ["w"]},
        "1/app": {"type": "dict", "keys": ["w"]},
        # real Sharded/DTensor entries carry NO top-level shape/dtype
        # (manifest.py:118-168): both derive from the shard union
        "0/app/w": {
            "type": "ShardedTensor", "shards": [shard("sharded/top", 0)],
        },
        "1/app/w": {
            "type": "ShardedTensor", "shards": [shard("sharded/bot", 2)],
        },
    }
    got = read_torchsnapshot(_write_snapshot(tmp_path, manifest, blobs))
    np.testing.assert_array_equal(got["app"]["w"], full)
    # rank 1's view assembles the same full tensor
    got1 = read_torchsnapshot(
        _write_snapshot(tmp_path, manifest, blobs), rank=1
    )
    np.testing.assert_array_equal(got1["app"]["w"], full)


def test_synthetic_replicated_overlay_for_other_ranks(tmp_path):
    # the reference consolidates replicated entries into rank 0's
    # manifest only (partitioner.py:311-355); other ranks' views must
    # overlay them (manifest_ops.py:35-109) — without the overlay a
    # rank-1 import would silently drop every replicated parameter
    payload = np.arange(4, dtype=np.float32).tobytes()
    manifest = {
        "0/app": {"type": "dict", "keys": ["shared", "only0"]},
        "0/app/shared": {
            **_tensor_entry("replicated/app/shared", "torch.float32", (4,)),
            "replicated": True,
        },
        "0/app/only0": {
            "type": "int", "serialized_value": "0",
            "replicated": False, "readable": None,
        },
        "1/app": {"type": "dict", "keys": ["mine"]},
        "1/app/mine": {
            "type": "int", "serialized_value": "1",
            "replicated": False, "readable": None,
        },
    }
    blobs = {"replicated/app/shared": payload}
    got1 = read_torchsnapshot(
        _write_snapshot(tmp_path, manifest, blobs), rank=1
    )
    np.testing.assert_array_equal(
        got1["app"]["shared"], np.arange(4, dtype=np.float32)
    )
    assert got1["app"]["mine"] == 1
    assert "only0" not in got1["app"]  # per-rank state is NOT overlaid


def test_sharded_merge_dedupes_replica_boxes():
    from torchsnapshot_tpu.tricks.torchsnapshot_reader import (
        _merge_sharded_across_ranks,
    )

    shard = {
        "offsets": [0, 0], "sizes": [2, 2],
        "tensor": _tensor_entry("sharded/x", "torch.float32", (2, 2)),
    }
    manifest = {
        "0/app/w": {"type": "DTensor", "shards": [shard]},
        "1/app/w": {"type": "DTensor", "shards": [dict(shard)]},  # replica
    }
    merged = _merge_sharded_across_ranks(manifest)
    # one box, listed once — no double reads, exact coverage accounting
    assert len(merged["app/w"]["shards"]) == 1


def test_synthetic_incomplete_shard_union_raises(tmp_path):
    manifest = {
        "0/app": {"type": "dict", "keys": ["w"]},
        "0/app/w": {
            "type": "ShardedTensor",
            # explicit shape (the ChunkedTensor-style path): rows 2-3
            # missing from the union must raise, not return garbage
            "dtype": "torch.float32", "shape": [4, 3],
            "shards": [{
                "offsets": [0, 0], "sizes": [2, 3],
                "tensor": _tensor_entry("sharded/top", "torch.float32", (2, 3)),
            }],
        },
    }
    blobs = {"sharded/top": np.zeros((2, 3), np.float32).tobytes()}
    with pytest.raises(ValueError, match="covers 6 of 12"):
        read_torchsnapshot(_write_snapshot(tmp_path, manifest, blobs))


def test_synthetic_dtensor_missing_rank_shards_raise(tmp_path):
    # a LOST trailing shard shrinks the union bounding box, which plain
    # coverage math can't see; DTensor's mesh/dim_map implies the shard
    # count, so the loss is detected
    shard = {
        "offsets": [0, 0], "sizes": [2, 3],
        "tensor": _tensor_entry("sharded/top", "torch.float32", (2, 3)),
    }
    manifest = {
        "0/app": {"type": "dict", "keys": ["w"]},
        "0/app/w": {
            "type": "DTensor",
            "shards": [shard],  # rank 1's shard lost
            "mesh": [[0], [1]],  # 2x1 mesh, dim 0 sharded over mesh dim 0
            "dim_map": [[0], [-1]],
        },
    }
    blobs = {"sharded/top": np.zeros((2, 3), np.float32).tobytes()}
    with pytest.raises(ValueError, match="1 distinct boxes .* imply 2"):
        read_torchsnapshot(_write_snapshot(tmp_path, manifest, blobs))


def test_synthetic_empty_shards_raise(tmp_path):
    manifest = {
        "0/app": {"type": "dict", "keys": ["w"]},
        "0/app/w": {"type": "ShardedTensor", "shards": []},
    }
    with pytest.raises(ValueError, match="no shards"):
        read_torchsnapshot(_write_snapshot(tmp_path, manifest, {}))


def test_synthetic_unknown_dtype_raises(tmp_path):
    manifest = {
        "0/app": {"type": "dict", "keys": ["q"]},
        "0/app/q": _tensor_entry("0/q", "torch.qint8", (2,)),
    }
    with pytest.raises(ValueError, match="qint8"):
        read_torchsnapshot(
            _write_snapshot(tmp_path, manifest, {"0/q": b"\x00\x00"})
        )


def test_dict_key_order_preserved(tmp_path):
    # the reference seeds containers via dict.fromkeys(entry.keys) so
    # iteration order survives the round trip; our inflate must too —
    # order-sensitive consumers (OrderedDict optimizer state) depend on it
    payload = np.arange(3, dtype=np.float32).tobytes()
    manifest = {
        "0/app": {"type": "dict", "keys": ["zeta", "alpha", "mid"]},
        "0/app/zeta": _tensor_entry("0/z", "torch.float32", (3,)),
        "0/app/alpha": _tensor_entry("0/a", "torch.float32", (3,)),
        "0/app/mid": _tensor_entry("0/m", "torch.float32", (3,)),
    }
    got = read_torchsnapshot(
        _write_snapshot(
            tmp_path, manifest, {"0/z": payload, "0/a": payload, "0/m": payload}
        )
    )
    assert list(got["app"].keys()) == ["zeta", "alpha", "mid"]


def test_blob_cache_evicts_after_last_consumer():
    # without eviction an import peaks at raw-blobs + assembled arrays
    # (~2x); each blob must drop as its LAST consumer decodes, with
    # refcounts covering replicated shards that share one key
    import asyncio

    from torchsnapshot_tpu.tricks.torchsnapshot_reader import _BlobCache

    reads = []

    class FakeStorage:
        async def read(self, read_io):
            reads.append(read_io.path)
            read_io.buf = b"\x01\x02"

    shared = {"location": "blob/shared"}
    solo = {"location": "blob/solo"}
    cache = _BlobCache(FakeStorage())
    # "shared" referenced by two consuming leaves, "solo" by one
    cache.prefetch([shared, shared, solo])
    assert sorted(reads) == ["blob/shared", "blob/solo"]  # fetched once each

    assert cache.get(solo) == b"\x01\x02"
    assert ("blob/solo", None) not in cache._blobs  # evicted immediately
    assert cache.get(shared) == b"\x01\x02"
    assert ("blob/shared", None) in cache._blobs  # one consumer left
    assert cache.get(shared) == b"\x01\x02"
    assert not cache._blobs  # last consumer: cache fully drained
    assert sorted(reads) == ["blob/shared", "blob/solo"]  # no refetches


def test_reads_real_quantized_snapshot(tmp_path):
    # quantized embeddings are common in migrating torchrec checkpoints;
    # the reference stores them via custom binary serializers
    # (serialization.py:278-477) — import dequantizes to float32 with a
    # warning instead of refusing
    if not _reference_available():
        pytest.skip("reference library / torch not available")
    sys.path.insert(0, _REFERENCE)
    try:
        import torch
        from torchsnapshot import Snapshot as RefSnapshot, StateDict
        from torchsnapshot.knobs import override_max_chunk_size_bytes

        torch.manual_seed(3)
        per_tensor = torch.quantize_per_tensor(
            torch.randn(6, 4), scale=0.07, zero_point=3, dtype=torch.qint8
        )
        per_channel = torch.quantize_per_channel(
            torch.randn(5, 3),
            scales=torch.tensor([0.1, 0.02, 0.5]),
            zero_points=torch.tensor([0, -2, 7]),
            axis=1,
            dtype=torch.qint8,
        )
        pt32 = torch.quantize_per_tensor(
            torch.randn(4), scale=0.001, zero_point=0, dtype=torch.qint32
        )
        big = torch.quantize_per_tensor(
            torch.randn(64, 16), scale=0.05, zero_point=1, dtype=torch.qint8
        )
        with override_max_chunk_size_bytes(256):  # force chunked quantized
            RefSnapshot.take(
                str(tmp_path / "snap"),
                {
                    "app": StateDict(
                        pt=per_tensor, pc=per_channel, pt32=pt32, big=big
                    )
                },
            )
    finally:
        sys.path.remove(_REFERENCE)
    got = read_torchsnapshot(str(tmp_path / "snap"))
    for name, ref in (
        ("pt", per_tensor),
        ("pc", per_channel),
        ("pt32", pt32),
        ("big", big),  # ChunkedTensor of torch_save quantized pieces
    ):
        arr = got["app"][name]
        assert arr.dtype == np.float32, name
        np.testing.assert_allclose(
            arr, ref.dequantize().numpy(), rtol=0, atol=1e-6, err_msg=name
        )


def test_synthetic_quantized_payloads(tmp_path):
    # format-rule pin that runs with no torch: hand-packed per-tensor and
    # per-channel payloads decode via the documented binary layout
    import struct

    ints = np.array([[-3, 0], [5, 127]], np.int8)
    pt_payload = ints.tobytes() + struct.pack("d", 0.5) + struct.pack("q", 2)
    # per-channel on axis 0: scales [1.0, 0.25], zero points [0, -1]
    pc_ints = np.array([[10, -10], [4, 8]], np.int8)
    pc_payload = (
        struct.pack("q", 0)
        + pc_ints.tobytes()
        + np.array([1.0, 0.25], np.float64).tobytes()
        + np.array([0, -1], np.int64).tobytes()
    )
    manifest = {
        "0/app": {"type": "dict", "keys": ["pt", "pc"]},
        "0/app/pt": {
            "type": "Tensor", "location": "0/pt",
            "serializer": "per_tensor_qtensor", "dtype": "torch.qint8",
            "shape": [2, 2], "replicated": False,
        },
        "0/app/pc": {
            "type": "Tensor", "location": "0/pc",
            "serializer": "per_channel_qtensor", "dtype": "torch.qint8",
            "shape": [2, 2], "replicated": False,
        },
    }
    got = read_torchsnapshot(
        _write_snapshot(
            tmp_path, manifest, {"0/pt": pt_payload, "0/pc": pc_payload}
        )
    )
    np.testing.assert_allclose(
        got["app"]["pt"], (ints.astype(np.float64) - 2) * 0.5
    )
    np.testing.assert_allclose(
        got["app"]["pc"],
        np.array([[10 * 1.0, -10 * 1.0], [(4 + 1) * 0.25, (8 + 1) * 0.25]]),
    )
    # corrupted length is refused with the size math in the message
    with pytest.raises(ValueError, match="implies"):
        read_torchsnapshot(
            _write_snapshot(
                tmp_path / "bad", manifest, {"0/pt": pt_payload + b"x",
                                             "0/pc": pc_payload}
            )
        )
