"""Manifest operations: per-rank views, shard merging, elasticity.

Reference: torchsnapshot/manifest_ops.py:35-287 and manifest_utils.py:25-106.

The global manifest maps ``"<rank>/<logical_path>" → Entry``.  A restoring
rank sees:

- its own per-rank entries (``rank/`` prefix stripped),
- every replicated entry (saved once under the writing rank after
  consolidation — any rank may read it; reference manifest_ops.py:77-79),
- sharded entries **merged across all saved ranks** so the full set of
  shard boxes is visible for overlap-based resharding reads (reference
  _get_merged_sharded_tensor_entries / _get_merged_dtensor_entries,
  manifest_ops.py:111-177),
- if ``rank >= saved world_size`` (world grew): rank 0's replicated+sharded
  view (reference manifest_ops.py:88).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .manifest import (
    Entry,
    Manifest,
    ShardedArrayEntry,
    SnapshotMetadata,
    is_container_entry,
)


def _split_rank_path(key: str) -> Tuple[int, str]:
    rank_str, _, lpath = key.partition("/")
    return int(rank_str), lpath


def _is_replicated_entry(entry: Entry) -> bool:
    return bool(getattr(entry, "replicated", False))


def merge_sharded_entries(entries: List[ShardedArrayEntry]) -> ShardedArrayEntry:
    """Merge per-rank shard lists into one global entry, deduping identical
    boxes (replicas saved by different ranks).

    Per-rank entries for the same logical path must agree on array
    metadata — a divergence means a corrupt or hand-edited manifest, and
    silently adopting ``entries[0]``'s dtype would misinterpret every
    other rank's payload bytes (a dtype swap with equal itemsize would
    even pass extent checks in ``verify.py``).  Raise instead."""
    first = entries[0]
    for e in entries[1:]:
        if (
            e.dtype != first.dtype
            or list(e.shape) != list(first.shape)
            or e.spec != first.spec
            or e.mesh_shape != first.mesh_shape
            or e.mesh_axis_names != first.mesh_axis_names
        ):
            raise ValueError(
                "per-rank sharded entries disagree on array metadata "
                "(dtype/shape/spec/mesh): "
                f"{first.dtype}/{first.shape}/{first.spec}/"
                f"{first.mesh_shape}x{first.mesh_axis_names} vs "
                f"{e.dtype}/{e.shape}/{e.spec}/"
                f"{e.mesh_shape}x{e.mesh_axis_names} — corrupt or "
                "hand-edited manifest?"
            )
    seen = set()
    shards = []
    for e in entries:
        for s in e.shards:
            box = (tuple(s.offsets), tuple(s.sizes))
            if box not in seen:
                seen.add(box)
                shards.append(s)
    shards.sort(key=lambda s: tuple(s.offsets))
    return ShardedArrayEntry(
        dtype=first.dtype,
        shape=first.shape,
        shards=shards,
        mesh_axis_names=first.mesh_axis_names,
        mesh_shape=first.mesh_shape,
        spec=first.spec,
    )


def get_manifest_for_rank(
    metadata: SnapshotMetadata, rank: int
) -> Manifest:
    """Build the logical-path → entry view for a restoring rank
    (reference get_manifest_for_rank, manifest_ops.py:35-109)."""
    per_rank: Dict[int, Manifest] = {}
    sharded: Dict[str, List[ShardedArrayEntry]] = {}
    replicated: Manifest = {}
    for key, entry in metadata.manifest.items():
        r, lpath = _split_rank_path(key)
        per_rank.setdefault(r, {})[lpath] = entry
        if isinstance(entry, ShardedArrayEntry):
            sharded.setdefault(lpath, []).append(entry)
        elif _is_replicated_entry(entry):
            replicated.setdefault(lpath, entry)

    if rank < metadata.world_size:
        view = dict(per_rank.get(rank, {}))
    else:
        # world grew: new ranks adopt rank 0's replicated/sharded view
        view = {
            lpath: e
            for lpath, e in per_rank.get(0, {}).items()
            if is_container_entry(e)
            or _is_replicated_entry(e)
            or isinstance(e, ShardedArrayEntry)
        }

    # overlay replicated entries this rank didn't write itself
    for lpath, entry in replicated.items():
        view.setdefault(lpath, entry)
    # overlay merged sharded entries (full global box set)
    for lpath, entries in sharded.items():
        if lpath in view or rank >= metadata.world_size:
            view[lpath] = merge_sharded_entries(entries)
    return view


def consolidate_manifests(
    per_rank_manifests: List[Dict[str, Entry]],
) -> Manifest:
    """Build the global manifest from gathered per-rank manifests, keeping
    replicated entries only under the lowest rank that has them (reference
    consolidate_replicated_entries, partitioner.py:311-355)."""
    global_manifest: Manifest = {}
    seen_replicated: set = set()
    for r, manifest in enumerate(per_rank_manifests):
        for lpath, entry in manifest.items():
            if _is_replicated_entry(entry):
                if lpath in seen_replicated:
                    continue
                seen_replicated.add(lpath)
            global_manifest[f"{r}/{lpath}"] = entry
    return global_manifest
