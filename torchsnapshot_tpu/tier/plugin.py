"""Composite tiered storage: fast local tier under a durable cloud tier.

``TieredStoragePlugin`` fans every snapshot write across two ordinary
``StoragePlugin``s — a *fast* tier (local SSD path, ``memory://``) and a
*durable* tier (``fs``/``gs://``/``s3://``) — and serves reads fast-first
with transparent fallback:

- **write_through**: the durable write is synchronous and authoritative;
  the fast copy is best-effort (a failed fast write only costs later
  reads a fallback).
- **write_back**: the take is acknowledged when the FAST tier commits;
  a background promoter (promoter.py) copies the data objects to the
  durable tier under the scheduler's memory budget and writes the
  durable ``.snapshot_metadata`` LAST — so an interrupted promotion
  leaves the durable tier with an aborted (metadata-less) snapshot,
  never a committed-but-incomplete one.
- **reads** hit the fast tier first.  When the snapshot's object-digest
  table has been primed (Snapshot primes it from committed metadata on
  restore/read_object/materialize), the first read of each fast object
  verifies the whole object against its recorded (crc32, size); a miss
  or mismatch falls back to a peer replica, then the durable tier,
  REPAIRING the fast copy on the way.  ``.snapshot_metadata`` reads are
  always validated via the metadata self-checksum before being served
  from a non-durable tier.
- **peer replicas**: with ``replica_count > 0``, ``finalize_take``
  mirrors this rank's fast-tier payloads into the next
  ``replica_count`` ranks' fast roots (addressable URLs exchanged over
  the coordination KV, or statically configured), so losing one host's
  fast tier still restores from a peer without touching the durable
  tier.

Construction normally goes through ``url_to_storage_plugin(url,
{"tier": {...}})`` (storage/__init__.py) or a tiered
``SnapshotManager`` (manager.py).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

from .. import knobs, obs
from ..io_types import ReadIO, StoragePlugin, WriteIO, is_mmap_backed
from ..resilience import get_breaker
from .promoter import PromotionGroup, get_promoter

logger = logging.getLogger(__name__)

_METADATA_FNAME = ".snapshot_metadata"  # == snapshot.SNAPSHOT_METADATA_FNAME
# telemetry sidecar (obs/aggregate.py), written by rank 0 AFTER
# finalize_take already handed the data objects to the promoter — it
# must never join group.paths (a post-enqueue mutation would race the
# running data job, and recovery would treat a missing record as a
# missing payload).  The promoter's commit job copies it best-effort
# just before the durable marker instead.
_OBSRECORD_FNAME = ".snapshot_obsrecord"


class _FastTierCorrupt(Exception):
    """Internal: the fast-tier copy failed its integrity check."""


def _as_bytes_view(buf: Any) -> memoryview:
    return memoryview(buf).cast("B")


def _metadata_intact(buf: Any) -> bool:
    """Parse-validate a ``.snapshot_metadata`` payload (its built-in
    self-checksum trailer makes any bit flip fail the load)."""
    from ..manifest import SnapshotMetadata

    try:
        SnapshotMetadata.from_yaml(bytes(_as_bytes_view(buf)).decode())
        return True
    except Exception:  # noqa: BLE001 — any failure means "don't serve it"
        return False


@obs.instrument_storage("tier")
class TieredStoragePlugin(StoragePlugin):
    def __init__(
        self,
        fast: StoragePlugin,
        durable: StoragePlugin,
        fast_url: str,
        durable_url: str,
        policy: Optional[str] = None,
        replica_count: int = 0,
        peer_fast_urls: Optional[List[str]] = None,
        verify_fast_reads: Optional[bool] = None,
    ) -> None:
        self.fast = fast
        self.durable = durable
        self.fast_url = fast_url.rstrip("/")
        self.durable_url = durable_url.rstrip("/")
        self.policy = policy or knobs.get_tier_policy()
        if self.policy not in ("write_back", "write_through"):
            raise ValueError(
                f"tier policy must be write_back|write_through, "
                f"got {self.policy!r}"
            )
        self.replica_count = int(replica_count)
        # all ranks' fast roots, indexed by rank (may include our own).
        # Exchanged lazily at finalize_take on the commit thread while
        # loop-side reads consult it for peer-repair candidates — every
        # touch goes through _peer_lock
        self._peer_lock = threading.Lock()
        self._peer_urls = (
            [u.rstrip("/") for u in peer_fast_urls]
            if peer_fast_urls
            else None
        )
        self._verify_reads = (
            knobs.tier_verify_fast_reads()
            if verify_fast_reads is None
            else bool(verify_fast_reads)
        )
        # fused digests come from whichever tier takes the synchronous
        # authoritative write
        auth = self.fast if self.policy == "write_back" else self.durable
        self.supports_fused_digest = bool(
            getattr(auth, "supports_fused_digest", False)
        )
        # zero-copy serving: reads are fast-first, so the composite can
        # honor want_mmap whenever the fast tier can (the durable
        # fallback may still copy — an s3 GET has no pages to map; a
        # cache-wrapped durable tier maps fine).  Budget exemption is
        # STRICTER: only when BOTH legs are exempt — a composite that
        # can decline into a whole-object cloud GET on its degraded
        # path must keep budgeted, striped reads there (the scheduler
        # keys on mmap_budget_exempt; see io_types.StoragePlugin).
        self.supports_mmap_read = bool(
            getattr(fast, "supports_mmap_read", False)
        )
        self.mmap_budget_exempt = bool(
            getattr(fast, "mmap_budget_exempt", False)
            and getattr(durable, "mmap_budget_exempt", False)
        )
        # location → [crc32, adler32, size] primed from committed
        # metadata (Snapshot._prime_tier_digests); gates read-side
        # verification of fast/peer copies
        self._digests: Dict[str, List[int]] = {}
        self._verified: set = set()
        self._bad_fast: set = set()
        self._group = PromotionGroup(self.fast_url, self.durable_url)
        # fast-tier circuit breaker (resilience/breaker.py): consecutive
        # fast-read failures (corrupt copies, a dying local disk) trip
        # reads straight onto the replica/durable fallback path without
        # paying a doomed local attempt each; a half-open probe after
        # the cooldown re-admits a recovered disk.  Keyed by fast root —
        # every plugin instance over the same local tier shares it.
        self._fast_breaker = get_breaker(f"tier.fast:{self.fast_url}")
        self._replica_target_urls: List[str] = []
        self._peer_plugins: Dict[str, StoragePlugin] = {}
        m = obs.REGISTRY
        self._m_hits = m.counter(obs.TIER_FAST_HITS)
        self._m_misses = m.counter(obs.TIER_FAST_MISSES)
        self._m_repairs = m.counter(obs.TIER_FAST_REPAIRS)
        self._m_corrupt = m.counter(obs.TIER_FAST_CORRUPT)
        self._m_peer_hits = m.counter(obs.TIER_PEER_HITS)
        self._m_replicated = m.counter(obs.BYTES_REPLICATED)

    # ------------------------------------------------------------ helpers

    def prime_digests(self, objects: Dict[str, Any]) -> None:
        """Install the committed metadata's whole-object digest table so
        fast/peer reads can be verified before they are trusted."""
        for loc, rec in (objects or {}).items():
            if isinstance(rec, (list, tuple)) and len(rec) == 3:
                self._digests[loc] = [int(x) for x in rec]

    def _peer_plugin(self, url: str) -> StoragePlugin:
        plugin = self._peer_plugins.get(url)
        if plugin is None:
            from ..storage import url_to_storage_plugin

            # peer fast roots are other hosts' local tiers: never layer
            # the shared-host cache over them (replica probes are
            # already one-hop local-network reads, and caching a peer's
            # copy would shadow later repairs)
            plugin = self._peer_plugins[url] = url_to_storage_plugin(
                url, {"host_cache": False}
            )
        return plugin

    def _digest_ok(self, path: str, buf: Any) -> bool:
        if path == _METADATA_FNAME:
            return _metadata_intact(buf)
        digest = self._digests.get(path)
        if digest is None:
            return True  # nothing recorded: trust the read
        from ..utils.checksums import crc32_fast

        view = _as_bytes_view(buf)
        return view.nbytes == digest[2] and crc32_fast(view) == digest[0]

    def _has_check(self, path: str) -> bool:
        return path == _METADATA_FNAME or (
            self._verify_reads and path in self._digests
        )

    # -------------------------------------------------------------- write

    async def write(self, write_io: WriteIO) -> None:
        if self.policy == "write_through":
            await self.durable.write(write_io)
            try:
                await self.fast.write(
                    WriteIO(
                        path=write_io.path,
                        buf=write_io.buf,
                        durable=write_io.durable,
                    )
                )
                if write_io.path != _OBSRECORD_FNAME:
                    self._group.paths.add(write_io.path)
                self._verified.add(write_io.path)
            except Exception as e:  # noqa: BLE001 — fast tier is a cache
                logger.warning(
                    "fast-tier write of %r failed (%r); reads will fall "
                    "back to the durable tier", write_io.path, e,
                )
                self._bad_fast.add(write_io.path)
            if write_io.durable:
                await self._replicate_metadata(write_io)
            return
        # write_back: fast tier is the ack point
        await self.fast.write(write_io)
        self._verified.add(write_io.path)
        if write_io.durable:
            # commit marker (.snapshot_metadata): replicate to peers so a
            # lost host's step is restorable cloud-free, then let the
            # promoter make it durable strictly AFTER the data objects
            await self._replicate_metadata(write_io)
            group = self._group
            if group.uid is None:
                # direct plugin use without Snapshot's finalize_take
                # hook: promote the data objects anyway, strictly ahead
                # of the commit marker (single-FIFO ordering)
                get_promoter().enqueue_data(group)
            get_promoter().enqueue_commit(group)
        elif write_io.path != _OBSRECORD_FNAME:
            self._group.paths.add(write_io.path)

    async def _replicate_metadata(self, write_io: WriteIO) -> None:
        for url in self._replica_target_urls:
            try:
                await self._peer_plugin(url).write(
                    WriteIO(path=write_io.path, buf=write_io.buf)
                )
                self._m_replicated.inc(_as_bytes_view(write_io.buf).nbytes)
            except Exception as e:  # noqa: BLE001 — replicas best-effort
                logger.warning(
                    "metadata replica to %r failed: %r", url, e
                )

    # --------------------------------------------------------------- read

    async def read(self, read_io: ReadIO) -> None:
        path = read_io.path
        # breaker first: with the fast tier tripped open, reads route
        # straight to the replica/durable fallback (allow() also admits
        # the half-open probe after the cooldown)
        if path not in self._bad_fast and self._fast_breaker.allow():
            try:
                await self._read_fast_checked(read_io)
                self._m_hits.inc()
                self._fast_breaker.record_success()
                return
            except FileNotFoundError:
                # a genuine miss (promotion-only object, evicted step)
                # says nothing about the disk's health: neither success
                # nor failure, but the half-open probe slot must be
                # released or the breaker wedges half-open
                self._fast_breaker.release_probe()
            except _FastTierCorrupt:
                self._fast_breaker.record_failure()
                self._m_corrupt.inc()
                logger.warning(
                    "fast-tier copy of %r failed its integrity check; "
                    "falling back", path,
                )
            except OSError as e:
                # a degraded local disk (EIO, stale mount) is at least
                # as likely as a bit flip — treat it as a miss and fall
                # back rather than aborting a restore the durable tier
                # can still serve
                self._fast_breaker.record_failure()
                logger.warning(
                    "fast-tier read of %r failed (%r); falling back",
                    path, e,
                )
            except BaseException:
                # cancellation (or any unclassified error) propagates —
                # but never with the half-open probe slot still claimed
                self._fast_breaker.release_probe()
                raise
            self._bad_fast.add(path)
        self._m_misses.inc()
        await self._fallback_read(read_io)

    async def _read_fast_checked(self, read_io: ReadIO) -> None:
        path = read_io.path
        if self._has_check(path) and path not in self._verified:
            # verify-through-the-map (the copy-on-verify decision): a
            # want_mmap probe maps the fast copy and the digest pass
            # reads every page through it RIGHT HERE — a file truncated
            # or corrupted before this point fails the checksum inside
            # ordinary exception handling (→ _FastTierCorrupt → peer/
            # durable fallback + repair) instead of a later SIGBUS, and
            # the verified mapping is then served without any heap copy.
            # Defensively copying instead would forfeit zero-copy for
            # every verified read; our own eviction paths unlink (never
            # truncate), so a mapping that passed this check stays valid
            # for its lifetime (see storage.fs.mmap_read).
            probe = ReadIO(path=path, want_mmap=read_io.want_mmap)
            await self.fast.read(probe)
            if not self._digest_ok(path, probe.buf):
                raise _FastTierCorrupt(path)
            self._verified.add(path)
            self._serve(read_io, probe.buf)
            return
        await self.fast.read(read_io)

    @staticmethod
    def _serve(read_io: ReadIO, buf: Any) -> None:
        if read_io.byte_range is None:
            read_io.buf = buf
        else:
            start, end = read_io.byte_range
            view = _as_bytes_view(buf)[start:end]
            # a ranged serve from an mmap-backed probe stays a view —
            # pinning the mapping costs address space, not heap; any
            # other probe buffer is sliced by copy so the served range
            # doesn't pin the whole object
            read_io.buf = view if is_mmap_backed(buf) else bytes(view)

    async def _fallback_read(self, read_io: ReadIO) -> None:
        path = read_io.path
        # peers first: a replica hit keeps the restore off the cloud
        for url in self._peers_for_read(path):
            try:
                probe = ReadIO(path=path)
                await self._peer_plugin(url).read(probe)
                if not self._digest_ok(path, probe.buf):
                    logger.warning(
                        "peer copy of %r at %r failed its integrity "
                        "check; trying next source", path, url,
                    )
                    continue
                self._m_peer_hits.inc()
                await self._repair_fast(path, probe.buf)
                self._serve(read_io, probe.buf)
                return
            except FileNotFoundError:
                continue
            except Exception as e:  # noqa: BLE001 — dead/unreachable
                # peer (stale mount, EIO, network path down): exactly
                # the scenario replicas exist for — try the next source
                logger.warning(
                    "peer read of %r from %r failed (%r); trying next "
                    "source", path, url, e,
                )
                continue
        # durable tier, the source of truth.  Whole-object read when we
        # can repair (byte_range absent, or the object's true extent is
        # known from the digest table); otherwise a plain ranged read.
        digest = self._digests.get(path)
        if read_io.byte_range is None or digest is not None:
            # forward want_mmap: a cache-wrapped durable tier serves the
            # probe as a mapping (zero-copy all the way through the
            # fallback); a cloud plugin ignores the flag and copies
            probe = ReadIO(path=path, want_mmap=read_io.want_mmap)
            await self.durable.read(probe)
            if not self._digest_ok(path, probe.buf):
                raise RuntimeError(
                    f"durable-tier copy of {path!r} does not match its "
                    f"recorded digest — every tier is corrupt"
                )
            await self._repair_fast(path, probe.buf)
            self._serve(read_io, probe.buf)
            return
        await self.durable.read(
            inner := ReadIO(
                path=path,
                byte_range=read_io.byte_range,
                into=read_io.into,
                want_mmap=read_io.want_mmap,
            )
        )
        read_io.buf = inner.buf

    def _peers_for_read(self, path: str) -> List[str]:
        """Peer fast roots to probe, PROBABLE HOLDERS FIRST: locations
        are rank-prefixed (``<rank>/...``), and a rank's payloads live
        on its own fast root plus its ``replica_count`` successor ranks
        — so on a large job the writer-derived candidates usually hit
        before any of the world_size-2 dead probes.  Ordering only (the
        full list remains the tail): peer lists are not guaranteed
        rank-indexed when hand-configured, and topology-aware placement
        (_pick_replica_targets) may have put the replica on a
        different-slice rank instead of a successor — pruning could
        miss a replica that mere ordering cannot."""
        with self._peer_lock:
            peer_urls = self._peer_urls or ()
        peers = [u for u in peer_urls if u != self.fast_url]
        if len(peers) < 2:
            return peers
        rank_str, _, _rest = path.partition("/")
        if not rank_str.isdigit() or not peer_urls:
            return peers
        n = len(peer_urls)
        writer = int(rank_str) % n
        likely = [
            peer_urls[(writer + d) % n]
            for d in range(0, max(1, self.replica_count) + 1)
        ]
        ordered = [u for u in likely if u in peers]
        return ordered + [u for u in peers if u not in ordered]

    async def _repair_fast(self, path: str, buf: Any) -> None:
        if path == _METADATA_FNAME:
            # never re-materialize metadata through the read path: every
            # discovery sweep (manager steps()/_verify) reads metadata,
            # and repairing it would resurrect fast-tier step dirs that
            # fast retention just evicted.  Fast-tier metadata exists
            # exactly where a take (or explicit peer replication) put it.
            return
        try:
            await self.fast.write(
                WriteIO(path=path, buf=bytes(_as_bytes_view(buf)))
            )
            self._bad_fast.discard(path)
            self._verified.add(path)
            self._m_repairs.inc()
        except Exception as e:  # noqa: BLE001 — repair is best-effort
            logger.warning("fast-tier repair of %r failed: %r", path, e)

    # ------------------------------------------------------ other plugin ops

    async def delete(self, path: str) -> None:
        found = False
        for tier_plugin in (self.fast, self.durable):
            try:
                await tier_plugin.delete(path)
                found = True
            except FileNotFoundError:
                pass
        self._verified.discard(path)
        if not found:
            raise FileNotFoundError(path)

    async def stat(self, path: str) -> int:
        try:
            return await self.fast.stat(path)
        except FileNotFoundError:
            return await self.durable.stat(path)

    async def link_from(self, base_url: str, path: str) -> None:
        # dedup links target the durable tier (the base url is a durable
        # snapshot root); the fast tier keeps no copy — reads of a
        # deduped object fall back and repair on first access.  A failed
        # durable link propagates so the scheduler degrades to a normal
        # (tiered) write.
        await self.durable.link_from(base_url, path)
        self._group.linked.add(path)
        self._group.paths.discard(path)

    async def close(self) -> None:
        for plugin in (
            self.fast, self.durable, *self._peer_plugins.values()
        ):
            try:
                await plugin.close()
            except Exception as e:  # noqa: BLE001
                # best-effort teardown must not mask the take/restore
                # outcome, but a close failure (leaked fd, wedged
                # executor) should still be attributable
                obs.swallowed_exception("tier.plugin_close", e)
        self._peer_plugins.clear()

    # ----------------------------------------------------- take lifecycle

    def finalize_take(self, coordinator: Any, uid: str) -> None:
        """Called by Snapshot once this rank's writes all landed in the
        fast tier (before the commit barrier / metadata write):

        1. replicate this rank's fast-tier payloads to its peer ranks'
           fast roots (``replica_count`` > 0), exchanging fast-root URLs
           over the coordination KV when not statically configured;
        2. for write_back, hand the data objects to the background
           promoter and record the coordination handle its cross-rank
           done-handshake needs.

        KV-only (explicit keys) — safe from the async commit thread."""
        with self._peer_lock:
            peers = self._peer_urls
        if self.replica_count > 0:
            if peers is None and coordinator.world_size > 1:
                # the exchange is a collective — strictly outside the lock
                peers = [
                    u.rstrip("/")
                    for u in coordinator.kv_exchange(
                        f"{uid}/tierfast", self.fast_url
                    )
                ]
                with self._peer_lock:
                    self._peer_urls = peers
            if peers and len(peers) > 1:
                rank = (
                    peers.index(self.fast_url)
                    if self.fast_url in peers
                    else coordinator.rank
                )
                # deliberately detected EVERY take, not memoized here:
                # detect_topology's publish-always contract (each rank
                # kv_sets its hint under this op's prefix even on its
                # own cache hits) is what keeps the exchange symmetric
                # when one rank's earlier detection failed — a
                # per-plugin memo would leave that rank waiting on keys
                # cached peers never publish.  The O(world) gather is
                # already memoized inside detect_topology.
                self._replica_target_urls = self._pick_replica_targets(
                    peers, rank, self._detect_topology(coordinator, uid)
                )
                try:
                    self._replicate_group(self._replica_target_urls)
                except Exception as e:  # noqa: BLE001 — best-effort
                    logger.warning(
                        "peer replication for %r failed: %r",
                        self.durable_url, e,
                    )
        if self.policy == "write_back":
            group = self._group
            group.coordinator = coordinator
            group.uid = uid
            get_promoter().enqueue_data(group)

    @staticmethod
    def _detect_topology(coordinator: Any, uid: str) -> Any:
        """Best-effort rank→slice placement for replica target choice.
        Symmetric: every rank with replica_count > 0 reaches this from
        finalize_take, so the one-per-op placement exchange
        (kv_exchange under explicit keys) is background-thread-legal
        and never one-sided.  Any failure degrades to the plain ring
        placement — topology is an optimization, never a take
        blocker."""
        try:
            from ..topology import detect_topology

            return detect_topology(
                coordinator, exchange_prefix=f"{uid}/tiertopo"
            )
        except Exception as e:  # noqa: BLE001 — degrade to ring order
            obs.swallowed_exception("tier.topology_detect", e)
            return None

    def _pick_replica_targets(
        self, peers: List[str], rank: int, topology: Any = None
    ) -> List[str]:
        """The ``replica_count`` peer fast roots this rank mirrors its
        payloads to.  With an explicit topology, candidates are ordered
        by ``Topology.replica_preference`` — DIFFERENT-slice peers
        first, so a whole-slice preemption can never take out both the
        primary and its replica; flat/unknown topologies keep the
        successor-ring placement (byte-identical to the pre-topology
        behavior)."""
        from ..topology import replica_candidate_order

        order = [
            peers[c]
            for c in replica_candidate_order(topology, rank, len(peers))
        ]
        targets: List[str] = []
        for cand in order:
            if len(targets) >= self.replica_count:
                break
            if cand != self.fast_url and cand not in targets:
                targets.append(cand)
        return targets

    def _replicate_group(self, target_urls: List[str]) -> None:
        """Mirror this rank's fast-tier payloads into each target fast
        root (same relative paths — locations are globally unique within
        a snapshot, so peers' own copies can never collide).  Uses the
        scheduler's budgeted concurrent copy engine so multi-GB payloads
        don't serialize object-by-object on the take path."""
        if not target_urls or not self._group.paths:
            return
        from ..scheduler import (
            get_process_memory_budget_bytes,
            sync_execute_copy_reqs,
        )

        with obs.span(
            "tier/replicate", targets=len(target_urls),
            objects=len(self._group.paths),
        ):
            paths = sorted(self._group.paths)
            for url in target_urls:
                sync_execute_copy_reqs(
                    paths,
                    self.fast,
                    self._peer_plugin(url),
                    get_process_memory_budget_bytes(),
                    counter_name=obs.BYTES_REPLICATED,
                )
