"""Tiered checkpoint storage: fast local tier + durable cloud tier.

Public surface:

- ``TierConfig`` — declarative tier settings for a tiered
  ``SnapshotManager`` (fast root, policy, replica placement, fast-tier
  retention).
- ``TieredStoragePlugin`` — the composite plugin (plugin.py).
- ``build_tiered`` — construct a ``TieredStoragePlugin`` from a durable
  plugin + the ``storage_options["tier"]`` dict (used by
  ``url_to_storage_plugin``).
- ``drain_promotions`` / ``get_promoter`` — write-back promotion queue
  control (promoter.py).

See docs/tiering.md for policies, replica placement, and the failure
matrix.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..io_types import StoragePlugin
from .plugin import TieredStoragePlugin  # noqa: F401
from .promoter import drain_promotions, get_promoter  # noqa: F401

__all__ = [
    "TierConfig",
    "TieredStoragePlugin",
    "build_tiered",
    "drain_promotions",
    "get_promoter",
]


@dataclass
class TierConfig:
    """Tier settings for a ``SnapshotManager(root, tier=...)``.

    ``fast_root`` — THIS host's fast-tier root (local SSD path or any
    storage URL); per-step snapshots land under ``{fast_root}/{prefix}N``
    mirroring the durable layout.
    ``policy`` — "write_back" | "write_through"; None = the
    ``TORCHSNAPSHOT_TPU_TIER_POLICY`` knob.
    ``fast_keep_last_n`` — committed steps that keep a fast-tier copy
    (older fast copies are evicted once durably committed); None = the
    ``TORCHSNAPSHOT_TPU_TIER_FAST_KEEP_LAST_N`` knob.
    ``replica_count`` — mirror each rank's fast payloads to this many
    other ranks' fast roots (0 = off).
    ``peer_fast_roots`` — all ranks' fast roots indexed by rank, for
    replica placement and peer-fallback reads; None = exchange over the
    coordination KV at take time (requires peer-addressable URLs).
    ``verify_fast_reads`` — None = the
    ``TORCHSNAPSHOT_TPU_TIER_VERIFY_FAST_READS`` knob.
    """

    fast_root: str
    policy: Optional[str] = None
    fast_keep_last_n: Optional[int] = None
    replica_count: int = 0
    peer_fast_roots: Optional[List[str]] = None
    verify_fast_reads: Optional[bool] = None


def build_tiered(
    durable: StoragePlugin,
    durable_url: str,
    fast_url: str,
    policy: Optional[str] = None,
    replica_count: int = 0,
    peer_fast_urls: Optional[List[str]] = None,
    verify_fast_reads: Optional[bool] = None,
    fast_storage_options: Optional[Dict[str, Any]] = None,
) -> TieredStoragePlugin:
    """Wrap ``durable`` (already constructed for ``durable_url``) with a
    fast tier built from ``fast_url`` — the ``storage_options["tier"]``
    entry point (storage/__init__.py)."""
    from ..storage import url_to_storage_plugin

    # the fast tier IS this host's local copy — routing it through the
    # shared-host object cache would store every byte twice
    fast = url_to_storage_plugin(
        fast_url, dict(fast_storage_options or {}, host_cache=False)
    )
    return TieredStoragePlugin(
        fast=fast,
        durable=durable,
        fast_url=fast_url,
        durable_url=durable_url,
        policy=policy,
        replica_count=replica_count,
        peer_fast_urls=peer_fast_urls,
        verify_fast_reads=verify_fast_reads,
    )
