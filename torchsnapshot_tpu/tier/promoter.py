"""Background write-back promoter: fast-tier payloads → durable tier.

One process-global worker thread drains a FIFO of promotion jobs.  Two
job kinds, always enqueued in this order per take (so FIFO alone gives
the durability invariant):

- ``data`` — copy one rank's fast-tier data objects to the durable tier
  under the scheduler's memory budget (scheduler.sync_execute_copy_reqs),
  then publish this rank's done-key over the coordination KV.
- ``commit`` — rank 0 only: wait for every rank's done-key, then copy
  ``.snapshot_metadata`` (fsync'd, the commit point) and record the
  promotion lag.  Because the metadata copy runs strictly after all
  ranks' data promotions, a crash anywhere in between leaves the durable
  tier WITHOUT metadata — an aborted snapshot by the restore-side
  contract (snapshot.py:645), never a committed-but-incomplete one.

The KV handshake uses only explicit keys (``{uid}/tierdone/{rank}``) —
no collectives, no uid counters — so it is legal from this background
thread under the same rules as the async-commit thread.

Dead peers (resilience/liveness.py): each rank's data job heartbeats
under ``{uid}/tier`` while it copies; the commit job's done-key wait
consults a ``LivenessMonitor`` (with the absence rule on — every live
peer starts stamping promptly here) so a SIGKILLed peer cannot wedge
the handshake for the full timeout.  A dead peer is SKIPPED, counted
(``takeover.promoter_dead_peers``), and the durable marker still lands
— but only after re-proving completeness directly: every location in
the marker's own manifest must be durable-resident (the dead peer may
have died after its copies landed but before its done-key).  A dead
peer whose objects never landed withholds the marker exactly like a
failed job.

``pause()``/``resume()`` exist for tests (deterministic "interrupted
promotion" scenarios); ``drain()`` blocks until the queue is empty and
surfaces any job errors.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import List, Optional, Set, Tuple

from .. import obs

logger = logging.getLogger(__name__)

_METADATA_FNAME = ".snapshot_metadata"  # == snapshot.SNAPSHOT_METADATA_FNAME
_OBSRECORD_FNAME = ".snapshot_obsrecord"  # == obs.aggregate.OBSRECORD_FNAME
_DONE_TIMEOUT_S = 600.0


class PromotionGroup:
    """One take's promotion state on one rank: which fast-tier paths
    need copying (linked/deduped objects are already durable) plus the
    coordination handle for the cross-rank done handshake."""

    def __init__(self, fast_url: str, durable_url: str) -> None:
        self.fast_url = fast_url
        self.durable_url = durable_url
        self.paths: Set[str] = set()
        self.linked: Set[str] = set()
        self.coordinator = None
        self.uid: Optional[str] = None
        self.commit_enqueued_ts: Optional[float] = None
        # set when this rank's data job failed: the commit job fails
        # fast instead of stalling the FIFO for the full done-key
        # timeout (cross-RANK failures still time out — rank 0 cannot
        # see a peer's failure except by its key never appearing)
        self.failed = False
        # crash-recovery re-promotion (SnapshotManager.repromote): paths
        # are the GLOBAL manifest locations, of which this host's fast
        # root may hold only its own rank's share — the data job skips
        # absent objects, and the commit job writes the durable marker
        # only once EVERY location is durable-resident (so a partial
        # multi-host recovery can never fabricate a committed-but-
        # incomplete durable snapshot)
        self.recovery = False
        # pinned commit-marker bytes (continuous/loop.py): when set, the
        # commit job writes THESE bytes as the durable marker instead of
        # copying the fast root's live marker file.  The continuous
        # store's HEAD keeps advancing while its promotion drains in
        # this queue — copying the live file would commit a HEAD whose
        # newer chunks were never part of this group's data job.
        self.marker_payload: Optional[bytes] = None
        # set by the worker when the commit job finished (marker
        # durably written): enqueuers that track durable residency
        # (continuous/loop.py) poll this instead of blocking on drain()
        self.completed = False


class Promoter:
    """Process-global promotion queue (see module docstring)."""

    def __init__(self) -> None:
        self._queue: "queue.Queue[Tuple[str, PromotionGroup]]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._resume = threading.Event()
        self._resume.set()
        self._errors: List[Tuple[str, BaseException]] = []

    # ------------------------------------------------------------ queue

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="tsnp-tier-promoter", daemon=True
                )
                self._thread.start()

    def enqueue_data(self, group: PromotionGroup) -> None:
        with obs.span(
            "tier/enqueue_data", durable=group.durable_url,
            objects=len(group.paths),
        ):
            self._ensure_thread()
            self._queue.put(("data", group))

    def enqueue_commit(self, group: PromotionGroup) -> None:
        with obs.span("tier/enqueue_commit", durable=group.durable_url):
            group.commit_enqueued_ts = time.monotonic()
            self._ensure_thread()
            self._queue.put(("commit", group))

    # ------------------------------------------------------- test hooks

    def pause(self) -> None:
        """Stop processing (jobs keep queueing) — simulates a promotion
        stall/crash window for tests."""
        self._resume.clear()

    def resume(self) -> None:
        self._resume.set()

    def drain(self, raise_on_error: bool = True) -> None:
        """Block until every queued job finished; re-raise the first job
        error (promotion failures are otherwise background warnings)."""
        with obs.span("tier/drain"):
            self._queue.join()
            with self._lock:
                errors, self._errors = self._errors, []
            if errors and raise_on_error:
                raise RuntimeError(
                    f"{len(errors)} promotion job(s) failed"
                ) from errors[0][1]

    # ------------------------------------------------------------ worker

    def _run(self) -> None:
        while True:
            kind, group = self._queue.get()
            try:
                self._resume.wait()
                self._run_job(kind, group)
            except BaseException as e:  # noqa: BLE001 — background thread
                group.failed = True
                logger.exception(
                    "tier promotion %s job for %r failed", kind,
                    group.durable_url,
                )
                # cross-rank abort: rank 0's commit job is (or will be)
                # blocked waiting for every rank's done-key — poison the
                # promotion scope so it withholds the durable commit
                # marker within one poll interval instead of stalling
                # the FIFO for the full done-key timeout
                coord = group.coordinator
                if coord is not None and group.uid is not None:
                    coord.poison(
                        f"{group.uid}/tier",
                        cause=repr(e),
                        site=f"tier.promote.{kind}/rank{coord.rank}",
                    )
                with self._lock:
                    self._errors.append((kind, e))
            finally:
                self._queue.task_done()

    def _run_job(self, kind: str, group: PromotionGroup) -> None:
        from ..scheduler import (
            get_process_memory_budget_bytes,
            sync_execute_copy_reqs,
        )
        from ..storage import url_to_storage_plugin

        from ..resilience.failpoints import failpoint

        src = url_to_storage_plugin(group.fast_url)
        dst = url_to_storage_plugin(group.durable_url)
        try:
            if kind == "data":
                failpoint("tier.promote.data", durable=group.durable_url)
                paths = sorted(group.paths - group.linked)
                if group.recovery:
                    # this host's fast root holds only its own share of
                    # the global manifest — copy what exists locally
                    paths = [p for p in paths if _stat_ok(src, p)]
                coord = group.coordinator
                hb = None
                if coord is not None and group.uid is not None:
                    # heartbeat for the commit job's done-key wait: a
                    # SLOW copy keeps stamping (never declared dead); a
                    # killed process leaves a frozen/absent stamp and is
                    # skipped instead of wedging the handshake
                    from ..resilience.liveness import LivenessSession

                    hb = LivenessSession(
                        coord, f"{group.uid}/tier"
                    ).start()
                try:
                    with obs.span(
                        "tier/promote_data", durable=group.durable_url,
                        objects=len(paths),
                    ):
                        sync_execute_copy_reqs(
                            paths,
                            src,
                            dst,
                            get_process_memory_budget_bytes(),
                        )
                    if coord is not None and group.uid is not None:
                        coord.kv_set(
                            f"{group.uid}/tierdone/{coord.rank}", "ok"
                        )
                finally:
                    # strictly after the done-key: the stamp must stay
                    # live until peers can observe completion
                    if hb is not None:
                        hb.stop()
                return
            # commit: all ranks durable → metadata last
            with obs.span(
                "tier/promote_commit", durable=group.durable_url
            ):
                failpoint("tier.promote.commit", durable=group.durable_url)
                if group.failed:
                    raise RuntimeError(
                        f"durable commit for {group.durable_url!r} "
                        f"withheld: this rank's data promotion failed"
                    )
                coord = group.coordinator
                dead_skipped: List[int] = []
                if coord is not None and group.uid is not None:
                    # abort-aware, death-aware done-key wait: a peer
                    # whose data promotion FAILED poisons {uid}/tier and
                    # this raises SnapshotAbortedError promptly; a peer
                    # that DIED (frozen/never-appearing heartbeat) is
                    # skipped so the handshake can't wedge — the
                    # residency re-proof below decides whether the
                    # marker may still land
                    dead_skipped = self._await_done_keys(coord, group)
                if group.recovery:
                    # no cross-rank handshake in recovery mode: gate the
                    # commit marker on every manifest location actually
                    # being durable-resident instead
                    missing = [
                        p for p in sorted(group.paths)
                        if not _stat_ok(dst, p)
                    ]
                    if missing:
                        raise RuntimeError(
                            f"recovery promotion for {group.durable_url!r}"
                            f" incomplete: {len(missing)} object(s) not "
                            f"yet durable (other hosts' shares?); durable"
                            f" commit marker withheld — e.g. {missing[:3]}"
                        )
                from ..io_types import ReadIO, WriteIO

                if group.marker_payload is not None:
                    # pinned marker (continuous promotion): commit the
                    # HEAD as of enqueue time, not whatever the still-
                    # advancing fast root says now; such groups have no
                    # flight-record sidecar
                    marker = group.marker_payload
                else:
                    # flight-record sidecar first, best-effort: the
                    # durable tier keeps the record-lands-before-marker
                    # ordering, and a missing/unreadable record never
                    # blocks the durable commit (it is telemetry, not
                    # payload — the tier plugin deliberately keeps it
                    # out of group.paths)
                    try:
                        rec_io = ReadIO(path=_OBSRECORD_FNAME)
                        src.sync_read(rec_io)
                        dst.sync_write(
                            WriteIO(
                                path=_OBSRECORD_FNAME,
                                buf=bytes(
                                    memoryview(rec_io.buf).cast("B")
                                ),
                            )
                        )
                    except Exception as e:  # noqa: BLE001 — best-effort
                        obs.swallowed_exception("tier.promote.obsrecord", e)
                    read_io = ReadIO(path=_METADATA_FNAME)
                    src.sync_read(read_io)
                    marker = bytes(memoryview(read_io.buf).cast("B"))
                if dead_skipped:
                    if group.marker_payload is not None:
                        # pinned-marker groups carry no parseable
                        # manifest to re-prove completeness against
                        raise RuntimeError(
                            f"durable commit for {group.durable_url!r} "
                            f"withheld: dead peer(s) {dead_skipped} and "
                            f"a pinned marker — completeness cannot be "
                            f"re-proven"
                        )
                    self._require_durable_complete(
                        dst, marker, dead_skipped, group
                    )
                dst.sync_write(
                    WriteIO(
                        path=_METADATA_FNAME, buf=marker, durable=True
                    )
                )
            group.completed = True
            if group.commit_enqueued_ts is not None:
                obs.histogram(obs.PROMOTION_LAG_S).observe(
                    time.monotonic() - group.commit_enqueued_ts
                )
            # goodput: under write-back, THIS is the durable commit —
            # the take→durable lag ends when the durable marker lands,
            # not when the fast tier acked
            obs.goodput.durable_commit(group.durable_url)
            obs.maybe_write_metrics_textfile()
        finally:
            src.sync_close()
            dst.sync_close()


    def _await_done_keys(
        self, coord, group: PromotionGroup
    ) -> List[int]:
        """Wait for every rank's ``{uid}/tierdone/{r}`` key.  Returns
        the ranks SKIPPED because the liveness monitor declared them
        dead (frozen or never-appearing ``{uid}/tier`` heartbeat) with
        their done-key still absent.  A dead rank whose done-key DID
        land is just a finished rank — death only matters while its
        key is missing."""
        from .. import knobs
        from ..resilience.liveness import LivenessMonitor

        # absence rule ON: every live peer's data job starts stamping
        # as soon as it dequeues, so prolonged absence here means the
        # process never got that far (or is gone)
        monitor = LivenessMonitor(
            coord,
            f"{group.uid}/tier",
            absent_after_s=knobs.get_liveness_timeout_s(),
        )
        deadline = time.monotonic() + _DONE_TIMEOUT_S
        skipped: List[int] = []
        for r in range(coord.world_size):
            while True:
                if coord.kv_try_get(f"{group.uid}/tierdone/{r}") is not None:
                    break
                coord.raise_if_poisoned(f"{group.uid}/tier")
                if r != coord.rank and r in monitor.dead_ranks():
                    skipped.append(r)
                    obs.counter(
                        obs.TAKEOVER_PROMOTER_DEAD_PEERS
                    ).inc()
                    logger.warning(
                        "tier promotion %r: rank %d declared dead "
                        "before publishing its done-key; skipping it "
                        "in the handshake", group.durable_url, r,
                    )
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"tier promotion for {group.durable_url!r}: "
                        f"done-key for live rank {r} never appeared "
                        f"within {_DONE_TIMEOUT_S:g}s"
                    )
                time.sleep(0.1)
        return skipped

    def _require_durable_complete(
        self,
        dst,
        marker: bytes,
        dead_skipped: List[int],
        group: PromotionGroup,
    ) -> None:
        """Dead peers were skipped in the handshake — the marker may
        only land if the durable tier is provably complete anyway (the
        peer died AFTER its copies landed but before its done-key).
        The marker bytes carry the global manifest, so completeness is
        re-proven directly against the durable tier; anything missing
        withholds the marker exactly like a failed job."""
        from ..manifest import SnapshotMetadata

        md = SnapshotMetadata.from_yaml(marker.decode())
        chunked = set((md.cas or {}).get("chunks") or {})
        locs: Set[str] = set()
        for entry in md.manifest.values():
            loc = getattr(entry, "location", None)
            if isinstance(loc, str):
                locs.add(loc)
            for attr in ("shards", "chunks"):
                for shard in getattr(entry, attr, None) or ():
                    locs.add(shard.location)
        missing = sorted(
            p for p in locs - chunked if not _stat_ok(dst, p)
        )
        if missing:
            raise RuntimeError(
                f"durable commit for {group.durable_url!r} withheld: "
                f"dead peer(s) {dead_skipped} skipped in the "
                f"done-handshake and {len(missing)} manifest "
                f"object(s) are not durable-resident — e.g. "
                f"{missing[:3]}"
            )
        logger.warning(
            "tier promotion %r: committing despite dead peer(s) %s — "
            "all %d manifest locations are durable-resident",
            group.durable_url, dead_skipped, len(locs - chunked),
        )


def _stat_ok(storage, path: str) -> bool:
    try:
        storage.sync_stat(path)
        return True
    except Exception:  # noqa: BLE001 — absent or unreachable
        return False


_PROMOTER = Promoter()


def get_promoter() -> Promoter:
    return _PROMOTER


def drain_promotions(raise_on_error: bool = True) -> None:
    """Block until all pending write-back promotions landed (tests,
    benchmarks, and clean shutdowns before the host may be lost)."""
    _PROMOTER.drain(raise_on_error=raise_on_error)
