"""Background write-back promoter: fast-tier payloads → durable tier.

One process-global worker thread drains a FIFO of promotion jobs.  Two
job kinds, always enqueued in this order per take (so FIFO alone gives
the durability invariant):

- ``data`` — copy one rank's fast-tier data objects to the durable tier
  under the scheduler's memory budget (scheduler.sync_execute_copy_reqs),
  then publish this rank's done-key over the coordination KV.
- ``commit`` — rank 0 only: wait for every rank's done-key, then copy
  ``.snapshot_metadata`` (fsync'd, the commit point) and record the
  promotion lag.  Because the metadata copy runs strictly after all
  ranks' data promotions, a crash anywhere in between leaves the durable
  tier WITHOUT metadata — an aborted snapshot by the restore-side
  contract (snapshot.py:645), never a committed-but-incomplete one.

The KV handshake uses only explicit keys (``{uid}/tierdone/{rank}``) —
no collectives, no uid counters — so it is legal from this background
thread under the same rules as the async-commit thread.

``pause()``/``resume()`` exist for tests (deterministic "interrupted
promotion" scenarios); ``drain()`` blocks until the queue is empty and
surfaces any job errors.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import List, Optional, Set, Tuple

from .. import obs

logger = logging.getLogger(__name__)

_METADATA_FNAME = ".snapshot_metadata"  # == snapshot.SNAPSHOT_METADATA_FNAME
_OBSRECORD_FNAME = ".snapshot_obsrecord"  # == obs.aggregate.OBSRECORD_FNAME
_DONE_TIMEOUT_S = 600.0


class PromotionGroup:
    """One take's promotion state on one rank: which fast-tier paths
    need copying (linked/deduped objects are already durable) plus the
    coordination handle for the cross-rank done handshake."""

    def __init__(self, fast_url: str, durable_url: str) -> None:
        self.fast_url = fast_url
        self.durable_url = durable_url
        self.paths: Set[str] = set()
        self.linked: Set[str] = set()
        self.coordinator = None
        self.uid: Optional[str] = None
        self.commit_enqueued_ts: Optional[float] = None
        # set when this rank's data job failed: the commit job fails
        # fast instead of stalling the FIFO for the full done-key
        # timeout (cross-RANK failures still time out — rank 0 cannot
        # see a peer's failure except by its key never appearing)
        self.failed = False
        # crash-recovery re-promotion (SnapshotManager.repromote): paths
        # are the GLOBAL manifest locations, of which this host's fast
        # root may hold only its own rank's share — the data job skips
        # absent objects, and the commit job writes the durable marker
        # only once EVERY location is durable-resident (so a partial
        # multi-host recovery can never fabricate a committed-but-
        # incomplete durable snapshot)
        self.recovery = False
        # pinned commit-marker bytes (continuous/loop.py): when set, the
        # commit job writes THESE bytes as the durable marker instead of
        # copying the fast root's live marker file.  The continuous
        # store's HEAD keeps advancing while its promotion drains in
        # this queue — copying the live file would commit a HEAD whose
        # newer chunks were never part of this group's data job.
        self.marker_payload: Optional[bytes] = None
        # set by the worker when the commit job finished (marker
        # durably written): enqueuers that track durable residency
        # (continuous/loop.py) poll this instead of blocking on drain()
        self.completed = False


class Promoter:
    """Process-global promotion queue (see module docstring)."""

    def __init__(self) -> None:
        self._queue: "queue.Queue[Tuple[str, PromotionGroup]]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._resume = threading.Event()
        self._resume.set()
        self._errors: List[Tuple[str, BaseException]] = []

    # ------------------------------------------------------------ queue

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="tsnp-tier-promoter", daemon=True
                )
                self._thread.start()

    def enqueue_data(self, group: PromotionGroup) -> None:
        with obs.span(
            "tier/enqueue_data", durable=group.durable_url,
            objects=len(group.paths),
        ):
            self._ensure_thread()
            self._queue.put(("data", group))

    def enqueue_commit(self, group: PromotionGroup) -> None:
        with obs.span("tier/enqueue_commit", durable=group.durable_url):
            group.commit_enqueued_ts = time.monotonic()
            self._ensure_thread()
            self._queue.put(("commit", group))

    # ------------------------------------------------------- test hooks

    def pause(self) -> None:
        """Stop processing (jobs keep queueing) — simulates a promotion
        stall/crash window for tests."""
        self._resume.clear()

    def resume(self) -> None:
        self._resume.set()

    def drain(self, raise_on_error: bool = True) -> None:
        """Block until every queued job finished; re-raise the first job
        error (promotion failures are otherwise background warnings)."""
        with obs.span("tier/drain"):
            self._queue.join()
            with self._lock:
                errors, self._errors = self._errors, []
            if errors and raise_on_error:
                raise RuntimeError(
                    f"{len(errors)} promotion job(s) failed"
                ) from errors[0][1]

    # ------------------------------------------------------------ worker

    def _run(self) -> None:
        while True:
            kind, group = self._queue.get()
            try:
                self._resume.wait()
                self._run_job(kind, group)
            except BaseException as e:  # noqa: BLE001 — background thread
                group.failed = True
                logger.exception(
                    "tier promotion %s job for %r failed", kind,
                    group.durable_url,
                )
                # cross-rank abort: rank 0's commit job is (or will be)
                # blocked waiting for every rank's done-key — poison the
                # promotion scope so it withholds the durable commit
                # marker within one poll interval instead of stalling
                # the FIFO for the full done-key timeout
                coord = group.coordinator
                if coord is not None and group.uid is not None:
                    coord.poison(
                        f"{group.uid}/tier",
                        cause=repr(e),
                        site=f"tier.promote.{kind}/rank{coord.rank}",
                    )
                with self._lock:
                    self._errors.append((kind, e))
            finally:
                self._queue.task_done()

    def _run_job(self, kind: str, group: PromotionGroup) -> None:
        from ..scheduler import (
            get_process_memory_budget_bytes,
            sync_execute_copy_reqs,
        )
        from ..storage import url_to_storage_plugin

        from ..resilience.failpoints import failpoint

        src = url_to_storage_plugin(group.fast_url)
        dst = url_to_storage_plugin(group.durable_url)
        try:
            if kind == "data":
                failpoint("tier.promote.data", durable=group.durable_url)
                paths = sorted(group.paths - group.linked)
                if group.recovery:
                    # this host's fast root holds only its own share of
                    # the global manifest — copy what exists locally
                    paths = [p for p in paths if _stat_ok(src, p)]
                with obs.span(
                    "tier/promote_data", durable=group.durable_url,
                    objects=len(paths),
                ):
                    sync_execute_copy_reqs(
                        paths,
                        src,
                        dst,
                        get_process_memory_budget_bytes(),
                    )
                coord = group.coordinator
                if coord is not None and group.uid is not None:
                    coord.kv_set(
                        f"{group.uid}/tierdone/{coord.rank}", "ok"
                    )
                return
            # commit: all ranks durable → metadata last
            with obs.span(
                "tier/promote_commit", durable=group.durable_url
            ):
                failpoint("tier.promote.commit", durable=group.durable_url)
                if group.failed:
                    raise RuntimeError(
                        f"durable commit for {group.durable_url!r} "
                        f"withheld: this rank's data promotion failed"
                    )
                coord = group.coordinator
                if coord is not None and group.uid is not None:
                    # abort-aware done-key wait: a peer whose data
                    # promotion failed poisons {uid}/tier, and this wait
                    # raises SnapshotAbortedError promptly — the durable
                    # commit marker is withheld either way
                    with coord.abort_scope(f"{group.uid}/tier"):
                        for r in range(coord.world_size):
                            coord.kv_get(
                                f"{group.uid}/tierdone/{r}", _DONE_TIMEOUT_S
                            )
                if group.recovery:
                    # no cross-rank handshake in recovery mode: gate the
                    # commit marker on every manifest location actually
                    # being durable-resident instead
                    missing = [
                        p for p in sorted(group.paths)
                        if not _stat_ok(dst, p)
                    ]
                    if missing:
                        raise RuntimeError(
                            f"recovery promotion for {group.durable_url!r}"
                            f" incomplete: {len(missing)} object(s) not "
                            f"yet durable (other hosts' shares?); durable"
                            f" commit marker withheld — e.g. {missing[:3]}"
                        )
                from ..io_types import ReadIO, WriteIO

                if group.marker_payload is not None:
                    # pinned marker (continuous promotion): commit the
                    # HEAD as of enqueue time, not whatever the still-
                    # advancing fast root says now; such groups have no
                    # flight-record sidecar
                    marker = group.marker_payload
                else:
                    # flight-record sidecar first, best-effort: the
                    # durable tier keeps the record-lands-before-marker
                    # ordering, and a missing/unreadable record never
                    # blocks the durable commit (it is telemetry, not
                    # payload — the tier plugin deliberately keeps it
                    # out of group.paths)
                    try:
                        rec_io = ReadIO(path=_OBSRECORD_FNAME)
                        src.sync_read(rec_io)
                        dst.sync_write(
                            WriteIO(
                                path=_OBSRECORD_FNAME,
                                buf=bytes(
                                    memoryview(rec_io.buf).cast("B")
                                ),
                            )
                        )
                    except Exception as e:  # noqa: BLE001 — best-effort
                        obs.swallowed_exception("tier.promote.obsrecord", e)
                    read_io = ReadIO(path=_METADATA_FNAME)
                    src.sync_read(read_io)
                    marker = bytes(memoryview(read_io.buf).cast("B"))
                dst.sync_write(
                    WriteIO(
                        path=_METADATA_FNAME, buf=marker, durable=True
                    )
                )
            group.completed = True
            if group.commit_enqueued_ts is not None:
                obs.histogram(obs.PROMOTION_LAG_S).observe(
                    time.monotonic() - group.commit_enqueued_ts
                )
            # goodput: under write-back, THIS is the durable commit —
            # the take→durable lag ends when the durable marker lands,
            # not when the fast tier acked
            obs.goodput.durable_commit(group.durable_url)
            obs.maybe_write_metrics_textfile()
        finally:
            src.sync_close()
            dst.sync_close()


def _stat_ok(storage, path: str) -> bool:
    try:
        storage.sync_stat(path)
        return True
    except Exception:  # noqa: BLE001 — absent or unreachable
        return False


_PROMOTER = Promoter()


def get_promoter() -> Promoter:
    return _PROMOTER


def drain_promotions(raise_on_error: bool = True) -> None:
    """Block until all pending write-back promotions landed (tests,
    benchmarks, and clean shutdowns before the host may be lost)."""
    _PROMOTER.drain(raise_on_error=raise_on_error)
