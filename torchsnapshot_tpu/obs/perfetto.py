"""Perfetto / Chrome ``trace_event`` JSON export for recorded spans.

The output loads directly in https://ui.perfetto.dev (or
chrome://tracing): one named track per pipeline stage (``pipeline/…``,
``storage/…`` spans are grouped by span name), one track per remaining
Python thread, and flow arrows ("s"/"f" events) linking staging
completion to storage-I/O start via the spans' ``flow_out``/``flow_in``
ids.

Complete ("X") events on one tid must be properly nested, but a stage's
spans are concurrent siblings (several staging ops in flight at once),
so each stage track is interval-partitioned: overlapping same-stage
spans spill onto ``<stage> #2``, ``#3``… tracks.  Same-name stage spans
never nest (they are independent pipeline items), and thread tracks
carry only synchronous — properly nested — spans, so the remaining
single-track cases are well-formed.

Each "X" (complete) event carries ``span_id``/``parent_id`` in ``args``
so the span TREE survives the export — tests (and humans) can check
nesting without re-deriving it from timestamps.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from .tracer import Span, Tracer, get_tracer

# Span-name prefixes that get one track per NAME (the pipeline stages);
# anything else is tracked by its recording thread.  ``stripe/`` is
# here so per-PART slices (stripe/stage_part, stripe/write_part) land
# on stage tracks with interval partitioning — on thread tracks the
# concurrent parts of one object would violate complete-event nesting
# and striped pipelining would be invisible.
_STAGE_PREFIXES = ("pipeline/", "storage/", "offload/", "stripe/")


def _track_key(s: Span) -> str:
    for prefix in _STAGE_PREFIXES:
        if s.name.startswith(prefix):
            return s.name
    return f"thread:{s.thread_name}"


def to_trace_events(spans: List[Span], pid: int = 1) -> Dict[str, Any]:
    """Build the ``{"traceEvents": [...]}`` dict for ``spans``."""
    events: List[Dict[str, Any]] = []
    tids: Dict[str, int] = {}

    def tid_for(key: str) -> int:
        tid = tids.get(key)
        if tid is None:
            tid = tids[key] = len(tids) + 1
            events.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": key},
                }
            )
        return tid

    slot_ends: Dict[str, List[int]] = {}
    # Slot cap per stage: admission spans all open at pipeline start, so
    # unbounded partitioning would mint one track per request (and an
    # O(n^2) scan) on a 10k-leaf take.  Past the cap, the earliest-
    # ending slot is reused — a rare, slightly-overlapping slice beats
    # ten thousand tracks.
    _MAX_SLOTS = 32

    def _slotted_track(s: Span, key: str) -> str:
        """First stage-track slot whose previous span ended before this
        one starts (greedy interval partitioning, bounded); overlapping
        siblings spill onto numbered sibling tracks."""
        ends = slot_ends.setdefault(key, [])
        for i, end in enumerate(ends):
            if s.start_ns >= end:
                ends[i] = s.end_ns
                return key if i == 0 else f"{key} #{i + 1}"
        if len(ends) >= _MAX_SLOTS:
            i = min(range(len(ends)), key=ends.__getitem__)
            ends[i] = max(ends[i], s.end_ns)
            return key if i == 0 else f"{key} #{i + 1}"
        ends.append(s.end_ns)
        slot = len(ends) - 1
        return key if slot == 0 else f"{key} #{slot + 1}"

    for s in sorted(spans, key=lambda s: s.start_ns):
        if not s.end_ns:
            continue  # never closed (crashed mid-span): skip
        key = _track_key(s)
        if key.startswith(_STAGE_PREFIXES):
            key = _slotted_track(s, key)
        tid = tid_for(key)
        ts = s.start_ns / 1000.0  # trace_event timestamps are µs
        dur = s.duration_ns / 1000.0
        events.append(
            {
                "ph": "X",
                "name": s.name,
                "cat": s.name.split("/", 1)[0],
                "pid": pid,
                "tid": tid,
                "ts": ts,
                "dur": dur,
                "args": {
                    "span_id": s.span_id,
                    "parent_id": s.parent_id,
                    "thread": s.thread_name,
                    **({"task": s.task_name} if s.task_name else {}),
                    **s.attrs,
                },
            }
        )
        # Flow arrows: staging completion -> I/O start.  The start step
        # anchors at this span's END, the finish step (binding point
        # "e" = enclosing slice) at the consuming span's START.
        if s.flow_out is not None:
            events.append(
                {
                    "ph": "s",
                    "cat": "flow",
                    "name": "staged→io",
                    "id": s.flow_out,
                    "pid": pid,
                    "tid": tid,
                    "ts": ts + dur,
                }
            )
        if s.flow_in is not None:
            events.append(
                {
                    "ph": "f",
                    "bp": "e",
                    "cat": "flow",
                    "name": "staged→io",
                    "id": s.flow_in,
                    "pid": pid,
                    "tid": tid,
                    "ts": ts,
                }
            )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_trace(path: str, tracer: Optional[Tracer] = None) -> int:
    """Write the tracer's recorded spans as Perfetto JSON; returns the
    number of spans exported."""
    tracer = tracer or get_tracer()
    spans = tracer.spans()
    doc = to_trace_events(spans)
    with open(path, "w") as f:
        json.dump(doc, f)
    return sum(1 for s in spans if s.end_ns)
