"""Goodput/SLO accounting: what checkpointing costs the training loop.

Three numbers, tracked per process and exposed as always-on gauges,
flight-record blocks (obs/aggregate.py) and BENCH blocks (bench.py):

- **time-to-unblock-train** (``goodput.time_to_unblock_s``) — how long
  the last take blocked its caller.  For ``async_take`` this is the
  blocked window before the handle returns (the library's headline
  value prop); for a sync ``take`` it is the whole call.
- **durability lag** (``goodput.durability_lag_s``) — last
  take-begin → durable-commit interval.  Under a write-back tier this
  covers background promotion: the lag ends when the DURABLE
  ``.snapshot_metadata`` marker lands (tier/promoter.py), not when the
  fast tier acks.
- **checkpoint overhead fraction** (``goodput.overhead_fraction``) —
  cumulative blocked seconds divided by wall time since the first take
  began: the fraction of the training run spent NOT training because of
  checkpointing (the goodput loss attributable to this library).

State is keyed by snapshot path so overlapping async takes to distinct
steps account independently; all updates are lock-guarded (take,
async-commit and promoter threads all report here).  A flight record is
written BEFORE its own take's durable commit, so the record's
``durability_lag_s`` describes the most recent COMPLETED commit —
step-over-step inspection is exactly what ``doctor --diff`` is for.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from .metrics import (
    GOODPUT_DURABILITY_LAG_S,
    GOODPUT_OVERHEAD_FRACTION,
    GOODPUT_TIME_TO_UNBLOCK_S,
    gauge,
)

_lock = threading.Lock()
# path -> monotonic begin timestamp of the most recent take of it.
# Bounded: durable_commit pops its entry, and takes whose commit never
# arrives (aborted, crashed promoter) are evicted oldest-first past the
# cap — a per-step SnapshotManager must not leak one entry per
# checkpoint for the life of the process.
_begin_ts: Dict[str, float] = {}
_MAX_PENDING_BEGINS = 64


def _key(path: str) -> str:
    # the tier promoter reports durable commits under the plugin's
    # rstripped durable url; normalize so "s3://b/ck/" and "s3://b/ck"
    # land on one entry
    return str(path).rstrip("/")


# cumulative seconds the caller was blocked inside take()/async_take()
_blocked_total_s = 0.0
# monotonic timestamp of the FIRST take begin (overhead denominator)
_first_begin_ts: Optional[float] = None
_takes = 0
_durable_commits = 0
_last_unblock_s: Optional[float] = None
_last_durability_lag_s: Optional[float] = None


def take_begin(path: str) -> float:
    """A take of ``path`` is starting; returns the begin timestamp the
    caller hands back to ``take_unblocked``."""
    from .. import obs

    with obs.span("goodput/take_begin", path=path):
        now = time.monotonic()
        global _first_begin_ts, _takes
        with _lock:
            k = _key(path)
            # re-insert at the tail so eviction order tracks recency
            _begin_ts.pop(k, None)
            _begin_ts[k] = now
            while len(_begin_ts) > _MAX_PENDING_BEGINS:
                _begin_ts.pop(next(iter(_begin_ts)))
            if _first_begin_ts is None:
                _first_begin_ts = now
            _takes += 1
        return now


def take_unblocked(path: str, begin_ts: float) -> float:
    """The caller regained control (sync take returned / async_take
    handed back its handle): record time-to-unblock and fold the
    blocked window into the overhead fraction.  Returns the blocked
    seconds."""
    from .. import obs

    with obs.span("goodput/take_unblocked", path=path):
        now = time.monotonic()
        blocked = max(0.0, now - begin_ts)
        global _blocked_total_s, _last_unblock_s
        with _lock:
            _blocked_total_s += blocked
            _last_unblock_s = blocked
            first = _first_begin_ts
            total_blocked = _blocked_total_s
        gauge(GOODPUT_TIME_TO_UNBLOCK_S).set(blocked)
        if first is not None and now > first:
            gauge(GOODPUT_OVERHEAD_FRACTION).set(
                min(1.0, total_blocked / (now - first))
            )
        return blocked


def durable_commit(path: str) -> Optional[float]:
    """The durable ``.snapshot_metadata`` marker for ``path`` landed
    (sync/async commit, or the write-back promoter's metadata copy):
    record the end-to-end durability lag.  Returns the lag, or None
    when no begin was recorded for the path in this process (e.g. a
    recovery re-promotion of a pre-crash take)."""
    from .. import obs

    with obs.span("goodput/durable_commit", path=path):
        now = time.monotonic()
        global _durable_commits, _last_durability_lag_s
        with _lock:
            # pop, not get: the committed entry's job is done (and the
            # dict stays bounded over a long per-step training run)
            begin = _begin_ts.pop(_key(path), None)
            _durable_commits += 1
            if begin is None:
                return None
            lag = max(0.0, now - begin)
            _last_durability_lag_s = lag
        gauge(GOODPUT_DURABILITY_LAG_S).set(lag)
        return lag


def block() -> Dict[str, Any]:
    """JSON-safe goodput block for flight records and BENCH records."""
    with _lock:
        first = _first_begin_ts
        out: Dict[str, Any] = {
            "takes": _takes,
            "durable_commits": _durable_commits,
            "time_to_unblock_s": (
                round(_last_unblock_s, 6)
                if _last_unblock_s is not None
                else None
            ),
            "durability_lag_s": (
                round(_last_durability_lag_s, 6)
                if _last_durability_lag_s is not None
                else None
            ),
            "blocked_total_s": round(_blocked_total_s, 6),
        }
    now = time.monotonic()
    out["overhead_fraction"] = (
        round(
            min(1.0, out["blocked_total_s"] / (now - first)), 6
        )
        if first is not None and now > first
        else None
    )
    return out


def reset() -> None:
    """Zero the tracker (tests; the metrics-registry gauges reset
    separately via ``obs.reset_metrics``)."""
    global _blocked_total_s, _first_begin_ts, _takes
    global _durable_commits, _last_unblock_s, _last_durability_lag_s
    with _lock:
        _begin_ts.clear()
        _blocked_total_s = 0.0
        _first_begin_ts = None
        _takes = 0
        _durable_commits = 0
        _last_unblock_s = None
        _last_durability_lag_s = None
