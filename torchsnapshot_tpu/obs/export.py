"""OpenMetrics (Prometheus text exposition) export of the registry.

Scrape-based fleets don't read BENCH records — they run node_exporter
with a textfile collector.  ``export_openmetrics()`` renders the live
registry in the text exposition format (counters as ``_total``, gauges
plus a ``_max`` high-water twin, histograms with cumulative
``_bucket{le=...}`` series), and ``write_metrics_textfile()`` dumps it
atomically (tmp + rename — textfile collectors must never scrape a
half-written file) to the path named by the
``TORCHSNAPSHOT_TPU_METRICS_TEXTFILE`` knob.  take/restore/async-commit
call ``maybe_write_metrics_textfile()`` on their way out, so an
exporter sidecar sees fresh numbers after every operation without any
in-process HTTP server.

Metric names are sanitized to the exposition charset
(``[a-zA-Z_:][a-zA-Z0-9_:]*``) and prefixed ``tsnp_``:
``storage.fs.write_latency_s`` → ``tsnp_storage_fs_write_latency_s``.
"""

from __future__ import annotations

import os
import re
import tempfile
from typing import Any, Dict, Optional

from .metrics import MetricsRegistry, REGISTRY

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "tsnp_"


def _name(raw: str) -> str:
    return _PREFIX + _NAME_RE.sub("_", raw)


def _fmt(v: Any) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def export_openmetrics(registry: Optional[MetricsRegistry] = None) -> str:
    """The registry rendered in Prometheus text exposition format."""
    snap = (registry or REGISTRY).snapshot()
    lines = []
    for raw, v in sorted(snap.get("counters", {}).items()):
        # the TYPE line must name the SAMPLE's metric name (_total
        # included) in the classic text format, or the type metadata
        # never attaches — node_exporter itself emits `# TYPE x_total
        # counter` / `x_total v`
        n = _name(raw) + "_total"
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {_fmt(v)}")
    for raw, g in sorted(snap.get("gauges", {}).items()):
        n = _name(raw)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_fmt(g['value'])}")
        lines.append(f"# TYPE {n}_max gauge")
        lines.append(f"{n}_max {_fmt(g['max'])}")
    for raw, h in sorted(snap.get("histograms", {}).items()):
        n = _name(raw)
        lines.append(f"# TYPE {n} histogram")
        cumulative = 0
        for bound, count in zip(h["bounds"], h["counts"]):
            cumulative += count
            lines.append(
                f'{n}_bucket{{le="{_fmt(bound)}"}} {cumulative}'
            )
        lines.append(f'{n}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{n}_sum {_fmt(h['sum'])}")
        lines.append(f"{n}_count {h['count']}")
    return "\n".join(lines) + "\n"


def write_metrics_textfile(
    path: str, registry: Optional[MetricsRegistry] = None
) -> str:
    """Atomic dump of the exposition text to ``path`` (tmp in the same
    directory + rename, the textfile-collector contract)."""
    text = export_openmetrics(registry)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".tsnp-metrics-", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def maybe_write_metrics_textfile() -> Optional[str]:
    """Dump the registry iff the ``TORCHSNAPSHOT_TPU_METRICS_TEXTFILE``
    knob names a path.  Best-effort and never raises: metrics export
    must not fail the operation it describes.  Returns the path written,
    or None.

    A ``{pid}`` placeholder in the path expands to this process's pid —
    REQUIRED when several worker processes share one host and one env:
    a fixed path is last-writer-wins and silently drops every other
    rank's registry from the scrape."""
    from .. import knobs, obs

    path = knobs.get_metrics_textfile()
    if not path:
        return None
    try:
        return write_metrics_textfile(
            path.replace("{pid}", str(os.getpid()))
        )
    except Exception as e:  # noqa: BLE001 — best-effort by contract
        obs.swallowed_exception("obs.export.textfile", e)
        return None
