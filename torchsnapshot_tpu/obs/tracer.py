"""In-process structured tracing: a span tree with monotonic timestamps.

Spans record where a ``take()``/``restore()`` spent its time: each span
carries monotonic start/end (ns), free-form attributes, its recording
thread and (when applicable) asyncio task identity, and a parent link so
exports can reconstruct the tree.  Parenthood propagates through a
``contextvars.ContextVar``, which is the one mechanism that is correct
across BOTH threads (each thread has its own context) and asyncio tasks
(each task snapshots the context at creation) — exactly the two
execution domains the scheduler pipeline spans (caller thread, staging
executor threads, loop-thread tasks).

Cost discipline: tracing is OFF by default and the disabled path is
allocation-free — ``span()`` checks the module-level ``ENABLED`` flag
and returns one shared ``nullcontext`` singleton before any Span object,
attrs dict copy, or clock read happens.  The flag is owned by the
``TORCHSNAPSHOT_TPU_TRACE`` knob (knobs.py); ``knobs.override_trace``
refreshes it so tests can toggle tracing without touching this module.

Completed spans also feed the existing ``log_event`` fan-out: when any
event handler is registered, each finished span fires an
``Event("span/<name>")`` through the same handler chain, so existing
telemetry collectors see span-level detail without a second
registration API.  (Spans created BY ``log_event``'s own bracketing are
excluded — the original event already fired for those.)
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from contextvars import ContextVar
from typing import Any, Dict, Iterator, List, Optional

from .. import knobs

# Shared disabled-path singleton: ``span()`` returns this before any
# allocation when tracing is off.
NULL_CM = contextlib.nullcontext(None)

# Module-level enabled flag — read directly (``tracer.ENABLED``) by hot
# paths that want to skip even the ``span()`` call's argument packing.
ENABLED = False

_ids = itertools.count(1)
_flow_ids = itertools.count(1)
_current: ContextVar[Optional["Span"]] = ContextVar("tsnp_span", default=None)

# Bound the recorded-span list: a runaway traced loop must degrade to
# dropped spans, never to unbounded host memory.
_MAX_SPANS = 200_000


class Span:
    """One timed operation.  ``start_ns``/``end_ns`` are
    ``time.monotonic_ns`` values; ``end_ns`` is 0 until the span closes."""

    __slots__ = (
        "name",
        "span_id",
        "parent_id",
        "start_ns",
        "end_ns",
        "attrs",
        "thread_id",
        "thread_name",
        "task_name",
        "flow_in",
        "flow_out",
    )

    def __init__(
        self,
        name: str,
        parent_id: Optional[int],
        attrs: Dict[str, Any],
    ) -> None:
        self.name = name
        self.span_id = next(_ids)
        self.parent_id = parent_id
        self.attrs = attrs
        self.start_ns = 0
        self.end_ns = 0
        t = threading.current_thread()
        self.thread_id = t.ident or 0
        self.thread_name = t.name
        self.task_name = _current_task_name()
        # Perfetto flow (async arrow) endpoints: ``flow_out`` emits an
        # arrow start at this span's END, ``flow_in`` an arrow end at
        # this span's START.  The scheduler links staging completion to
        # storage-I/O start this way.
        self.flow_in: Optional[int] = None
        self.flow_out: Optional[int] = None

    @property
    def duration_ns(self) -> int:
        return max(0, self.end_ns - self.start_ns)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "thread_id": self.thread_id,
            "thread_name": self.thread_name,
            "task_name": self.task_name,
            "flow_in": self.flow_in,
            "flow_out": self.flow_out,
            "attrs": dict(self.attrs),
        }


def _current_task_name() -> Optional[str]:
    try:
        import asyncio

        task = asyncio.current_task()
    except RuntimeError:  # no running event loop on this thread
        return None
    return task.get_name() if task is not None else None


class Tracer:
    """Lock-protected recorder of finished spans.

    ``begin``/``end`` exist for spans whose lifetime crosses loop
    iterations (e.g. budget-admission waits); the ``span()`` context
    manager is the ergonomic path for lexically-scoped spans and is the
    only one that establishes parenthood for code nested under it."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self.dropped = 0

    # ----------------------------------------------------------- record

    def begin(
        self, name: str, parent: Optional[Span] = None, **attrs: Any
    ) -> Span:
        """Open a span WITHOUT making it the context parent (it can be
        closed from any thread/task via ``end``)."""
        if parent is None:
            parent = _current.get()
        s = Span(name, parent.span_id if parent else None, attrs)
        s.start_ns = time.monotonic_ns()
        return s

    def end(self, s: Span, fire_event: bool = False) -> None:
        if s.end_ns:  # already closed — idempotent
            return
        s.end_ns = time.monotonic_ns()
        self._record(s)
        if fire_event:
            _fire_span_event(s)

    def _record(self, s: Span) -> None:
        with self._lock:
            if len(self._spans) >= _MAX_SPANS:
                self.dropped += 1
                return
            self._spans.append(s)

    # ---------------------------------------------------------- inspect

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def current_span() -> Optional[Span]:
    return _current.get()


def next_flow_id() -> int:
    return next(_flow_ids)


# ------------------------------------------------------------- enabling


def tracing_enabled() -> bool:
    return ENABLED


def set_tracing(on: bool) -> None:
    global ENABLED
    ENABLED = bool(on)


def refresh_enabled() -> bool:
    """Re-resolve the ``TORCHSNAPSHOT_TPU_TRACE`` knob into the module
    flag (called by ``knobs.override_trace`` and at import)."""
    set_tracing(knobs.is_trace_enabled())
    return ENABLED


refresh_enabled()


# ----------------------------------------------------------------- span


def span(name: str, fire_event: bool = True, **attrs: Any):
    """Context manager recording one span, or a shared no-op when
    tracing is disabled.  Yields the ``Span`` (None when disabled) so
    callers can attach late attributes (``s.attrs["bytes"] = n``)."""
    if not ENABLED:
        return NULL_CM
    return _span_cm(name, fire_event, attrs)


@contextlib.contextmanager
def _span_cm(
    name: str, fire_event: bool, attrs: Dict[str, Any]
) -> Iterator[Span]:
    parent = _current.get()
    s = Span(name, parent.span_id if parent else None, attrs)
    token = _current.set(s)
    s.start_ns = time.monotonic_ns()
    try:
        yield s
    except BaseException:
        s.attrs["error"] = True
        raise
    finally:
        s.end_ns = time.monotonic_ns()
        _current.reset(token)
        _TRACER._record(s)
        if fire_event:
            _fire_span_event(s)


def _fire_span_event(s: Span) -> None:
    """Feed the finished span into the event-handler fan-out (lazy
    import: event_handlers composes with this module in both
    directions)."""
    from .. import event_handlers

    # entry-point discovery must run before the emptiness check, or a
    # collector registered solely via the entry-point group would miss
    # every span of the first traced operation (discovery is cached, so
    # this is one flag check per span after the first)
    event_handlers._load_entry_point_handlers()
    if not (
        event_handlers._handlers or event_handlers._entry_point_handlers
    ):
        return
    from ..event import Event

    event_handlers._fire(
        Event(
            f"span/{s.name}",
            {
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "duration_s": s.duration_ns / 1e9,
                **s.attrs,
            },
        )
    )
