"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Always-on by design (unlike spans): the instruments below are updated a
handful of times per storage op / pipeline transition, and each update
is one lock-protected arithmetic op — cheap enough to leave running so
benchmarks and the CLI can read real numbers without flipping any knob.

Snapshot format (``snapshot()``) is plain JSON-safe dicts so ``bench.py``
can embed it verbatim in BENCH records:

    {"counters": {name: int},
     "gauges": {name: {"value": float, "max": float}},
     "histograms": {name: {"count": int, "sum": float, "min": float,
                           "max": float, "bounds": [...], "counts": [...]}}}

``counts`` has ``len(bounds) + 1`` entries; the last is the overflow
bucket (values above every bound) — no ``Infinity`` literals, so the
snapshot survives strict JSON parsers.
"""

from __future__ import annotations

import bisect
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Default bucket ladders.  Latency in seconds (sub-ms to a minute);
# bytes from 1KB to 4GB in powers of ~4 — both chosen to straddle the
# ranges the storage plugins and scheduler actually produce.
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
BYTES_BUCKETS: Tuple[float, ...] = (
    1024.0, 16384.0, 262144.0, 1048576.0, 4194304.0, 16777216.0,
    67108864.0, 268435456.0, 1073741824.0, 4294967296.0,
)

# Well-known instrument names (the instrumented hot path uses these; a
# single source of truth keeps bench/docs/tests from drifting).
BYTES_STAGED = "bytes_staged"
BYTES_WRITTEN = "bytes_written"
BYTES_READ = "bytes_read"
BYTES_DEDUPED = "bytes_deduped"
BYTES_OFFLOADED = "bytes_offloaded"
BUDGET_BYTES_IN_USE = "budget_bytes_in_use"
IO_QUEUE_DEPTH = "io_queue_depth"
# the read pipeline's twins: an async_take's background drain can
# overlap a restore in the same process, so the two pipelines must not
# interleave writes to one gauge
BUDGET_BYTES_IN_USE_READ = "budget_bytes_in_use_read"
IO_QUEUE_DEPTH_READ = "io_queue_depth_read"
RSS_PEAK_DELTA_BYTES = "rss_peak_delta_bytes"
SLABS_PACKED = "slabs_packed"
# tiered storage (tier/): read-path residency + write-back promotion.
# hits/misses count tier-plugin reads served by the fast tier vs fallen
# back (peer or durable); repairs count fast-tier copies rewritten from
# a fallback source; corrupt counts fast copies that failed their
# digest/parse check.  bytes_promoted/promotion_lag_s describe the
# write-back promoter (fast-commit → durable-commit).
TIER_FAST_HITS = "tier.fast_hits"
TIER_FAST_MISSES = "tier.fast_misses"
TIER_FAST_REPAIRS = "tier.fast_repairs"
TIER_FAST_CORRUPT = "tier.fast_corrupt"
TIER_PEER_HITS = "tier.peer_hits"
BYTES_PROMOTED = "tier.bytes_promoted"
BYTES_REPLICATED = "tier.bytes_replicated"
PROMOTION_LAG_S = "tier.promotion_lag_s"
# Striped storage I/O (storage/stripe.py): whole-object writes/reads
# that were split into parts, the parts themselves, bytes moved through
# the striped paths, and aborted striped writes (failure/poison cleanup
# that tore down a multipart upload).  Part-level latencies land in the
# storage.stripe.part_write_latency_s / part_read_latency_s histograms;
# per-backend byte/latency instruments keep recording per part via
# record_storage_io, so backend dashboards see striped traffic too.
STRIPE_WRITES = "storage.stripe.writes"
STRIPE_READS = "storage.stripe.reads"
STRIPE_PARTS_WRITTEN = "storage.stripe.parts_written"
STRIPE_PARTS_READ = "storage.stripe.parts_read"
STRIPE_BYTES_WRITTEN = "storage.stripe.bytes_written"
STRIPE_BYTES_READ = "storage.stripe.bytes_read"
STRIPE_ABORTS = "storage.stripe.aborts"
STRIPE_STREAMED_WRITES = "storage.stripe.streamed_writes"
STRIPE_PART_WRITE_LATENCY_S = "storage.stripe.part_write_latency_s"
STRIPE_PART_READ_LATENCY_S = "storage.stripe.part_read_latency_s"
# Per-part compression (codec.py): raw bytes entering the encode stage,
# stored (frame) bytes leaving it, parts that kept their encoded frame
# vs fell back to store-raw (min-ratio check), and frames decoded on
# restore.  Per-codec encode/decode latencies land in
# storage.codec.{encode,decode}_latency_s.<codec> histograms.
CODEC_BYTES_IN = "storage.codec.bytes_in"
CODEC_BYTES_OUT = "storage.codec.bytes_out"
CODEC_PARTS_ENCODED = "storage.codec.parts_encoded"
CODEC_PARTS_RAW_FALLBACK = "storage.codec.parts_raw_fallback"
CODEC_PARTS_DECODED = "storage.codec.parts_decoded"
# Shared-host object cache (storage/hostcache.py): a hit served the
# read from the per-host cache directory without touching the durable
# tier; a miss performed the one durable GET that fills the entry; a
# singleflight wait blocked behind another process's in-flight fill of
# the SAME object and then served the filled entry (no GET of its own)
# — on an N-reader cold start hits+waits should approach N-1 per
# object while misses stay at exactly 1.
CACHE_HITS = "storage.cache.hits"
CACHE_MISSES = "storage.cache.misses"
CACHE_SINGLEFLIGHT_WAITS = "storage.cache.singleflight_waits"
CACHE_BYTES_FILLED = "storage.cache.bytes_filled"
CACHE_EVICTIONS = "storage.cache.evictions"
# Native fast-I/O engine (storage/fastio.py): bytes moved through the
# engine's GIL-free part readers/writers, parts that took the O_DIRECT
# leg vs the buffered (pwritev-batched) leg, part digests fused into
# the same native pass that moved the bytes (each one is a full read
# pass the old path paid separately), waits for an exhausted aligned
# bounce-buffer pool (backpressure — size FASTIO_BUFFER_POOL_BYTES up
# if this grows), and reads that applied the posix_fadvise(DONTNEED)
# fallback where O_DIRECT was unavailable.
FASTIO_BYTES_WRITTEN = "storage.fastio.bytes_written"
FASTIO_BYTES_READ = "storage.fastio.bytes_read"
FASTIO_DIRECT_PARTS = "storage.fastio.direct_parts"
FASTIO_BUFFERED_PARTS = "storage.fastio.buffered_parts"
FASTIO_FUSED_DIGESTS = "storage.fastio.fused_digests"
FASTIO_POOL_WAITS = "storage.fastio.pool_waits"
FASTIO_DONTNEED_READS = "storage.fastio.dontneed_reads"
# Zero-copy mmap reads (io_types.ReadIO.want_mmap): reads served as
# read-only file-backed mappings instead of heap copies, and the bytes
# mapped (pages fault in lazily — mapped ≠ resident).
MMAP_READS = "storage.mmap.reads"
MMAP_BYTES_MAPPED = "storage.mmap.bytes_mapped"
# Phase timing (cross-rank straggler attribution, obs/aggregate.py):
# always-on histograms of where a take/restore spent its wall time on
# THIS rank.  One observe per pipeline task / coordination wait — cheap
# enough to leave running; per-operation deltas ride the flight-record
# exchange so rank 0 can name "rank 3, write phase" without a re-run.
# stage/encode/write are take-side, read/consume restore-side, barrier
# covers coordination waits in both directions.
PHASE_STAGE_S = "phase.stage_s"
PHASE_ENCODE_S = "phase.encode_s"
PHASE_WRITE_S = "phase.write_s"
PHASE_READ_S = "phase.read_s"
PHASE_CONSUME_S = "phase.consume_s"
PHASE_BARRIER_S = "phase.barrier_s"
PHASE_PREFIX = "phase."
# Goodput/SLO accounting (obs/goodput.py): how long the training loop
# was blocked by the last checkpoint, last take→durable-commit lag
# (covers write-back promotion), and the cumulative fraction of wall
# time spent blocked on checkpointing since the first take.
GOODPUT_TIME_TO_UNBLOCK_S = "goodput.time_to_unblock_s"
GOODPUT_DURABILITY_LAG_S = "goodput.durability_lag_s"
GOODPUT_OVERHEAD_FRACTION = "goodput.overhead_fraction"
# GC/retention: bytes of storage objects reclaimed by delete_snapshot.
# Under the chunk store (cas/) this counts per-step objects PLUS only
# the chunks whose refcount dropped to zero — shared chunks are not
# reclaimed by deleting one of their referencing steps.
GC_BYTES_RECLAIMED = "snapshot.gc.bytes_reclaimed"
# Content-addressed chunk store (cas/): chunks/bytes a take actually
# wrote vs skipped because an earlier committed step already stored the
# content (bytes_shared / bytes_written is the take's dedup win), chunks
# physically deleted by the two-phase GC sweep, and index rebuilds.
CAS_CHUNKS_WRITTEN = "cas.chunks_written"
CAS_CHUNKS_SHARED = "cas.chunks_shared"
CAS_BYTES_WRITTEN = "cas.bytes_written"
CAS_BYTES_SHARED = "cas.bytes_shared"
CAS_CHUNKS_SWEPT = "cas.chunks_swept"
CAS_BYTES_SWEPT = "cas.bytes_swept"
CAS_FSCKS = "cas.fscks"
# Multislice topology (topology/): write-side replicated objects/bytes
# this rank wrote under the topology-aware partition (explicit
# topologies only; a chunk-split object counts once per rank carrying
# any of its chunks — per-slice rollups come from grouping ranks by
# their flight-record slice id), and the
# fan-out restore's ledger — inner durable-tier GETs issued for shared
# (replicated) objects by this rank (designated reads + fallbacks; the
# per-slice sum is the bounded quantity: O(objects), not
# O(objects × ranks)), reads served from a sibling's publication
# instead of the durable tier, bytes redistributed over the
# coordination KV, publications performed, and timeouts/digest
# mismatches that degraded a read to a direct durable GET.
TOPOLOGY_SLICES = "topology.slices"
TOPOLOGY_REPLICATED_OBJECTS_WRITTEN = "topology.replicated_objects_written"
TOPOLOGY_REPLICATED_BYTES_WRITTEN = "topology.replicated_bytes_written"
FANOUT_DURABLE_READS = "topology.fanout_durable_reads"
FANOUT_DURABLE_GETS_SAVED = "topology.durable_gets_saved"
FANOUT_BYTES_REDISTRIBUTED = "topology.fanout_bytes_redistributed"
FANOUT_PUBLISHES = "topology.fanout_publishes"
FANOUT_FALLBACKS = "topology.fanout_fallbacks"
# Payload transport (transport/): how redistribution bytes physically
# moved.  collective_ops/collective_bytes count payload transfers the
# device-collective engine carried (bytes are pre-padding payload
# bytes, so KV and collective numbers compare directly);
# kv_ops/kv_bytes the same for the chunked-KV engine (fan-out blob
# publishes ride these too once routed through a Transport);
# fallbacks counts per-op degrades collective→KV (probe said
# collective but the transfer failed or the runtime lost the mesh) —
# the never-wedge contract's visible trace; device_moves counts
# host→device→host payload round-trips the continuous peer-delta leg
# performed; swept_parts counts leaked blob chunk keys reclaimed by
# the publish-path sweep (a publisher killed between meta-key and
# delete leaves parts — the sweep is the regression fix's counter).
# Latency histograms transport.collective_s / transport.kv_s time one
# payload transfer end-to-end (publish→consume on the measuring side).
TRANSPORT_COLLECTIVE_OPS = "transport.collective_ops"
TRANSPORT_COLLECTIVE_BYTES = "transport.collective_bytes"
TRANSPORT_KV_OPS = "transport.kv_ops"
TRANSPORT_KV_BYTES = "transport.kv_bytes"
TRANSPORT_FALLBACKS = "transport.fallbacks"
TRANSPORT_DEVICE_MOVES = "transport.device_moves"
TRANSPORT_SWEPT_PARTS = "transport.swept_parts"
TRANSPORT_COLLECTIVE_S = "transport.collective_s"
TRANSPORT_KV_S = "transport.kv_s"
# Continuous per-step checkpointing (continuous/): every training
# step's changed chunks replicate to a peer host's RAM.  steps counts
# step() calls that ran; bytes/chunks replicated vs skipped is the
# per-step delta win (skipped = content the targets already held);
# step_overhead_s is the BLOCKED window inside step() (digest + delta
# staging — the seconds the training loop actually lost, also folded
# into goodput.overhead_fraction); replication_lag_s is step-begin →
# all-targets-complete (the at-risk window: a host killed inside it
# loses that one step); replication_lag_steps gauges how far the
# background writer trails the training loop; replication_errors
# counts steps whose replication failed (training continues — the peer
# simply keeps the previous step); restore_s is the measured
# recovery-time objective of recover(), per source; preemption_drains
# counts SIGTERM grace-window drains that completed.
CONTINUOUS_STEPS = "continuous.steps"
CONTINUOUS_BYTES_REPLICATED = "continuous.bytes_replicated"
CONTINUOUS_BYTES_SKIPPED = "continuous.bytes_skipped"
CONTINUOUS_CHUNKS_REPLICATED = "continuous.chunks_replicated"
CONTINUOUS_CHUNKS_SKIPPED = "continuous.chunks_skipped"
CONTINUOUS_STEP_OVERHEAD_S = "continuous.step_overhead_s"
CONTINUOUS_REPLICATION_LAG_S = "continuous.replication_lag_s"
CONTINUOUS_REPLICATION_LAG_STEPS = "continuous.replication_lag_steps"
CONTINUOUS_REPLICATION_ERRORS = "continuous.replication_errors"
CONTINUOUS_PROMOTIONS = "continuous.promotions"
CONTINUOUS_RESTORES_FROM_LOCAL = "continuous.restores_from_local"
CONTINUOUS_RESTORES_FROM_PEER = "continuous.restores_from_peer"
CONTINUOUS_RESTORES_FROM_DURABLE = "continuous.restores_from_durable"
CONTINUOUS_RESTORE_S = "continuous.restore_s"
CONTINUOUS_PREEMPTION_DRAINS = "continuous.preemption_drains"
# Live weight publication (publish/): the training→serving hot-swap
# channel.  Publisher side: records counts publication records
# committed (marker-last), bytes/chunks_delta the NEW bytes/chunks
# this record introduced vs the previous one (the wire cost of one
# update), announce_failures the best-effort KV announces that failed
# (subscribers degrade to durable polling — this counter is the only
# trace).  Subscriber side: swaps counts completed generation bumps,
# bytes/chunks_fetched the actual delta traffic, chunks_reused the
# chunks the held generation already had (the savings), lag_s is
# record-publish-time → swap-complete (the propagation lag a serving
# fleet cares about), apply_s the staged-apply + swap wall time,
# fallback_polls counts durable-poll wake-ups that found a new record
# the announce channel never delivered, watch_errors counts watcher
# iterations that failed and were retried (degrade-never-wedge),
# leaves_skipped counts record leaves a subscriber could not apply
# (template mismatch in non-strict mode) or a publisher could not
# reference (codec'd/sharded sources); generation gauges the
# subscriber's current swap generation.
PUBLISH_RECORDS = "publish.records"
PUBLISH_BYTES_DELTA = "publish.bytes_delta"
PUBLISH_CHUNKS_DELTA = "publish.chunks_delta"
PUBLISH_ANNOUNCE_FAILURES = "publish.announce_failures"
PUBLISH_SUB_SWAPS = "publish.subscriber_swaps"
PUBLISH_SUB_BYTES_FETCHED = "publish.subscriber_bytes_fetched"
PUBLISH_SUB_CHUNKS_FETCHED = "publish.subscriber_chunks_fetched"
PUBLISH_SUB_CHUNKS_REUSED = "publish.subscriber_chunks_reused"
PUBLISH_SUB_LAG_S = "publish.subscriber_lag_s"
PUBLISH_SUB_APPLY_S = "publish.subscriber_apply_s"
PUBLISH_FALLBACK_POLLS = "publish.fallback_polls"
PUBLISH_WATCH_ERRORS = "publish.watch_errors"
PUBLISH_LEAVES_SKIPPED = "publish.leaves_skipped"
PUBLISH_GENERATION = "publish.generation"
# Resilience (resilience/): transient-error retries (total, plus
# per-backend twins named resilience.<backend>.retries), cross-rank
# aborts initiated via the poison protocol, deterministic failpoint
# fires, circuit-breaker trips (closed->open transitions; per-backend
# state gauges are named resilience.breaker_state.<backend>: 0 closed,
# 1 half-open, 2 open), and the backoff-delay histogram.
RESILIENCE_RETRIES = "resilience.retries"
RESILIENCE_ABORTS = "resilience.aborts"
RESILIENCE_FAILPOINTS_FIRED = "resilience.failpoints_fired"
RESILIENCE_BREAKER_TRIPS = "resilience.breaker_trips"
RESILIENCE_BACKOFF_DELAY_S = "resilience.backoff_delay_s"
# Rank liveness + write takeover (resilience/liveness.py,
# snapshot take recovery): heartbeat stamps published, peer ranks
# declared dead (stamp frozen past LIVENESS_TIMEOUT_S — each rank
# counts its own observations), replicated objects/bytes a survivor
# re-wrote on behalf of a dead writer, commits that landed with a
# `degraded` manifest section (sharded-only loss), degraded paths
# healed back to complete (next take / SnapshotManager.repair), and
# dead peers the tier promoter's done-handshake skipped instead of
# wedging on.
LIVENESS_HEARTBEATS = "liveness.heartbeats"
LIVENESS_DEAD_RANKS = "liveness.dead_ranks"
TAKEOVER_OBJECTS = "takeover.objects"
TAKEOVER_BYTES = "takeover.bytes"
TAKEOVER_DEGRADED_COMMITS = "takeover.degraded_commits"
TAKEOVER_PATHS_REPAIRED = "takeover.paths_repaired"
TAKEOVER_PROMOTER_DEAD_PEERS = "takeover.promoter_dead_peers"
# Exception hygiene (tools/lint exception-hygiene pass): every
# deliberate broad-except swallow on a fallback path increments this
# via obs.swallowed_exception, so "how often are we falling back" is a
# dashboard number instead of an invisible `pass`.
EXCEPTIONS_SWALLOWED = "exceptions.swallowed"
# Registered event handlers that raised from the log_event fan-out
# (the handler error is logged and suppressed so telemetry can never
# break the operation it observes — this counter keeps the failure
# visible).
EVENT_HANDLER_ERRORS = "events.handler_errors"


class Counter:
    """Monotonically-increasing integer."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def _reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Last-set value plus its high-water mark (``max``) since reset —
    the high-water is what budget/queue-depth gauges exist for."""

    __slots__ = ("name", "_value", "_max", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v
            if v > self._max:
                self._max = v

    def set_max(self, v: float) -> None:
        """Record ``v`` only as a high-water candidate (value untouched)."""
        with self._lock:
            if v > self._max:
                self._max = v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    @property
    def max(self) -> float:
        with self._lock:
            return self._max

    def _reset(self) -> None:
        with self._lock:
            self._value = 0.0
            self._max = 0.0


class Histogram:
    """Fixed-bucket histogram: ``bounds`` are inclusive upper edges,
    observations above every bound land in the overflow bucket."""

    __slots__ = ("name", "bounds", "_counts", "_sum", "_min", "_max",
                 "_count", "_lock")

    def __init__(
        self, name: str, bounds: Sequence[float] = LATENCY_BUCKETS_S
    ) -> None:
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError(f"histogram bounds must be strictly increasing: {bounds}")
        self.name = name
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        idx = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "bounds": list(self.bounds),
                "counts": list(self._counts),
            }

    def _reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._min = None
            self._max = None
            self._count = 0


class MetricsRegistry:
    """Name → instrument, get-or-create.  One process-global instance
    (``REGISTRY``); independent registries exist only for tests."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(
        self, name: str, bounds: Sequence[float] = LATENCY_BUCKETS_S
    ) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, bounds)
            return h

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            histograms = list(self._histograms.values())
        return {
            "counters": {c.name: c.value for c in counters},
            "gauges": {
                g.name: {"value": g.value, "max": g.max} for g in gauges
            },
            "histograms": {h.name: h.to_dict() for h in histograms},
        }

    def reset(self) -> None:
        """Zero every instrument (instrument objects stay registered, so
        references held by instrumented code remain live)."""
        with self._lock:
            instruments: List[Any] = [
                *self._counters.values(),
                *self._gauges.values(),
                *self._histograms.values(),
            ]
        for inst in instruments:
            inst._reset()


REGISTRY = MetricsRegistry()


def counter(name: str) -> Counter:
    return REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return REGISTRY.gauge(name)


def histogram(
    name: str, bounds: Sequence[float] = LATENCY_BUCKETS_S
) -> Histogram:
    return REGISTRY.histogram(name, bounds)


def metrics_snapshot() -> Dict[str, Any]:
    return REGISTRY.snapshot()


def reset_metrics() -> None:
    REGISTRY.reset()


def record_storage_io(backend: str, op: str, nbytes: int, seconds: float) -> None:
    """One storage write/read completed: latency histogram + byte counter,
    labeled per backend (``storage.fs.write_latency_s`` …)."""
    REGISTRY.histogram(
        f"storage.{backend}.{op}_latency_s", LATENCY_BUCKETS_S
    ).observe(seconds)
    REGISTRY.counter(f"storage.{backend}.{op}_bytes").inc(nbytes)
