"""Cross-rank metric aggregation and persisted snapshot flight records.

The single-process half of observability (metrics.py, tracer.py) dies
with the process and never crosses a rank boundary; this module is the
distributed, persistent half.  After a take/restore each rank computes
its per-operation **metrics delta** (counters/histograms windowed
against a capture taken at operation start) plus a phase rollup
(``phase.*`` histograms: stage/encode/write/read/consume/barrier
seconds) and publishes it over the coordination KV under explicit keys
(``{uid}/obsrec/{rank}`` — background-thread-legal, no collectives).
Rank 0 merges the payloads — counters summed, histograms bucket-summed,
gauges per-rank — computes **straggler attribution** (which rank, which
phase, per-backend breakdown), and persists the merged record next to
the snapshot as ``.snapshot_obsrecord``:

- written **before** the ``.snapshot_metadata`` commit marker and
  strictly best-effort — a lost record can never fail a commit;
- **self-CRC'd** like the metadata file (trailer comment carrying the
  body crc32), so a truncated/corrupt record is detected, not
  misrendered;
- publication is best-effort per rank (``obs.publish`` failpoint): a
  rank dying between its data writes and its publish degrades the
  record to a partial one with the missing rank NOTED, never blocks
  the commit.

``python -m torchsnapshot_tpu doctor <path>`` renders a record
(slowest ranks/objects/phases, retries, breaker trips, codec ratios,
goodput) and diffs two of them step-over-step.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any, Dict, List, Optional, Sequence

from . import goodput as goodput_mod
from . import tracer as tracer_mod
from .metrics import PHASE_PREFIX, metrics_snapshot
from ..utils.selfcrc import append_crc_trailer, strip_crc_trailer

logger = logging.getLogger(__name__)

OBSRECORD_FNAME = ".snapshot_obsrecord"
RECORD_VERSION = 1

# Self-checksum trailer, same construction as the metadata file's
# (manifest._META_CRC_MARKER): newline + '#' can never occur inside the
# JSON body (json.dumps escapes newlines), and a plain-JSON/YAML reader
# treats the trailer as trailing garbage/comment rather than data.
_RECORD_CRC_MARKER = "\n#tsnp-obsrecord-crc32:"

# How long rank 0 waits for one rank's payload AFTER the commit barrier
# already proved the rank finished its writes: the payload was published
# before the barrier, so anything still missing is a failed (best-effort)
# publish, not an in-flight one — keep the wait short.
_COLLECT_TIMEOUT_S = 5.0

# Slowest-object rollup: only available when tracing recorded the
# operation's pipeline spans; bounded so the record stays small.
_TOP_OBJECTS = 10
_OBJECT_SPAN_NAMES = ("pipeline/io", "pipeline/stream", "pipeline/staging")

# The last merged record of each operation kind, kept in-process so
# restores (which have no natural persistence point next to a snapshot
# they may lack write access to) are still inspectable.
_LAST_RECORDS: Dict[str, Dict[str, Any]] = {}


# --------------------------------------------------------- delta/merge


def capture() -> Dict[str, Any]:
    """Registry capture at operation start; pair with ``delta``."""
    return metrics_snapshot()


def delta(before: Dict[str, Any], after: Dict[str, Any]) -> Dict[str, Any]:
    """Windowed registry view of one operation: counters and histogram
    counts/sums subtract cleanly; gauges cannot be windowed (their
    value/high-water is as-of-capture) and are carried from ``after``
    verbatim.  Instruments born mid-window delta against zero."""
    b_counters = before.get("counters", {})
    counters = {
        name: v - b_counters.get(name, 0)
        for name, v in after.get("counters", {}).items()
        if v - b_counters.get(name, 0)
    }
    b_hists = before.get("histograms", {})
    histograms = {}
    for name, h in after.get("histograms", {}).items():
        bh = b_hists.get(name)
        if bh is not None and bh.get("bounds") == h.get("bounds"):
            d = {
                "count": h["count"] - bh["count"],
                "sum": h["sum"] - bh["sum"],
                # min/max are process-lifetime (not windowable)
                "min": h["min"],
                "max": h["max"],
                "bounds": h["bounds"],
                "counts": [
                    a - b for a, b in zip(h["counts"], bh["counts"])
                ],
            }
        else:
            d = dict(h)
        if d["count"]:
            histograms[name] = d
    return {
        "counters": counters,
        "gauges": dict(after.get("gauges", {})),
        "histograms": histograms,
    }


def _phase_rollup(metrics: Dict[str, Any]) -> Dict[str, Dict[str, float]]:
    """phase name → {seconds, count} from the ``phase.*`` histograms of
    one rank's delta."""
    out: Dict[str, Dict[str, float]] = {}
    for name, h in metrics.get("histograms", {}).items():
        if name.startswith(PHASE_PREFIX) and h.get("count"):
            phase = name[len(PHASE_PREFIX):]
            if phase.endswith("_s"):
                phase = phase[:-2]
            out[phase] = {
                "seconds": round(float(h.get("sum", 0.0)), 6),
                "count": int(h.get("count", 0)),
            }
    return out


def _backend_rollup(metrics: Dict[str, Any]) -> Dict[str, Dict[str, float]]:
    """backend → {write_s, read_s, write_bytes, read_bytes} from the
    per-backend storage instruments of one rank's delta."""
    out: Dict[str, Dict[str, float]] = {}
    for name, h in metrics.get("histograms", {}).items():
        if not name.startswith("storage.") or not name.endswith(
            "_latency_s"
        ):
            continue
        parts = name.split(".")
        if len(parts) != 3:
            continue  # storage.stripe.part_* / storage.codec.* rollups
        backend, op = parts[1], parts[2][: -len("_latency_s")]
        if h.get("count"):
            out.setdefault(backend, {})[f"{op}_s"] = round(
                float(h.get("sum", 0.0)), 6
            )
    for name, v in metrics.get("counters", {}).items():
        if name.startswith("storage.") and name.endswith(
            ("write_bytes", "read_bytes")
        ):
            parts = name.split(".")
            if len(parts) == 3 and v:
                out.setdefault(parts[1], {})[parts[2]] = v
    return out


def _slow_objects_from_tracer() -> List[Dict[str, Any]]:
    """Top-N slowest per-object pipeline spans (path + phase + seconds)
    when tracing recorded the operation; [] when tracing is off — the
    record notes object-level detail is span-gated."""
    if not tracer_mod.ENABLED:
        return []
    spans = [
        s
        for s in tracer_mod.get_tracer().spans()
        if s.name in _OBJECT_SPAN_NAMES and s.end_ns and "path" in s.attrs
    ]
    spans.sort(key=lambda s: s.duration_ns, reverse=True)
    return [
        {
            "path": str(s.attrs.get("path")),
            "phase": s.name.rsplit("/", 1)[-1],
            "seconds": round(s.duration_ns / 1e9, 6),
            "bytes": s.attrs.get("bytes"),
        }
        for s in spans[:_TOP_OBJECTS]
    ]


def _topology_stamp() -> Optional[Dict[str, Any]]:
    """This process's last-detected topology placement, or None (never
    raises — the stamp is flight-record garnish)."""
    try:
        from ..topology import current_topology_info

        return current_topology_info()
    except Exception as e:  # noqa: BLE001 — telemetry never fails the op
        from .. import obs

        obs.swallowed_exception("obs.aggregate.topology_stamp", e)
        return None


# counter name → per-slice rollup field for the topology record rows
_TOPOLOGY_SLICE_COUNTERS = (
    ("topology.replicated_objects_written", "replicated_objects_written"),
    ("topology.replicated_bytes_written", "replicated_bytes_written"),
    ("topology.fanout_durable_reads", "durable_reads"),
    ("topology.durable_gets_saved", "durable_gets_saved"),
    ("topology.fanout_bytes_redistributed", "bytes_redistributed"),
    ("topology.fanout_fallbacks", "fanout_fallbacks"),
)


def _topology_rollup(
    payloads: Sequence[Dict[str, Any]]
) -> Optional[Dict[str, Any]]:
    """Per-slice rows (ranks, write egress, fan-out savings) from the
    payloads' topology stamps + counter deltas; None when no rank
    reported a placement."""
    stamped = [
        p for p in payloads if isinstance(p.get("topology"), dict)
    ]
    if not stamped:
        return None
    slices: Dict[str, Dict[str, Any]] = {}
    for p in stamped:
        s = str(p["topology"].get("slice", 0))
        row = slices.setdefault(
            s,
            {"ranks": [], **{field: 0 for _, field in _TOPOLOGY_SLICE_COUNTERS}},
        )
        row["ranks"].append(int(p["rank"]))
        counters = (p.get("metrics") or {}).get("counters") or {}
        for name, field in _TOPOLOGY_SLICE_COUNTERS:
            row[field] += int(counters.get(name, 0))
    for row in slices.values():
        row["ranks"].sort()
    return {
        "num_slices": max(
            int(p["topology"].get("num_slices", 1)) for p in stamped
        ),
        "slices": dict(sorted(slices.items(), key=lambda kv: int(kv[0]))),
    }


def _transport_stamp(
    metric_delta: Dict[str, Any]
) -> Optional[Dict[str, Any]]:
    """The payload-transport engine this rank's operation selected plus
    its per-op byte/fallback deltas, or None when transport was never
    resolved — never raises (flight-record garnish, same contract as
    the topology stamp).  The engine name makes per-op selection
    auditable from the flight record alone: a fleet that silently
    degraded to KV shows ``engine: kv`` (or a nonzero ``fallbacks``)
    on the affected ranks."""
    try:
        from ..transport import current_engine

        engine = current_engine()
        if engine is None:
            return None
        c = metric_delta.get("counters", {})
        return {
            "engine": engine,
            "collective_ops": int(c.get("transport.collective_ops", 0)),
            "collective_bytes": int(
                c.get("transport.collective_bytes", 0)
            ),
            "kv_ops": int(c.get("transport.kv_ops", 0)),
            "kv_bytes": int(c.get("transport.kv_bytes", 0)),
            "fallbacks": int(c.get("transport.fallbacks", 0)),
            "device_moves": int(c.get("transport.device_moves", 0)),
        }
    except Exception as e:  # noqa: BLE001 — telemetry never fails the op
        from .. import obs

        obs.swallowed_exception("obs.aggregate.transport_stamp", e)
        return None


def _continuous_stamp() -> Optional[Dict[str, Any]]:
    """The active continuous checkpointer's rollup (continuous/loop.py
    summary_block), or None — never raises (flight-record garnish, same
    contract as the topology stamp)."""
    try:
        from ..continuous import summary_block

        return summary_block()
    except Exception as e:  # noqa: BLE001 — telemetry never fails the op
        from .. import obs

        obs.swallowed_exception("obs.aggregate.continuous_stamp", e)
        return None


def rank_payload(
    rank: int, op: str, before: Dict[str, Any]
) -> Dict[str, Any]:
    """One rank's flight-record contribution for the operation that
    started at the ``before`` capture.  NEVER raises: every call site
    sits on a commit path inside an abort scope, where a latent
    telemetry bug must cost record fidelity, not the checkpoint — a
    failed rollup degrades to a minimal payload noting the error."""
    try:
        m = delta(before, metrics_snapshot())
        out = {
            "rank": rank,
            "op": op,
            "metrics": m,
            "phases": _phase_rollup(m),
            "backends": _backend_rollup(m),
            "goodput": goodput_mod.block(),
            "slow_objects": _slow_objects_from_tracer(),
        }
        # topology stamp (topology/): the rank's slice/host placement
        # lets rank 0 roll per-slice write-egress and fan-out-savings
        # rows without a second exchange
        tinfo = _topology_stamp()
        if tinfo is not None:
            out["topology"] = tinfo
        # continuous-loop stamp (continuous/): replica residency +
        # replication lag for the doctor's preemption-readiness rows
        cinfo = _continuous_stamp()
        if cinfo is not None:
            out["continuous"] = cinfo
        # payload-transport stamp (transport/): which engine this op's
        # redistribution bytes rode, with per-op byte/fallback deltas
        xinfo = _transport_stamp(m)
        if xinfo is not None:
            out["transport"] = xinfo
        return out
    except Exception as e:  # noqa: BLE001 — telemetry never fails the op
        from .. import obs

        obs.swallowed_exception("obs.aggregate.rank_payload", e)
        return {
            "rank": rank,
            "op": op,
            "metrics": {},
            "phases": {},
            "backends": {},
            "goodput": {},
            "slow_objects": [],
            "error": repr(e)[:200],
        }


def _merge_metrics(deltas: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    counters: Dict[str, int] = {}
    gauges: Dict[str, Dict[str, Any]] = {}
    histograms: Dict[str, Dict[str, Any]] = {}
    for d in deltas:
        for name, v in d.get("counters", {}).items():
            counters[name] = counters.get(name, 0) + v
        for name, g in d.get("gauges", {}).items():
            cur = gauges.setdefault(name, {"value": 0.0, "max": 0.0})
            cur["value"] = max(cur["value"], g.get("value", 0.0))
            cur["max"] = max(cur["max"], g.get("max", 0.0))
        for name, h in d.get("histograms", {}).items():
            cur = histograms.get(name)
            if cur is None:
                histograms[name] = {
                    k: (list(v) if isinstance(v, list) else v)
                    for k, v in h.items()
                }
                continue
            if cur.get("bounds") != h.get("bounds"):
                # bound skew across ranks (version mismatch): keep the
                # first rank's histogram rather than sum apples+oranges
                continue
            cur["count"] += h["count"]
            cur["sum"] += h["sum"]
            cur["counts"] = [
                a + b for a, b in zip(cur["counts"], h["counts"])
            ]
            for agg, fn in (("min", min), ("max", max)):
                vals = [v for v in (cur.get(agg), h.get(agg)) if v is not None]
                cur[agg] = fn(vals) if vals else None
    return {
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
    }


def _straggler(
    phases_by_rank: Dict[str, Dict[str, Dict[str, float]]]
) -> Optional[Dict[str, Any]]:
    """The rank with the largest total WORK time, attributed to its
    dominant phase; None when no rank reported any work phase.

    Barrier seconds are excluded from the totals: barrier time is by
    definition time spent WAITING on other ranks — the fastest rank
    accrues the most of it while the real straggler works, so counting
    it would name the victim.  It stays visible in the per-rank table;
    it just never wins the attribution."""
    def work(phases):
        return {
            name: p for name, p in phases.items() if name != "barrier"
        }

    totals = {
        r: sum(p["seconds"] for p in work(phases).values())
        for r, phases in phases_by_rank.items()
        if work(phases)
    }
    if not totals:
        return None
    worst = max(totals, key=totals.get)
    phases = work(phases_by_rank[worst])
    phase = max(phases, key=lambda p: phases[p]["seconds"])
    others = [s for r, s in totals.items() if r != worst]
    return {
        "rank": int(worst),
        "phase": phase,
        "seconds": round(phases[phase]["seconds"], 6),
        "total_s": round(totals[worst], 6),
        "lead_over_peers_s": round(
            totals[worst] - (max(others) if others else 0.0), 6
        ),
    }


def merge_payloads(
    payloads: Sequence[Dict[str, Any]],
    op: str,
    path: str,
    world_size: int,
) -> Dict[str, Any]:
    """The merged flight record: summed counters, merged histograms,
    per-rank phase/backend rollups, straggler attribution, fleet
    goodput, and the slowest objects across all reporting ranks.
    ``payloads`` may be partial — absent ranks land in
    ``missing_ranks`` and every rollup is computed over what arrived."""
    payloads = [p for p in payloads if p]
    reported = sorted(int(p["rank"]) for p in payloads)
    phases_by_rank = {
        str(p["rank"]): p.get("phases", {}) for p in payloads
    }
    goodputs = {
        str(p["rank"]): p.get("goodput", {}) for p in payloads
    }
    slow = sorted(
        (o for p in payloads for o in p.get("slow_objects", ())),
        key=lambda o: o.get("seconds", 0.0),
        reverse=True,
    )[:_TOP_OBJECTS]
    merged_goodput: Dict[str, Any] = {"by_rank": goodputs}
    for key in (
        "time_to_unblock_s",
        "durability_lag_s",
        "overhead_fraction",
    ):
        vals = [
            g[key]
            for g in goodputs.values()
            if isinstance(g.get(key), (int, float))
        ]
        # the fleet unblocks when the SLOWEST rank does
        merged_goodput[key] = round(max(vals), 6) if vals else None
    record = {
        "record": "tsnp-obsrecord",
        "version": RECORD_VERSION,
        "op": op,
        "path": path,
        "world_size": world_size,
        "ranks_reported": reported,
        "missing_ranks": sorted(set(range(world_size)) - set(reported)),
        "merged": _merge_metrics([p.get("metrics", {}) for p in payloads]),
        "per_rank": {
            str(p["rank"]): {
                "phases": p.get("phases", {}),
                "backends": p.get("backends", {}),
            }
            for p in payloads
        },
        "straggler": _straggler(phases_by_rank),
        "goodput": merged_goodput,
        "slow_objects": slow,
    }
    topology = _topology_rollup(payloads)
    if topology is not None:
        record["topology"] = topology
    continuous = _continuous_rollup(payloads)
    if continuous is not None:
        record["continuous"] = continuous
    return record


def _continuous_rollup(
    payloads: Sequence[Dict[str, Any]]
) -> Optional[Dict[str, Any]]:
    """Fleet continuous-checkpoint rows: per-rank residency plus the
    fleet's weakest guarantees (the MIN over ranks of last-peer and
    last-durable steps — a preemption can hit any host, so the floor is
    what matters); None when no rank runs a continuous loop."""
    stamped = [
        p for p in payloads if isinstance(p.get("continuous"), dict)
    ]
    if not stamped:
        return None
    by_rank = {str(p["rank"]): p["continuous"] for p in stamped}

    def _floor(key: str) -> Optional[int]:
        vals = [
            c.get(key)
            for c in by_rank.values()
            if isinstance(c.get(key), int)
        ]
        return min(vals) if vals else None

    lags = [
        c.get("replication_lag_steps")
        for c in by_rank.values()
        if isinstance(c.get("replication_lag_steps"), int)
    ]
    return {
        "by_rank": by_rank,
        "last_peer_step_floor": _floor("last_peer_step"),
        "last_durable_step_floor": _floor("last_durable_step"),
        "max_replication_lag_steps": max(lags) if lags else None,
    }


# ------------------------------------------------------ KV publication


def _obsrec_key(uid: str, rank: int) -> str:
    return f"{uid}/obsrec/{rank}"


def publish(coordinator: Any, uid: str, payload: Dict[str, Any]) -> bool:
    """Best-effort publication of this rank's payload under the
    operation uid.  Never raises: a failed publish (the ``obs.publish``
    failpoint, a dead KV) degrades the merged record to a partial one —
    it must not fail a take whose data writes all landed."""
    from .. import obs
    from ..resilience.failpoints import failpoint

    with obs.span("obs/publish", rank=coordinator.rank, uid=uid):
        try:
            failpoint("obs.publish", rank=coordinator.rank)
            if coordinator.world_size == 1:
                _LAST_RECORDS[f"_local/{uid}"] = payload
            else:
                coordinator.kv_set(
                    _obsrec_key(uid, coordinator.rank),
                    json.dumps(payload),
                )
            return True
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            obs.swallowed_exception("obs.aggregate.publish", e)
            return False


def collect_and_merge(
    coordinator: Any, uid: str, op: str, path: str
) -> Dict[str, Any]:
    """Rank 0's half of ``exchange``: gather whatever payloads were
    published under ``uid`` and merge them.  Called strictly AFTER the
    commit barrier proved every surviving rank finished (so a short
    per-rank wait suffices); ranks that never published are noted in
    the record, never waited on for long."""
    payloads: List[Dict[str, Any]] = []
    world = coordinator.world_size
    if world == 1:
        local = _LAST_RECORDS.pop(f"_local/{uid}", None)
        if local is not None:
            payloads.append(local)
    else:
        # ONE shared deadline for all ranks, not one per missing rank:
        # a systematic publish failure must cost at most one collect
        # window before the commit proceeds, never world_size windows
        # (which would outwait the commit barrier at fleet scale)
        raws: Dict[int, Optional[str]] = {
            r: coordinator.kv_try_get(_obsrec_key(uid, r))
            for r in range(world)
        }
        deadline = time.monotonic() + _COLLECT_TIMEOUT_S
        while any(v is None for v in raws.values()) and (
            time.monotonic() < deadline
        ):
            # bounded poll: KV propagation may trail the barrier on
            # real coordination services
            time.sleep(0.05)
            for r, v in raws.items():
                if v is None:
                    raws[r] = coordinator.kv_try_get(_obsrec_key(uid, r))
        for r in range(world):
            raw = raws[r]
            if raw is None:
                continue
            try:
                payloads.append(json.loads(raw))
            except (ValueError, TypeError) as e:
                from .. import obs

                obs.swallowed_exception("obs.aggregate.decode", e)
    record = merge_payloads(payloads, op=op, path=path, world_size=world)
    _LAST_RECORDS[op] = record
    return record


def exchange_and_merge(
    coordinator: Any,
    uid: str,
    payload: Dict[str, Any],
    op: str,
    path: str,
) -> Optional[Dict[str, Any]]:
    """Publish this rank's payload and, on rank 0, merge everything
    published so far (single-phase convenience for call sites that have
    already synchronized — restore's tail).  Returns the merged record
    on rank 0, None elsewhere."""
    from .. import obs

    with obs.span("obs/exchange_and_merge", uid=uid, op=op):
        publish(coordinator, uid, payload)
        if coordinator.rank != 0:
            return None
        try:
            return collect_and_merge(coordinator, uid, op=op, path=path)
        except Exception as e:  # noqa: BLE001 — telemetry never fails the op
            obs.swallowed_exception("obs.aggregate.exchange", e)
            return None


def last_record(op: str) -> Optional[Dict[str, Any]]:
    """The most recent merged record of kind ``op`` in this process
    (rank 0 only fills these)."""
    return _LAST_RECORDS.get(op)


# ------------------------------------------------------- persistence


def encode_record(record: Dict[str, Any]) -> bytes:
    """Serialize with the self-checksum trailer (same discipline — and
    same shared implementation, ``utils/selfcrc.py`` — as
    ``.snapshot_metadata``: the record explains incidents, so it must
    be able to vouch for its own bytes)."""
    return append_crc_trailer(
        json.dumps(record, sort_keys=True), _RECORD_CRC_MARKER
    ).encode()


def decode_record(data: bytes) -> Dict[str, Any]:
    """Parse + verify a ``.snapshot_obsrecord``; raises ``RuntimeError``
    on checksum mismatch, a mangled trailer, or structural garbage."""
    s = bytes(data).decode()
    s, _ = strip_crc_trailer(
        s, _RECORD_CRC_MARKER, "obsrecord", ".snapshot_obsrecord"
    )
    try:
        record = json.loads(s)
    except ValueError as e:
        raise RuntimeError(
            f".snapshot_obsrecord is not parseable: {e}"
        ) from e
    if not isinstance(record, dict) or record.get("record") != "tsnp-obsrecord":
        raise RuntimeError(
            ".snapshot_obsrecord has an unexpected structure "
            "(not a flight record)"
        )
    return record


def write_obsrecord(storage: Any, record: Dict[str, Any]) -> bool:
    """Best-effort persistence next to the snapshot, BEFORE the caller
    writes the metadata commit marker.  Never raises — a take whose
    data is durable must commit even when its trace record cannot be
    written."""
    from .. import obs
    from ..io_types import WriteIO

    with obs.span("obs/write_obsrecord", path=record.get("path")):
        try:
            storage.sync_write(
                WriteIO(path=OBSRECORD_FNAME, buf=encode_record(record))
            )
            return True
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            obs.swallowed_exception("obs.aggregate.write_obsrecord", e)
            return False


def read_obsrecord(path: str, storage_options: Any = None) -> Dict[str, Any]:
    """Load + verify the flight record stored next to a snapshot (the
    ``doctor`` CLI's entry point)."""
    from .. import obs
    from ..io_types import ReadIO
    from ..storage import url_to_storage_plugin

    with obs.span("obs/read_obsrecord", path=path):
        storage = (
            url_to_storage_plugin(path, storage_options)
            if storage_options
            else url_to_storage_plugin(path)
        )
        try:
            read_io = ReadIO(path=OBSRECORD_FNAME)
            storage.sync_read(read_io)
        except FileNotFoundError as e:
            raise FileNotFoundError(
                f"no {OBSRECORD_FNAME} under {path!r} — the snapshot was "
                f"taken before flight records existed, or the record's "
                f"best-effort write failed"
            ) from e
        finally:
            storage.sync_close()
        return decode_record(bytes(memoryview(read_io.buf).cast("B")))
