"""Observability: structured spans, a metrics registry, Perfetto export.

Three layers, one import surface:

- **Spans** (``span``, ``get_tracer``) — opt-in via the
  ``TORCHSNAPSHOT_TPU_TRACE`` knob; zero-cost (one module-flag check,
  no allocation) when disabled.  See ``tracer.py``.
- **Metrics** (``counter``/``gauge``/``histogram``,
  ``metrics_snapshot``) — always on; the instrumented hot path records
  bytes staged/written, budget high-water, queue depths and per-backend
  storage latency.  See ``metrics.py``.
- **Export** (``write_trace``) — dump recorded spans as Chrome
  ``trace_event`` JSON for ui.perfetto.dev.  See ``perfetto.py``.

Operator surface: ``python -m torchsnapshot_tpu stats|trace`` and the
metrics block ``bench.py`` embeds in its BENCH records.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Any

from .metrics import (  # noqa: F401
    CODEC_BYTES_IN,
    GOODPUT_DURABILITY_LAG_S,
    GOODPUT_OVERHEAD_FRACTION,
    GOODPUT_TIME_TO_UNBLOCK_S,
    PHASE_BARRIER_S,
    PHASE_CONSUME_S,
    PHASE_ENCODE_S,
    PHASE_PREFIX,
    PHASE_READ_S,
    PHASE_STAGE_S,
    PHASE_WRITE_S,
    CODEC_BYTES_OUT,
    CODEC_PARTS_DECODED,
    CODEC_PARTS_ENCODED,
    CODEC_PARTS_RAW_FALLBACK,
    BUDGET_BYTES_IN_USE,
    BYTES_DEDUPED,
    BYTES_OFFLOADED,
    BYTES_PROMOTED,
    BYTES_READ,
    BYTES_REPLICATED,
    BYTES_STAGED,
    BYTES_WRITTEN,
    BYTES_BUCKETS,
    CACHE_BYTES_FILLED,
    CACHE_EVICTIONS,
    CACHE_HITS,
    CACHE_MISSES,
    CACHE_SINGLEFLIGHT_WAITS,
    MMAP_BYTES_MAPPED,
    MMAP_READS,
    CAS_BYTES_SHARED,
    CAS_BYTES_SWEPT,
    CAS_BYTES_WRITTEN,
    CAS_CHUNKS_SHARED,
    CAS_CHUNKS_SWEPT,
    CAS_CHUNKS_WRITTEN,
    CAS_FSCKS,
    CONTINUOUS_BYTES_REPLICATED,
    CONTINUOUS_BYTES_SKIPPED,
    CONTINUOUS_CHUNKS_REPLICATED,
    CONTINUOUS_CHUNKS_SKIPPED,
    CONTINUOUS_PREEMPTION_DRAINS,
    CONTINUOUS_PROMOTIONS,
    CONTINUOUS_REPLICATION_ERRORS,
    CONTINUOUS_REPLICATION_LAG_S,
    CONTINUOUS_REPLICATION_LAG_STEPS,
    CONTINUOUS_RESTORE_S,
    CONTINUOUS_RESTORES_FROM_DURABLE,
    CONTINUOUS_RESTORES_FROM_LOCAL,
    CONTINUOUS_RESTORES_FROM_PEER,
    CONTINUOUS_STEP_OVERHEAD_S,
    CONTINUOUS_STEPS,
    EVENT_HANDLER_ERRORS,
    EXCEPTIONS_SWALLOWED,
    FASTIO_BUFFERED_PARTS,
    FASTIO_BYTES_READ,
    FASTIO_BYTES_WRITTEN,
    FASTIO_DIRECT_PARTS,
    FASTIO_DONTNEED_READS,
    FASTIO_FUSED_DIGESTS,
    FASTIO_POOL_WAITS,
    GC_BYTES_RECLAIMED,
    IO_QUEUE_DEPTH,
    LATENCY_BUCKETS_S,
    LIVENESS_DEAD_RANKS,
    LIVENESS_HEARTBEATS,
    PROMOTION_LAG_S,
    REGISTRY,
    RESILIENCE_ABORTS,
    RESILIENCE_BACKOFF_DELAY_S,
    RESILIENCE_BREAKER_TRIPS,
    RESILIENCE_FAILPOINTS_FIRED,
    RESILIENCE_RETRIES,
    RSS_PEAK_DELTA_BYTES,
    SLABS_PACKED,
    STRIPE_ABORTS,
    STRIPE_BYTES_READ,
    STRIPE_BYTES_WRITTEN,
    STRIPE_PART_READ_LATENCY_S,
    STRIPE_PART_WRITE_LATENCY_S,
    STRIPE_PARTS_READ,
    STRIPE_PARTS_WRITTEN,
    STRIPE_READS,
    STRIPE_STREAMED_WRITES,
    STRIPE_WRITES,
    TIER_FAST_CORRUPT,
    TIER_FAST_HITS,
    TIER_FAST_MISSES,
    TIER_FAST_REPAIRS,
    TIER_PEER_HITS,
    TAKEOVER_OBJECTS,
    TAKEOVER_BYTES,
    TAKEOVER_DEGRADED_COMMITS,
    TAKEOVER_PATHS_REPAIRED,
    TAKEOVER_PROMOTER_DEAD_PEERS,
    TOPOLOGY_SLICES,
    TOPOLOGY_REPLICATED_OBJECTS_WRITTEN,
    TOPOLOGY_REPLICATED_BYTES_WRITTEN,
    FANOUT_DURABLE_READS,
    FANOUT_DURABLE_GETS_SAVED,
    FANOUT_BYTES_REDISTRIBUTED,
    FANOUT_PUBLISHES,
    FANOUT_FALLBACKS,
    TRANSPORT_COLLECTIVE_OPS,
    TRANSPORT_COLLECTIVE_BYTES,
    TRANSPORT_KV_OPS,
    TRANSPORT_KV_BYTES,
    TRANSPORT_FALLBACKS,
    TRANSPORT_DEVICE_MOVES,
    TRANSPORT_SWEPT_PARTS,
    TRANSPORT_COLLECTIVE_S,
    TRANSPORT_KV_S,
    PUBLISH_RECORDS,
    PUBLISH_BYTES_DELTA,
    PUBLISH_CHUNKS_DELTA,
    PUBLISH_ANNOUNCE_FAILURES,
    PUBLISH_SUB_SWAPS,
    PUBLISH_SUB_BYTES_FETCHED,
    PUBLISH_SUB_CHUNKS_FETCHED,
    PUBLISH_SUB_CHUNKS_REUSED,
    PUBLISH_SUB_LAG_S,
    PUBLISH_SUB_APPLY_S,
    PUBLISH_FALLBACK_POLLS,
    PUBLISH_WATCH_ERRORS,
    PUBLISH_LEAVES_SKIPPED,
    PUBLISH_GENERATION,
    MetricsRegistry,
    counter,
    gauge,
    histogram,
    metrics_snapshot,
    record_storage_io,
    reset_metrics,
)
from .export import (  # noqa: F401
    export_openmetrics,
    maybe_write_metrics_textfile,
    write_metrics_textfile,
)
from .perfetto import to_trace_events, write_trace  # noqa: F401
from .tracer import (  # noqa: F401
    Span,
    Tracer,
    current_span,
    get_tracer,
    next_flow_id,
    refresh_enabled,
    set_tracing,
    span,
    tracing_enabled,
)

__all__ = [
    "Span",
    "Tracer",
    "span",
    "get_tracer",
    "current_span",
    "tracing_enabled",
    "set_tracing",
    "refresh_enabled",
    "counter",
    "gauge",
    "histogram",
    "metrics_snapshot",
    "reset_metrics",
    "record_storage_io",
    "buf_nbytes",
    "swallowed_exception",
    "instrument_storage",
    "to_trace_events",
    "write_trace",
    "REGISTRY",
    "MetricsRegistry",
    "aggregate",
    "goodput",
    "export_openmetrics",
    "write_metrics_textfile",
    "maybe_write_metrics_textfile",
]

# The distributed/persistent half (cross-rank aggregation + flight
# records) and the goodput tracker are reached as submodules:
# ``obs.aggregate.read_obsrecord(...)``, ``obs.goodput.block()``.
from . import aggregate, goodput  # noqa: E402,F401


_swallow_logger = logging.getLogger(__name__)


def swallowed_exception(site: str, exc: BaseException) -> None:
    """Record a deliberately-swallowed exception on a fallback path:
    one counter increment (``exceptions.swallowed``) plus a debug log
    carrying the site and the exception.  One shared counter, not one
    per site — site names are free-form and must not grow the registry
    unboundedly; per-site attribution lives in the log line.  Cheap
    enough for hot paths (a lock-guarded int add; the log call is lazy
    below DEBUG level)."""
    counter(EXCEPTIONS_SWALLOWED).inc()
    _swallow_logger.debug("swallowed exception at %s: %r", site, exc)


def buf_nbytes(buf: Any) -> int:
    """Byte length of a staged/read buffer, 0 for None.  ``.nbytes``
    first: extension-dtype numpy arrays (bfloat16/fp8 — the primary TPU
    dtypes, handed out raw by read-into plugins) reject
    ``memoryview(...).cast("B")``, and ``len()`` on a multi-dim array
    is the first-dim length, not bytes."""
    if buf is None:
        return 0
    n = getattr(buf, "nbytes", None)
    if isinstance(n, int):
        return n
    try:
        return memoryview(buf).cast("B").nbytes
    except (TypeError, ValueError):
        try:
            return len(buf)
        except TypeError:
            return 0


def instrument_storage(backend: str):
    """Class decorator for ``StoragePlugin`` subclasses: wraps ``write``
    and ``read`` with a (knob-gated) span plus always-on per-backend
    latency/byte metrics.  Subclasses that override ``write``/``read``
    (e.g. fault-injection test doubles) simply shadow the wrapper —
    behavior is unchanged for them."""

    def deco(cls):
        orig_write = cls.write
        orig_read = cls.read

        @functools.wraps(orig_write)
        async def write(self, write_io):
            nbytes = buf_nbytes(write_io.buf)
            with span(
                "storage/write", backend=backend,
                path=write_io.path, bytes=nbytes,
            ):
                t0 = time.perf_counter()
                await orig_write(self, write_io)
                record_storage_io(
                    backend, "write", nbytes, time.perf_counter() - t0
                )

        @functools.wraps(orig_read)
        async def read(self, read_io):
            with span(
                "storage/read", backend=backend, path=read_io.path
            ) as s:
                t0 = time.perf_counter()
                await orig_read(self, read_io)
                nbytes = buf_nbytes(read_io.buf)
                if s is not None:
                    s.attrs["bytes"] = nbytes
                record_storage_io(
                    backend, "read", nbytes, time.perf_counter() - t0
                )

        cls.write = write
        cls.read = read
        # the stripe engine bypasses write() (it drives write_part on a
        # handle) but still labels its per-part metrics by backend
        cls.obs_backend = backend
        return cls

    return deco
