"""Per-part compression codec layer for the stripe engine.

Checkpoint bytes move under an explicit host-memory budget with staging
overlapped against storage I/O (scheduler.py); this module makes
compression a *tenant of that same pipeline* instead of a stage
serialized in front of it: each 64MB part's encode runs on the staging
executor between its raw digest and its ``write_part`` dispatch, so
compression overlaps the storage I/O of earlier parts, and every
compressed byte is a byte not paid for at S3/GCS bandwidth, durable-tier
storage cost, tier-promotion copy time, or many-reader restore fan-in.

Design rules, in dependency order:

- **Digests are computed over the RAW bytes, before encoding.**  Entry
  crc32s, the incremental-dedup objects table, and deep-verify all keep
  today's values bitwise; the *stored* (encoded) digest is recorded
  separately per object so the tier layer's digest-verified fast reads
  keep working against the bytes actually on disk.
- **Every part is an independently-decodable frame** (24-byte header:
  magic + codec id + filter id + raw/encoded lengths), so ranged restore
  and part-parallel reads survive compression — a raw byte range maps
  to the overlapping frames via the manifest's per-object codec table,
  and frames decode concurrently on the read executor.
- **Store-raw is the per-part fallback** whenever the encoded frame
  isn't smaller than the raw bytes by ``CODEC_MIN_RATIO`` — the
  zero-copy value prop survives for incompressible parts (mantissa
  noise, already-compressed blobs), which simply pay one 24-byte header.
- **Codecs are optional dependencies.**  ``zlib`` is stdlib and always
  present; ``zstd``/``lz4`` import lazily (the ``ml_dtypes`` pattern)
  and an unavailable *write*-side codec degrades to ``raw`` with one
  warning, while an unavailable *read*-side codec raises a typed
  ``CodecUnavailableError`` naming it (raw-fallback frames still
  decode).  ``huff`` is the native fastio block-Huffman coder — float
  checkpoint payloads after byte-shuffle preconditioning are
  entropy-bound, which LZ matchers can't exploit; see fastio.cpp.
- **Byte-shuffle preconditioning** groups the i-th byte of every
  element together (filter id == the element stride), turning bf16/f32
  noise into compressible byte planes; ``filter_for_dtype`` picks the
  stride for float dtypes and 0 (none) for bytes/objects/ints.

Integrity model: frames carry lengths, not checksums — corruption
inside an encoded payload surfaces as a decode failure or as a
raw-digest mismatch at the verify layers (manifest entry crc32s, the
tier plugin's stored-digest check), exactly where raw payloads'
corruption already surfaces.
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct
import threading
import time
import weakref
import zlib
from concurrent.futures import Executor
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import knobs, obs
from .io_types import ReadIO, StoragePlugin, resolve_read_destination
from .resilience import classify_generic, retry_call
from .resilience.failpoints import failpoint
from .resilience.retry import SharedProgress

logger = logging.getLogger(__name__)

# ----------------------------------------------------------------- frame

FRAME_MAGIC = b"TSCF"
FRAME_VERSION = 1
# magic(4) + version(1) + codec_id(1) + filter_id(1) + reserved(1)
# + raw_len(u64le) + enc_len(u64le)
FRAME_HEADER_BYTES = 24
_HEADER = struct.Struct("<4sBBBBQQ")

CODEC_IDS: Dict[str, int] = {
    "raw": 0,
    "zlib": 1,
    "zstd": 2,
    "lz4": 3,
    "huff": 4,
}
_ID_TO_NAME = {v: k for k, v in CODEC_IDS.items()}


class CodecError(IOError):
    """Base for codec-layer failures."""


class CodecFrameError(CodecError):
    """A frame failed structural validation: bad magic/version, a
    truncated payload, a codec/filter id outside the registry, or a
    decode that produced the wrong byte count."""


class CodecUnavailableError(CodecError):
    """The frame names a codec this host cannot decode (optional
    dependency not installed / native extension not built)."""

    def __init__(self, codec: str, detail: str = "") -> None:
        self.codec = codec
        super().__init__(
            f"codec {codec!r} is not available on this host{detail} — "
            f"install it to restore this snapshot (raw-fallback parts "
            f"decode regardless)"
        )


# -------------------------------------------------------------- registry


def _zlib_compress(view: memoryview, level: int) -> bytes:
    # zlib accepts any C-contiguous buffer: no bytes() staging copy
    return zlib.compress(view, level if 1 <= level <= 9 else 1)


def _zlib_decompress(view: memoryview, raw_len: int) -> bytes:
    return zlib.decompress(view)


def _zstd_mod():
    try:
        import zstandard

        return zstandard
    except ImportError:
        return None


def _zstd_compress(view: memoryview, level: int) -> bytes:
    # zstd/lz4/zlib all take buffer-protocol objects directly: a 64MB
    # part must not pay a GIL-held bytes() staging memcpy per encode
    zstandard = _zstd_mod()
    return zstandard.ZstdCompressor(
        level=level if level else 3
    ).compress(view)


def _zstd_decompress(view: memoryview, raw_len: int) -> bytes:
    zstandard = _zstd_mod()
    return zstandard.ZstdDecompressor().decompress(
        view, max_output_size=raw_len
    )


def _lz4_mod():
    try:
        import lz4.frame

        return lz4.frame
    except ImportError:
        return None


def _lz4_compress(view: memoryview, level: int) -> bytes:
    return _lz4_mod().compress(view, compression_level=level)


def _lz4_decompress(view: memoryview, raw_len: int) -> bytes:
    return _lz4_mod().decompress(view)


def _huff_compress(view: memoryview, level: int) -> bytes:
    # encode_frame's huff fast path builds the frame in place
    # (headroom=FRAME_HEADER_BYTES) and bypasses this entry; it exists
    # so the registry stays uniform — a generic caller gets the same
    # bare stream the fast path frames
    from . import _csrc

    out = _csrc.huff_compress(view)
    if out is None:  # availability is checked before compress is called
        raise CodecUnavailableError("huff", " (native fastio lib absent)")
    return out


def _huff_decompress(view: memoryview, raw_len: int) -> bytes:
    from . import _csrc

    try:
        out = _csrc.huff_decompress(view, raw_len)
    except ValueError as e:
        raise CodecFrameError(f"corrupt huff frame payload: {e}") from e
    if out is None:
        raise CodecUnavailableError("huff", " (native fastio lib absent)")
    return out


def _huff_available() -> bool:
    from . import _csrc

    return _csrc.huff_available()


class _Codec:
    __slots__ = ("name", "codec_id", "_compress", "_decompress", "_avail")

    def __init__(self, name, compress, decompress, avail) -> None:
        self.name = name
        self.codec_id = CODEC_IDS[name]
        self._compress = compress
        self._decompress = decompress
        self._avail = avail

    def available(self) -> bool:
        return self._avail()

    def compress(self, view: memoryview, level: int) -> bytes:
        return self._compress(view, level)

    def decompress(self, view: memoryview, raw_len: int) -> bytes:
        return self._decompress(view, raw_len)


_REGISTRY: Dict[str, _Codec] = {
    "zlib": _Codec("zlib", _zlib_compress, _zlib_decompress, lambda: True),
    "zstd": _Codec(
        "zstd", _zstd_compress, _zstd_decompress,
        lambda: _zstd_mod() is not None,
    ),
    "lz4": _Codec(
        "lz4", _lz4_compress, _lz4_decompress,
        lambda: _lz4_mod() is not None,
    ),
    "huff": _Codec("huff", _huff_compress, _huff_decompress, _huff_available),
}


def available_codecs() -> List[str]:
    """Codec names usable on this host, ``raw`` first."""
    return ["raw"] + [n for n, c in _REGISTRY.items() if c.available()]


_warned_unavailable: set = set()
_warned_lock = threading.Lock()  # resolve runs from loop + executors


def resolve_codec(name: Optional[str] = None) -> str:
    """Resolve the write-side codec: the argument, else the CODEC knob.
    Unknown or unavailable codecs degrade to ``raw`` with one warning —
    a typo'd env var or a missing optional dependency must never fail a
    take (compression is an optimization, not a correctness
    dependency)."""
    name = (name or knobs.get_codec()).lower()
    if name == "raw":
        return "raw"
    codec = _REGISTRY.get(name)
    if codec is None or not codec.available():
        with _warned_lock:
            first = name not in _warned_unavailable
            _warned_unavailable.add(name)
        if first:
            why = "unknown codec" if codec is None else "not installed"
            logger.warning(
                "TORCHSNAPSHOT_TPU_CODEC=%r %s (available: %s); writing "
                "raw", name, why, ",".join(available_codecs()),
            )
        return "raw"
    return name


# --------------------------------------------------------------- filters

# dtypes whose byte planes separate well: float formats, where the
# exponent/sign bytes are low-entropy and the mantissa bytes are noise.
# Ints/bytes/objects keep filter 0 — shuffling random bytes or text
# mostly just costs a pass.
_FLOAT_ITEMSIZE = {
    "float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
}


def filter_for_dtype(dtype_str: Optional[str]) -> int:
    """Byte-shuffle stride for a manifest dtype string (0 = no filter)."""
    if not dtype_str:
        return 0
    return _FLOAT_ITEMSIZE.get(dtype_str.lower(), 0)


def shuffle(view: memoryview, stride: int):
    """Byte-shuffle: group byte plane i of every ``stride``-sized
    element together.  A tail shorter than one element (a part span not
    aligned to the itemsize) is appended unshuffled — the operation
    stays self-inverse per frame regardless of alignment.

    Returns a bytes-like object (the native path hands back a uint8
    array with no extra copy; the numpy fallback returns bytes) — this
    is the encode hot path, one call per 64MB part on the staging
    executor, so the native transpose matters twice: it skips a copy
    and it runs outside the GIL, letting part encodes actually
    parallelize across executor threads."""
    from . import _csrc

    out = _csrc.byte_shuffle(view, stride)
    if out is not None:
        return out
    import numpy as np

    n = view.nbytes
    body = n - (n % stride)
    arr = np.frombuffer(view, dtype=np.uint8, count=body)
    res = np.ascontiguousarray(
        arr.reshape(-1, stride).T
    ).tobytes()
    if body != n:
        res += bytes(view[body:])
    return res


def unshuffle(view: memoryview, stride: int):
    """Inverse of ``shuffle``; bytes-like (the native path returns the
    coder's uint8 array as-is — a 64MB decode must not pay a tobytes
    memcpy per frame on the restore hot path)."""
    from . import _csrc

    out = _csrc.byte_shuffle(view, stride, inverse=True)
    if out is not None:
        return out
    import numpy as np

    n = view.nbytes
    body = n - (n % stride)
    arr = np.frombuffer(view, dtype=np.uint8, count=body)
    res = np.ascontiguousarray(
        arr.reshape(stride, -1).T
    ).tobytes()
    if body != n:
        res += bytes(view[body:])
    return res


# ------------------------------------------------------------- metrics

CODEC_BYTES_IN = obs.CODEC_BYTES_IN
CODEC_BYTES_OUT = obs.CODEC_BYTES_OUT
CODEC_PARTS_RAW_FALLBACK = obs.CODEC_PARTS_RAW_FALLBACK
CODEC_PARTS_ENCODED = obs.CODEC_PARTS_ENCODED
CODEC_PARTS_DECODED = obs.CODEC_PARTS_DECODED


def _enc_hist(name: str):
    return obs.histogram(f"storage.codec.encode_latency_s.{name}")


def _dec_hist(name: str):
    return obs.histogram(f"storage.codec.decode_latency_s.{name}")


# --------------------------------------------------------- write spec


class WriteSpec:
    """Resolved write-side codec parameters, read once per pipeline run
    (CODEC=raw resolves to ``None`` at the call site, so the disabled
    path costs one knob read per take and nothing per part)."""

    __slots__ = ("codec", "level", "min_ratio")

    def __init__(self, codec: str, level: int, min_ratio: float) -> None:
        self.codec = codec
        self.level = level
        self.min_ratio = min_ratio


def resolve_write_spec() -> Optional[WriteSpec]:
    """The active write-side spec, or None when the codec resolves to
    raw (the zero-overhead disabled path)."""
    name = resolve_codec()
    if name == "raw":
        return None
    return WriteSpec(
        name, knobs.get_codec_level(), knobs.get_codec_min_ratio()
    )


# ------------------------------------------------------ frame encode


def _count_encode(
    codec_name: str, raw_len: int, frame_len: int, fallback: bool, dt: float
) -> None:
    """Metrics for ONE part's successful encode — kept out of the
    retried attempt so a transient (chaos encode failpoint) doesn't
    count the same part's bytes twice."""
    _enc_hist(codec_name).observe(dt)
    obs.counter(CODEC_BYTES_IN).inc(raw_len)
    obs.counter(CODEC_BYTES_OUT).inc(frame_len)
    obs.counter(
        CODEC_PARTS_RAW_FALLBACK if fallback else CODEC_PARTS_ENCODED
    ).inc()


def encode_frame(
    view: memoryview,
    spec: WriteSpec,
    filter_stride: int = 0,
    min_frame_bytes: int = 0,
):
    """Encode one part into a self-describing frame (bytes-like; the
    native paths return uint8 arrays assembled with no staging copies —
    this runs once per 64MB part on the staging executor, where every
    GIL-holding memcpy serializes otherwise-parallel encodes).  Falls
    back to a raw frame (codec 0, filter 0, payload = the raw bytes)
    whenever the encoded frame isn't smaller than the raw part by
    ``spec.min_ratio`` — incompressible parts pay one header, never a
    decode-side codec dependency.

    ``min_frame_bytes`` is the backend's non-final-part floor
    (StripedWriteHandle.min_part_bytes; S3's EntityTooSmall): a frame
    that compresses BELOW it also falls back to raw — but only when the
    raw frame actually clears the floor (when even raw is undersized,
    the smaller encoded frame is kept; the backend rejects either)."""
    frame, raw_len, fallback, dt = _encode_frame_uncounted(
        view, spec, filter_stride, min_frame_bytes
    )
    _count_encode(
        spec.codec, raw_len, memoryview(frame).nbytes, fallback, dt
    )
    return frame


def _encode_frame_uncounted(
    view: memoryview,
    spec: WriteSpec,
    filter_stride: int = 0,
    min_frame_bytes: int = 0,
) -> tuple:
    """``encode_frame`` minus metrics: ``(frame, raw_len, fallback,
    encode_seconds)``.  The retried async path counts once on success
    via ``_count_encode``."""
    import numpy as np

    view = memoryview(view).cast("B")
    raw_len = view.nbytes
    codec = _REGISTRY[spec.codec]
    t0 = time.perf_counter()
    filtered = shuffle(view, filter_stride) if filter_stride > 1 else view
    if spec.codec == "huff":
        # native fast path: the coder writes its stream directly after
        # a header-sized reservation — the frame is built in place
        from . import _csrc

        out = _csrc.huff_compress(
            memoryview(filtered), headroom=FRAME_HEADER_BYTES
        )
        if out is None:
            raise CodecUnavailableError("huff", " (native fastio lib absent)")
        enc_len = len(out) - FRAME_HEADER_BYTES
    else:
        enc = codec.compress(memoryview(filtered), spec.level)
        enc_len = len(enc)
        out = None
    dt = time.perf_counter() - t0
    frame_len = FRAME_HEADER_BYTES + enc_len
    if raw_len < spec.min_ratio * frame_len or (
        0 < frame_len < min_frame_bytes <= FRAME_HEADER_BYTES + raw_len
    ):
        raw_out = np.empty(FRAME_HEADER_BYTES + raw_len, dtype=np.uint8)
        _HEADER.pack_into(
            raw_out, 0, FRAME_MAGIC, FRAME_VERSION, 0, 0, 0,
            raw_len, raw_len,
        )
        raw_out[FRAME_HEADER_BYTES:] = np.frombuffer(view, dtype=np.uint8)
        return raw_out, raw_len, True, dt
    header = (
        FRAME_MAGIC, FRAME_VERSION, codec.codec_id,
        filter_stride if filter_stride > 1 else 0, 0, raw_len, enc_len,
    )
    if out is not None:
        _HEADER.pack_into(out, 0, *header)
    else:
        out = np.empty(frame_len, dtype=np.uint8)
        _HEADER.pack_into(out, 0, *header)
        out[FRAME_HEADER_BYTES:] = np.frombuffer(
            memoryview(enc), dtype=np.uint8
        )
    return out, raw_len, False, dt


_ENCODE_SLOTS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _encode_slots(loop: asyncio.AbstractEventLoop) -> asyncio.Semaphore:
    """Per-loop cap on concurrent part encodes at the physical core
    count.  The window gate admits parts in bursts (write completions
    cluster), and N-way-contended encodes each take N× longer — same
    aggregate throughput, but every frame reaches the wire late and the
    storage streams sit idle for the whole burst.  Capping at the cores
    that can actually run them keeps per-frame latency minimal, which
    is what feeds the wire steadily."""
    sem = _ENCODE_SLOTS.get(loop)
    if sem is None:
        sem = asyncio.Semaphore(max(1, os.cpu_count() or 1))
        _ENCODE_SLOTS[loop] = sem
    return sem


async def encode_frame_async(
    view: memoryview,
    spec: WriteSpec,
    filter_stride: int,
    executor: Optional[Executor],
    *,
    path: str = "",
    part: int = 0,
    min_frame_bytes: int = 0,
) -> bytes:
    """``encode_frame`` on the staging executor, under the shared retry
    policy with the ``scheduler.codec.encode`` failpoint inside the
    attempt — a transient mid-pipeline encode fault (chaos schedules)
    retries like any storage transient instead of failing the take."""

    def attempt() -> tuple:
        failpoint("scheduler.codec.encode", path=path, part=part)
        # metrics-free attempt: a retried transient must not count the
        # same part's bytes twice (_count_encode runs once, on success)
        return _encode_frame_uncounted(
            view, spec, filter_stride, min_frame_bytes
        )

    with obs.span(
        "codec/encode_part", path=path, part=part,
        bytes=memoryview(view).nbytes, codec=spec.codec,
    ):
        async with _encode_slots(asyncio.get_running_loop()):
            frame, raw_len, fallback, dt = await retry_call(
                attempt,
                op_name=f"encode {path} [part {part}]",
                backend="codec",
                classify=classify_generic,
                progress=_encode_progress(),
                executor=executor,
            )
    _count_encode(
        spec.codec, raw_len, memoryview(frame).nbytes, fallback, dt
    )
    return frame


_ENCODE_PROGRESS: Optional[SharedProgress] = None


def _encode_progress() -> SharedProgress:
    global _ENCODE_PROGRESS
    if _ENCODE_PROGRESS is None:
        _ENCODE_PROGRESS = SharedProgress(label="codec.encode")
    return _ENCODE_PROGRESS


# ------------------------------------------------------ frame decode


def parse_frame_header(view: memoryview, offset: int = 0) -> Tuple[int, int, int, int]:
    """(codec_id, filter_id, raw_len, enc_len) of the frame at
    ``offset``; raises CodecFrameError on structural problems."""
    view = memoryview(view).cast("B")
    if offset + FRAME_HEADER_BYTES > view.nbytes:
        raise CodecFrameError(
            f"truncated frame header at offset {offset}: "
            f"{view.nbytes - offset} of {FRAME_HEADER_BYTES} bytes"
        )
    magic, version, codec_id, filter_id, _r, raw_len, enc_len = (
        _HEADER.unpack_from(view, offset)
    )
    if magic != FRAME_MAGIC:
        raise CodecFrameError(
            f"bad frame magic at offset {offset}: {bytes(magic)!r}"
        )
    if version != FRAME_VERSION:
        raise CodecFrameError(f"unsupported frame version {version}")
    if codec_id not in _ID_TO_NAME:
        raise CodecFrameError(f"unknown codec id {codec_id} in frame")
    return codec_id, filter_id, raw_len, enc_len


def decode_frame(view: memoryview, offset: int = 0) -> Tuple[Any, int]:
    """Decode the frame at ``offset``; returns (raw bytes-like, total
    frame length).  The raw value may be a view into ``view`` (raw-
    fallback frames) or a coder-owned uint8 array — consumers copy into
    their destination, so no per-frame staging copy is paid here.
    Typed errors: CodecFrameError for corruption, CodecUnavailableError
    when the frame names a codec this host can't decode."""
    view = memoryview(view).cast("B")
    codec_id, filter_id, raw_len, enc_len = parse_frame_header(view, offset)
    start = offset + FRAME_HEADER_BYTES
    if start + enc_len > view.nbytes:
        raise CodecFrameError(
            f"truncated frame payload at offset {offset}: "
            f"{view.nbytes - start} of {enc_len} bytes"
        )
    payload = view[start : start + enc_len]
    if codec_id == 0:
        if enc_len != raw_len:
            raise CodecFrameError(
                f"raw frame length mismatch: header says raw={raw_len} "
                f"enc={enc_len}"
            )
        return payload, FRAME_HEADER_BYTES + enc_len
    name = _ID_TO_NAME[codec_id]
    codec = _REGISTRY[name]
    if not codec.available():
        raise CodecUnavailableError(name)
    t0 = time.perf_counter()
    try:
        raw = codec.decompress(payload, raw_len)
    except CodecError:
        raise
    except Exception as e:  # noqa: BLE001 — decoder-internal errors
        raise CodecFrameError(
            f"corrupt {name} frame payload at offset {offset}: {e!r}"
        ) from e
    if len(raw) != raw_len:
        raise CodecFrameError(
            f"{name} frame decoded to {len(raw)} bytes, header says "
            f"{raw_len}"
        )
    if filter_id > 1:
        raw = unshuffle(memoryview(raw), filter_id)
    _dec_hist(name).observe(time.perf_counter() - t0)
    obs.counter(CODEC_PARTS_DECODED).inc()
    return raw, FRAME_HEADER_BYTES + enc_len


# ----------------------------------------------------------- codec table
#
# The manifest records, per encoded storage object (SnapshotMetadata
# .codecs[location]):
#   {"codec":  <registry name chosen at write time>,
#    "part_size": <raw bytes per frame (last frame may be short)>,
#    "raw_size":  <total raw bytes>,
#    "parts":  [<full frame length in stored bytes>, ...],
#    "digest": [crc32, adler32, stored_size]}    # of the STORED bytes;
#                                                # optional (WRITE_CHECKSUMS)
# Raw frame offsets are i*part_size; stored frame offsets are prefix
# sums of "parts" — enough to map any raw byte range to the frames
# covering it.  Objects absent from the table are stored raw (including
# everything written before this layer existed).


def make_table(
    codec_name: str,
    part_size: int,
    raw_size: int,
    frame_lens: List[int],
    stored_digest: Optional[List[int]] = None,
) -> Dict[str, Any]:
    tbl: Dict[str, Any] = {
        "codec": codec_name,
        "part_size": int(part_size),
        "raw_size": int(raw_size),
        "parts": [int(x) for x in frame_lens],
    }
    if stored_digest is not None:
        tbl["digest"] = [int(x) for x in stored_digest]
    return tbl


def table_stored_size(table: Dict[str, Any]) -> int:
    return sum(table["parts"])


def validate_table(table: Dict[str, Any]) -> bool:
    """Structural sanity of a manifest codec-table entry (metadata is
    self-checksummed, so this guards against version skew, not
    corruption)."""
    try:
        return (
            isinstance(table.get("codec"), str)
            and int(table["part_size"]) > 0
            and int(table["raw_size"]) >= 0
            and all(int(x) > 0 for x in table["parts"])
        )
    except (KeyError, TypeError, ValueError):
        return False


def _frame_spans(
    table: Dict[str, Any]
) -> List[Tuple[int, int, int, int]]:
    """(raw_lo, raw_hi, enc_lo, enc_hi) per frame."""
    part_size = int(table["part_size"])
    raw_size = int(table["raw_size"])
    spans = []
    enc_lo = 0
    raw_lo = 0
    for frame_len in table["parts"]:
        raw_hi = min(raw_lo + part_size, raw_size)
        spans.append((raw_lo, raw_hi, enc_lo, enc_lo + int(frame_len)))
        raw_lo = raw_hi
        enc_lo += int(frame_len)
    return spans


def part_read_concurrency() -> int:
    """Concurrent frame reads/decodes per object — same bound as the
    stripe engine's part concurrency (one object must not monopolize
    every storage slot)."""
    return max(2, min(knobs.get_max_per_rank_io_concurrency(), 8))


async def framed_read(
    storage: StoragePlugin,
    path: str,
    table: Dict[str, Any],
    *,
    byte_range: Optional[List[int]] = None,
    into: Any = None,
    executor: Optional[Executor] = None,
) -> Any:
    """Read raw bytes ``[byte_range)`` of an encoded object: ranged-read
    the overlapping frames concurrently, decode each on ``executor``
    while later frames are still in flight, and assemble into one
    buffer (honoring the ``into`` destination hint by identity, the
    plugins' read-into contract).

    A raw range that straddles a frame decodes the whole frame and
    slices — so heavily tiled reads of one frame pay repeated decodes
    (documented in docs/compression.md; restore's budget-tiled paths
    size tiles at the budget, typically >= the part size)."""
    raw_size = int(table["raw_size"])
    if byte_range is None:
        lo, hi = 0, raw_size
    else:
        lo, hi = int(byte_range[0]), int(byte_range[1])
    if not (0 <= lo <= hi <= raw_size):
        raise CodecFrameError(
            f"raw range [{lo}, {hi}) outside encoded object {path!r} "
            f"of raw size {raw_size}"
        )
    length = hi - lo
    out = resolve_read_destination(into, length)
    if length == 0:
        return out
    out_view = memoryview(out).cast("B")
    frames = [
        s for s in _frame_spans(table) if s[0] < hi and s[1] > lo
    ]
    sem = asyncio.Semaphore(part_read_concurrency())
    loop = asyncio.get_running_loop()

    with obs.span(
        "codec/framed_read", path=path, bytes=length, frames=len(frames),
        codec=table.get("codec"),
    ):

        async def one(raw_lo: int, raw_hi: int, enc_lo: int, enc_hi: int):
            async with sem:
                rio = ReadIO(path=path, byte_range=[enc_lo, enc_hi])
                await storage.read(rio)
                frame = memoryview(rio.buf).cast("B")
                if frame.nbytes != enc_hi - enc_lo:
                    raise CodecFrameError(
                        f"frame read of {path!r} [{enc_lo}:{enc_hi}] "
                        f"returned {frame.nbytes} bytes"
                    )

                def decode_and_place() -> None:
                    raw, _ = decode_frame(frame)
                    if len(raw) != raw_hi - raw_lo:
                        raise CodecFrameError(
                            f"frame of {path!r} decoded to {len(raw)} "
                            f"bytes, table says {raw_hi - raw_lo}"
                        )
                    s = max(raw_lo, lo)
                    e = min(raw_hi, hi)
                    out_view[s - lo : e - lo] = memoryview(raw)[
                        s - raw_lo : e - raw_lo
                    ]

                if executor is not None:
                    await loop.run_in_executor(executor, decode_and_place)
                else:
                    decode_and_place()

        await asyncio.gather(*(one(*f) for f in frames))
    return out


