"""Replicated-write load balancing across ranks, slices and hosts.

Reference: torchsnapshot/partitioner.py:67-213.  The reference all_gathers
entry metadata, has rank 0 compute a greedy partition, and broadcasts the
result (partitioner.py:170-192).  Here the partition is a *pure
deterministic function* of its inputs, so in JAX's multi-controller model
every process computes the identical assignment locally — the only
communication needed is one small all_gather of per-rank pre-load bytes
(non-replicated write volume), matching the reference's pre-load counting
(partitioner.py:266-270).

Topology awareness (topology/): with a ``Topology`` descriptor (itself
identical on every process — detect_topology exchanges hints once per
operation), the greedy choice balances hierarchically: least-loaded
SLICE first (per-slice durable egress rides the slice's DCN uplink —
the scarce resource at multislice scale), least-loaded HOST within it
(per-NIC egress), then least-loaded rank, ties by rank for
determinism.  Each replicated object is still written exactly once per
FLEET; the hierarchy only decides by whom.  Without a topology (or
with a non-explicit one) the flat greedy is byte-identical to the
pre-topology behavior.

Note: sharded jax.Arrays (including fully-replicated ones) never reach this
partitioner — their dedup+balance happens in the sharded preparer from the
globally-known sharding metadata with zero communication
(preparers/sharded.py, whose ``assign_box_writers`` applies the same
hierarchical tie-break).  This module only balances *host-side* replicated
state: numpy arrays, objects, chunked host arrays declared replicated via
glob patterns.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


def _topology_chooser(topology, loads: List[int]):
    """A candidate-rank chooser balancing (slice, host, rank) loads.
    Slice/host loads are maintained incrementally from the SAME load
    vector greedy updates mutate, so the hierarchy composes with
    preloads and with earlier assignments."""
    slice_loads = [0] * topology.num_slices
    host_loads = [0] * topology.num_hosts
    for r, load in enumerate(loads):
        slice_loads[topology.slice_of[r]] += load
        host_loads[topology.host_of[r]] += load

    def key(r: int):
        return (
            slice_loads[topology.slice_of[r]],
            host_loads[topology.host_of[r]],
            loads[r],
            r,
        )

    def charge(r: int, nbytes: int) -> None:
        loads[r] += nbytes
        slice_loads[topology.slice_of[r]] += nbytes
        host_loads[topology.host_of[r]] += nbytes

    return key, charge


def partition_replicated_writes(
    items: Sequence[Tuple[str, int]],
    world_size: int,
    preloads: Sequence[int] = (),
    topology: Optional[object] = None,
) -> Dict[str, int]:
    """Assign each replicated logical path to exactly one writer rank.

    ``items``: (logical_path, nbytes) — must be identical on every rank
    (replication is the caller's invariant).  ``preloads``: per-rank bytes
    already being written for non-replicated state.  Greedy: largest item
    first to the least-loaded rank; ties broken by rank for determinism.
    ``topology``: an optional ``topology.Topology`` (identical on every
    rank) switching the least-loaded choice to the hierarchical
    slice → host → rank ordering described in the module docstring;
    non-explicit topologies fall back to the flat choice.
    """
    loads: List[int] = list(preloads) if preloads else [0] * world_size
    if len(loads) != world_size:
        raise ValueError(f"preloads len {len(loads)} != world_size {world_size}")
    assignment: Dict[str, int] = {}
    if topology is not None and getattr(topology, "explicit", False):
        key, charge = _topology_chooser(topology, loads)
    else:
        def key(r: int):
            return (loads[r], r)

        def charge(r: int, nbytes: int) -> None:
            loads[r] += nbytes

    for path, nbytes in sorted(items, key=lambda kv: (-kv[1], kv[0])):
        writer = min(range(world_size), key=key)
        assignment[path] = writer
        charge(writer, nbytes)
    return assignment


def elect_takeover_writers(
    orphans: Sequence[Tuple[str, int]],
    dead_ranks: Sequence[int],
    world_size: int,
    preloads: Sequence[int] = (),
    topology: Optional[object] = None,
    origin_of: Optional[Dict[str, int]] = None,
) -> Dict[str, int]:
    """Re-assign a dead writer's replicated objects to live ranks.

    Pure and deterministic, like ``partition_replicated_writes`` — every
    survivor computes the identical election locally from the shared
    dead set, so takeover needs no extra collectives (the recovery
    protocol only agrees on WHO is dead, not on who writes what).

    ``orphans``: (logical_path, nbytes) whose elected writer died.
    ``origin_of``: optional path → dead writer rank — with a topology,
    a live rank in the dead writer's SLICE is preferred (the re-write
    egresses over the uplink the original partition budgeted for,
    instead of adding load to an unrelated slice's DCN), then the usual
    slice → host → rank load order among the rest.  Greedy largest-first
    over post-partition loads; ties by rank.
    """
    dead = set(dead_ranks)
    live = [r for r in range(world_size) if r not in dead]
    if not live:
        raise ValueError("takeover election with zero live ranks")
    loads: List[int] = list(preloads) if preloads else [0] * world_size
    if len(loads) != world_size:
        raise ValueError(f"preloads len {len(loads)} != world_size {world_size}")
    explicit = topology is not None and getattr(topology, "explicit", False)
    if explicit:
        base_key, charge = _topology_chooser(topology, loads)
    else:
        def base_key(r: int):
            return (loads[r], r)

        def charge(r: int, nbytes: int) -> None:
            loads[r] += nbytes

    assignment: Dict[str, int] = {}
    for path, nbytes in sorted(orphans, key=lambda kv: (-kv[1], kv[0])):
        origin = (origin_of or {}).get(path)
        if explicit and origin is not None:
            dead_slice = topology.slice_of[origin]

            def key(r: int):
                return (topology.slice_of[r] != dead_slice,) + base_key(r)
        else:
            key = base_key
        writer = min(live, key=key)
        assignment[path] = writer
        charge(writer, nbytes)
    return assignment
