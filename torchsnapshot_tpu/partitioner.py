"""Replicated-write load balancing across ranks.

Reference: torchsnapshot/partitioner.py:67-213.  The reference all_gathers
entry metadata, has rank 0 compute a greedy partition, and broadcasts the
result (partitioner.py:170-192).  Here the partition is a *pure
deterministic function* of its inputs, so in JAX's multi-controller model
every process computes the identical assignment locally — the only
communication needed is one small all_gather of per-rank pre-load bytes
(non-replicated write volume), matching the reference's pre-load counting
(partitioner.py:266-270).

Note: sharded jax.Arrays (including fully-replicated ones) never reach this
partitioner — their dedup+balance happens in the sharded preparer from the
globally-known sharding metadata with zero communication
(preparers/sharded.py).  This module only balances *host-side* replicated
state: numpy arrays, objects, chunked host arrays declared replicated via
glob patterns.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple


def partition_replicated_writes(
    items: Sequence[Tuple[str, int]],
    world_size: int,
    preloads: Sequence[int] = (),
) -> Dict[str, int]:
    """Assign each replicated logical path to exactly one writer rank.

    ``items``: (logical_path, nbytes) — must be identical on every rank
    (replication is the caller's invariant).  ``preloads``: per-rank bytes
    already being written for non-replicated state.  Greedy: largest item
    first to the least-loaded rank; ties broken by rank for determinism.
    """
    loads: List[int] = list(preloads) if preloads else [0] * world_size
    if len(loads) != world_size:
        raise ValueError(f"preloads len {len(loads)} != world_size {world_size}")
    assignment: Dict[str, int] = {}
    for path, nbytes in sorted(items, key=lambda kv: (-kv[1], kv[0])):
        writer = min(range(world_size), key=lambda r: (loads[r], r))
        assignment[path] = writer
        loads[writer] += nbytes
    return assignment
