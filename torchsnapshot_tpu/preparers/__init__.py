"""IO preparers: turn checkpointable objects into (Entry, WriteReqs) on save
and (ReadReqs, Future) on load.

Reference: torchsnapshot/io_preparer.py:82-182 and io_preparers/*.

Dispatch (TPU-native):

- primitives → inlined ``PrimitiveEntry`` (no storage I/O)
- ``jax.Array`` spanning multiple devices (sharded and/or replicated over a
  Mesh) → sharded preparer.  This single path subsumes the reference's
  ShardedTensor, DTensor *and* replicated-DDP handling: the sharding's
  device→index map is global knowledge in SPMD JAX, so every process can
  compute an identical dedup + write-load balance without any collectives.
- single-device ``jax.Array`` / ``np.ndarray`` / CPU ``torch.Tensor`` →
  array preparer (chunked above the 512MB knob)
- everything else → object preparer (safe codec, pickle behind a knob)
"""

from __future__ import annotations

import fnmatch
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from .. import knobs
from ..io_types import Future, ReadReq, WriteReq
from ..manifest import Entry, PrimitiveEntry, is_primitive_type
from .array import (
    ArrayIOPreparer,
    ChunkedArrayIOPreparer,
    is_array_like,
    array_nbytes,
)
from .object import ObjectIOPreparer
from .sharded import ShardedArrayIOPreparer, is_multi_device_jax_array


def path_is_replicated(logical_path: str, replicated_globs: Sequence[str]) -> bool:
    return any(fnmatch.fnmatch(logical_path, g) for g in replicated_globs)


def estimate_write_bytes(obj: Any) -> int:
    """Cheap, gather-free byte estimate of one leaf's write load, used to
    pre-load the sharded-box balancer with per-rank host-state weight
    (reference partitioner.py:266-270).  Exactness doesn't matter —
    balancing is a heuristic — but the estimate must be computable
    without staging (no serialization, no D2H)."""
    if is_primitive_type(obj):
        return 0
    if is_array_like(obj):
        return array_nbytes(obj)
    if isinstance(obj, (bytes, bytearray)):
        return len(obj)
    return 0  # arbitrary object: serialized size unknown until staged


def prepare_write(
    obj: Any,
    logical_path: str,
    rank: int,
    replicated: bool = False,
    is_async_snapshot: bool = False,
    process_index: int = 0,
    process_count: int = 1,
    writer_loads: Optional[List[int]] = None,
    chunk_size_bytes: Optional[int] = None,
    topology: Optional[Any] = None,
) -> Tuple[Entry, List[WriteReq]]:
    """Plan the write of one leaf (reference io_preparer.py:82-147).

    Storage-path namespace (reference io_preparer.py:52-61):
    ``replicated/`` for replicated entries, ``sharded/`` for sharded arrays,
    ``<rank>/`` for per-rank entries.

    ``writer_loads``: shared per-process load vector for the sharded-box
    balancer (see assign_box_writers); identical across controllers.

    ``topology``: optional ``topology.Topology`` (identical across
    controllers) so sharded-replica box writers spread across slices
    and hosts, not just ranks.
    """
    if is_primitive_type(obj):
        return PrimitiveEntry.from_object(obj, replicated=replicated), []

    if is_multi_device_jax_array(obj):
        return ShardedArrayIOPreparer.prepare_write(
            obj=obj,
            logical_path=logical_path,
            process_index=process_index,
            process_count=process_count,
            writer_loads=writer_loads,
            topology=topology,
        )

    if is_array_like(obj):
        # Normalize torch tensors to a host numpy view ONCE here (zero-copy
        # for CPU tensors, a single transfer otherwise) so the size check,
        # the chunked path and the stager never re-materialize.
        from .array import _is_torch_tensor, _to_host_view

        if _is_torch_tensor(obj):
            obj = _to_host_view(obj)
        namespace = "replicated" if replicated else str(rank)
        location = f"{namespace}/{logical_path}"
        # callers planning many leaves resolve the knob once and pass it
        # down (per-leaf env resolution is measurable planning cost)
        if chunk_size_bytes is None:
            chunk_size_bytes = knobs.get_max_chunk_size_bytes()
        if array_nbytes(obj) > chunk_size_bytes:
            return ChunkedArrayIOPreparer.prepare_write(
                obj=obj,
                location=location,
                replicated=replicated,
                is_async_snapshot=is_async_snapshot,
            )
        return ArrayIOPreparer.prepare_write(
            obj=obj,
            location=location,
            replicated=replicated,
            is_async_snapshot=is_async_snapshot,
        )

    namespace = "replicated" if replicated else str(rank)
    return ObjectIOPreparer.prepare_write(
        obj=obj,
        location=f"{namespace}/{logical_path}",
        replicated=replicated,
    )


def prepare_read(
    entry: Entry,
    obj_out: Optional[Any] = None,
    buffer_size_limit_bytes: Optional[int] = None,
) -> Tuple[List[ReadReq], Future]:
    """Plan the read of one entry (reference io_preparer.py:150-182).

    ``obj_out`` is the restore template: its type (and, for a sharded
    ``jax.Array``, its sharding) decides how the saved bytes are
    materialized.  Resharding happens here: the template's shard boxes are
    intersected with the saved boxes.
    """
    from ..manifest import (
        ArrayEntry,
        ChunkedArrayEntry,
        ObjectEntry,
        PrimitiveEntry as _PrimitiveEntry,
        ShardedArrayEntry,
    )

    if isinstance(entry, _PrimitiveEntry):
        fut: Future = Future(entry.get_value())
        fut.set(entry.get_value())
        return [], fut
    if isinstance(entry, ShardedArrayEntry):
        return ShardedArrayIOPreparer.prepare_read(
            entry, obj_out, buffer_size_limit_bytes
        )
    if isinstance(entry, ChunkedArrayEntry):
        return ChunkedArrayIOPreparer.prepare_read(
            entry, obj_out, buffer_size_limit_bytes
        )
    if isinstance(entry, ArrayEntry):
        return ArrayIOPreparer.prepare_read(entry, obj_out, buffer_size_limit_bytes)
    if isinstance(entry, ObjectEntry):
        return ObjectIOPreparer.prepare_read(entry)
    raise TypeError(f"cannot prepare read for entry type {type(entry)}")
