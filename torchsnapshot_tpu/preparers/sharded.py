"""Sharded-array preparer: multi-device ``jax.Array`` save/restore with
collective-free write partitioning and overlap-based resharding reads.

This single path subsumes three reference components — ShardedTensor
(io_preparers/sharded_tensor.py:129-333), DTensor (io_preparers/
dtensor.py:123-278), and the replicated-write partitioner's common case
(partitioner.py:67-213) — because on TPU the sharding layout is *global
knowledge*: every process holds the same ``Sharding.devices_indices_map``,
so dedup of replicated shards and write load-balancing are pure functions
computed identically everywhere, with zero collectives.  (The reference
must all_gather entry metadata and have rank 0 broadcast a partition,
partitioner.py:170-192 — that entire control-plane round trip disappears.)

Write: unique shard boxes are balanced greedily (largest-first) across the
processes that can address them; boxes larger than the max-shard-size knob
are subdivided along their largest dim (reference sharded_tensor.py:48-78).

Read: the restore template's shard boxes are intersected with the saved
boxes (overlap algebra in overlap.py); each overlapping saved shard is read
once and scattered into every overlapping local region (reference
sharded_tensor.py:197-298).  When the overlap is a dim-0 slab of the saved
blob, only that byte range is fetched.  The assembled per-device buffers
become the restored array via ``jax.make_array_from_single_device_arrays``
— resharding across world sizes/meshes (elasticity) is this same code path
with a different template sharding.
"""

from __future__ import annotations

import asyncio
from concurrent.futures import Executor
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import knobs
from ..io_types import BufferConsumer, BufferStager, Future, ReadReq, WriteReq
from ..manifest import Shard, ShardedArrayEntry
from ..serialization import (
    array_from_buffer,
    fast_copyto,
    serialized_size_bytes,
    string_to_dtype,
)
from .array import (
    JaxArrayBufferStager,
    array_dtype_str,
    donate_template,
    materialize_into_template,
    _Countdown,
    _TileCrcFold,
    _is_jax_array,
    _plan_flat_tiles,
)
from .overlap import (
    Box,
    box_intersect,
    box_nelems,
    index_to_box,
    is_dim0_slab,
    make_box,
    relative_slices,
)


def is_multi_device_jax_array(obj: Any) -> bool:
    if not _is_jax_array(obj):
        return False
    return len(obj.sharding.device_set) > 1


def _location_for_box(logical_path: str, box: Box) -> str:
    off = "_".join(str(o) for o in box[0])
    sz = "_".join(str(s) for s in box[1])
    return f"sharded/{logical_path}.{off}.{sz}" if off else f"sharded/{logical_path}.scalar"


def _sharding_metadata(sharding: Any) -> Tuple[Optional[List[str]], Optional[List[int]], Optional[List[Any]]]:
    """Extract (mesh_axis_names, mesh_shape, spec) from a NamedSharding for
    the manifest (advisory; analogue of DTensorEntry's mesh+dim_map,
    reference manifest.py:211-261)."""
    try:
        from jax.sharding import NamedSharding
    except ImportError:  # pragma: no cover
        return None, None, None
    if not isinstance(sharding, NamedSharding):
        return None, None, None
    mesh = sharding.mesh
    axis_names = [str(a) for a in mesh.axis_names]
    mesh_shape = [int(s) for s in mesh.devices.shape]
    spec: List[Any] = []
    for elem in sharding.spec:
        if elem is None:
            spec.append(None)
        elif isinstance(elem, (tuple, list)):
            spec.append([str(e) for e in elem])
        else:
            spec.append(str(elem))
    return axis_names, mesh_shape, spec


def _unique_boxes(sharding: Any, shape: Tuple[int, ...]) -> Dict[Box, List[Any]]:
    """Map each unique shard box to the devices holding it (replicas)."""
    boxes: Dict[Box, List[Any]] = {}
    for dev, idx in sharding.devices_indices_map(tuple(shape)).items():
        box = index_to_box(idx, shape)
        boxes.setdefault(box, []).append(dev)
    return boxes


def _subdivide(box: Box, itemsize: int, max_bytes: int) -> List[Box]:
    """Split a box along its largest dim until every piece ≤ max_bytes
    (reference sharded_tensor.py:48-78; dtensor.py:63-98 picks the largest
    sharded dim — largest dim is the natural generalization)."""
    nbytes = box_nelems(box) * itemsize
    if nbytes <= max_bytes or not box[1]:
        return [box]
    dim = max(range(len(box[1])), key=lambda d: box[1][d])
    if box[1][dim] <= 1:
        return [box]
    rows = box[1][dim]
    row_bytes = nbytes // rows
    rows_per = max(1, max_bytes // max(1, row_bytes))
    out: List[Box] = []
    for r in range(0, rows, rows_per):
        n = min(rows_per, rows - r)
        offsets = list(box[0])
        sizes = list(box[1])
        offsets[dim] += r
        sizes[dim] = n
        out.extend(_subdivide(make_box(offsets, sizes), itemsize, max_bytes))
    return out


def assign_box_writers(
    boxes: Dict[Box, List[Any]],
    itemsize: int,
    process_count: int,
    preloads: Optional[List[int]] = None,
    topology: Optional[Any] = None,
) -> Dict[Box, int]:
    """Deterministic greedy balance: every process computes the identical
    assignment from the (global) sharding metadata. Largest box first, to
    the least-loaded candidate process (reference partitioner.py:140-213,
    minus the gather+broadcast).

    ``preloads``: per-process byte loads already committed elsewhere —
    per-rank host-state bytes and earlier sharded leaves' assignments
    (reference partitioner.py:266-270 counts non-replicated bytes as
    pre-load).  MUTATED IN PLACE so one vector composes across every
    sharded leaf of a take; callers must pass an identical vector on
    every controller (it feeds a collective-free assignment).

    ``topology``: optional ``topology.Topology`` (identical on every
    controller) — a box whose replica group spans several slices elects
    its writer by least-loaded slice → host → rank, so sharded-replica
    writes spread across slices like replicated host state does
    (partitioner.partition_replicated_writes).  The flat behavior is
    unchanged when omitted or non-explicit."""
    loads = preloads if preloads is not None else [0] * max(1, process_count)
    assignment: Dict[Box, int] = {}
    if topology is not None and getattr(topology, "explicit", False):
        from ..partitioner import _topology_chooser

        choose_key, charge = _topology_chooser(topology, loads)
    else:
        def choose_key(p: int):
            return (loads[p], p)

        def charge(p: int, nbytes: int) -> None:
            loads[p] += nbytes

    ordered = sorted(
        boxes.keys(), key=lambda b: (-box_nelems(b), b[0])
    )
    for box in ordered:
        candidates = sorted({d.process_index for d in boxes[box]})
        writer = min(candidates, key=choose_key)
        assignment[box] = writer
        charge(writer, box_nelems(box) * itemsize)
    return assignment


class ShardedArrayIOPreparer:
    @staticmethod
    def prepare_write(
        obj: Any,
        logical_path: str,
        process_index: int,
        process_count: int,
        writer_loads: Optional[List[int]] = None,
        topology: Optional[Any] = None,
    ) -> Tuple[ShardedArrayEntry, List[WriteReq]]:
        shape = tuple(int(s) for s in obj.shape)
        itemsize = np.dtype(obj.dtype).itemsize
        boxes = _unique_boxes(obj.sharding, shape)
        assignment = assign_box_writers(
            boxes, itemsize, process_count, preloads=writer_loads,
            topology=topology,
        )

        # device -> local shard data for this process
        local_data: Dict[Any, Any] = {
            s.device: s.data for s in obj.addressable_shards
        }

        axis_names, mesh_shape, spec = _sharding_metadata(obj.sharding)
        shards: List[Shard] = []
        write_reqs: List[WriteReq] = []
        max_shard_bytes = knobs.get_max_shard_size_bytes()
        for box, devices in boxes.items():
            if assignment[box] != process_index:
                continue
            device = next(d for d in devices if d.process_index == process_index)
            data = local_data[device]
            for sub in _subdivide(box, itemsize, max_shard_bytes):
                location = _location_for_box(logical_path, sub)
                shards.append(
                    Shard(
                        offsets=list(sub[0]),
                        sizes=list(sub[1]),
                        location=location,
                    )
                )
                index = relative_slices(sub, box)
                shard_stager = JaxArrayBufferStager(
                    data,
                    index=index if sub != box else None,
                    nbytes=box_nelems(sub) * itemsize,
                )
                # codec preconditioning hint (see preparers/array.py)
                from ..codec import filter_for_dtype

                shard_stager.codec_filter_stride = filter_for_dtype(
                    array_dtype_str(obj)
                )
                write_reqs.append(
                    WriteReq(
                        path=location,
                        buffer_stager=shard_stager,
                        checksum_sinks=[
                            (
                                lambda c, s=shards[-1]: setattr(
                                    s, "crc32", c
                                ),
                                None,
                            )
                        ],
                    )
                )
        entry = ShardedArrayEntry(
            dtype=array_dtype_str(obj),
            shape=list(shape),
            shards=shards,
            mesh_axis_names=axis_names,
            mesh_shape=mesh_shape,
            spec=spec,
        )
        return entry, write_reqs

    @staticmethod
    def prepare_read(
        entry: ShardedArrayEntry,
        obj_out: Any = None,
        buffer_size_limit_bytes: Optional[int] = None,
    ) -> Tuple[List[ReadReq], Future]:
        fut: Future = Future()
        shape = tuple(entry.shape)
        dtype = string_to_dtype(entry.dtype)
        itemsize = dtype.itemsize

        # Dedup saved shards by box (replicas may appear in merged manifests).
        saved: Dict[Box, Shard] = {}
        for s in entry.shards:
            saved.setdefault(make_box(s.offsets, s.sizes), s)

        if obj_out is not None and is_multi_device_jax_array(obj_out):
            sharding = obj_out.sharding
            local_boxes: Dict[Box, List[Any]] = {}
            idx_map = sharding.devices_indices_map(tuple(obj_out.shape))
            for dev in sharding.addressable_devices:
                box = index_to_box(idx_map[dev], obj_out.shape)
                local_boxes.setdefault(box, []).append(dev)
            target_dtype = np.dtype(obj_out.dtype)
        else:
            # No sharded template: materialize the full array, then hand it
            # to the template logic (numpy in-place / device_put / fresh).
            local_boxes = {make_box((0,) * len(shape), shape): [None]}
            target_dtype = dtype

        buffers: Dict[Box, np.ndarray] = {
            box: np.empty(box[1], dtype=dtype) for box in local_boxes
        }

        # saved box -> [(overlap, local_box), ...]
        plans: List[Tuple[Shard, Box, List[Tuple[Box, Box]]]] = []
        for sbox, shard in saved.items():
            overlaps = []
            for lbox in local_boxes:
                inter = box_intersect(sbox, lbox)
                if inter is not None:
                    overlaps.append((inter, lbox))
            if overlaps:
                plans.append((shard, sbox, overlaps))

        def assemble() -> None:
            if obj_out is not None and is_multi_device_jax_array(obj_out):
                import jax

                from .array import transfer_gate

                if target_dtype != dtype:
                    for box in list(buffers):
                        buffers[box] = buffers[box].astype(target_dtype)
                full_box = make_box(
                    (0,) * len(obj_out.shape), tuple(obj_out.shape)
                )
                if set(local_boxes) == {full_box}:
                    # fully-replicated template: one broadcasting device_put
                    with transfer_gate() as pending:
                        out = jax.device_put(buffers[full_box], sharding)
                        pending.append(out)
                    # fut.set BEFORE donation: a donated template must
                    # always imply a replacement reachable through the
                    # Future (1x-restore; see donate_template)
                    fut.set(out)
                    donate_template(obj_out)
                    return
                arrays = []
                with transfer_gate() as pending:
                    for box, devs in local_boxes.items():
                        for dev in devs:
                            arrays.append(jax.device_put(buffers[box], dev))
                    pending.extend(arrays)
                out = jax.make_array_from_single_device_arrays(
                    tuple(obj_out.shape), sharding, arrays
                )
                fut.set(out)
                donate_template(obj_out)
            else:
                (buf,) = buffers.values()
                result = materialize_into_template(buf, obj_out)
                fut.set(result)
                if result is not obj_out:
                    donate_template(obj_out)

        if not plans:  # degenerate: nothing to read (e.g. zero-size array)
            assemble()
            return [], fut

        countdown = _Countdown(n=len(plans), on_zero=assemble)
        read_reqs: List[ReadReq] = []
        for shard, sbox, overlaps in plans:
            expected_crc: Optional[int] = None
            # Minimal fetch: if every overlap is a dim-0 slab of the saved
            # blob, fetch just the covering row range.
            if all(is_dim0_slab(ov, sbox) for ov, _ in overlaps) and sbox[1]:
                r0 = min(ov[0][0] for ov, _ in overlaps) - sbox[0][0]
                r1 = max(ov[0][0] + ov[1][0] for ov, _ in overlaps) - sbox[0][0]
                row_bytes = (box_nelems(sbox) // max(1, sbox[1][0])) * itemsize
                base = shard.byte_range[0] if shard.byte_range else 0
                byte_range: Optional[List[int]] = [
                    base + r0 * row_bytes,
                    base + r1 * row_bytes,
                ]
                read_offsets = list(sbox[0])
                read_offsets[0] += r0
                read_sizes = list(sbox[1])
                read_sizes[0] = r1 - r0
                read_box = make_box(read_offsets, read_sizes)
                if r0 == 0 and r1 == sbox[1][0]:
                    # the covering row range IS the whole shard payload:
                    # its recorded checksum applies
                    expected_crc = shard.crc32
            else:
                byte_range = list(shard.byte_range) if shard.byte_range else None
                read_box = sbox
                # this branch reads the WHOLE shard payload: its recorded
                # checksum applies (partial row-range reads above don't)
                expected_crc = shard.crc32
            read_reqs.extend(
                _emit_shard_reads(
                    shard.location,
                    read_box,
                    byte_range,
                    expected_crc,
                    entry.dtype,
                    itemsize,
                    overlaps,
                    buffers,
                    countdown,
                    buffer_size_limit_bytes,
                )
            )
        return read_reqs, fut


def _emit_shard_reads(
    location: str,
    read_box: Box,
    byte_range: Optional[List[int]],
    expected_crc: Optional[int],
    dtype: str,
    itemsize: int,
    overlaps: List[Tuple[Box, Box]],
    buffers: Dict[Box, np.ndarray],
    outer: _Countdown,
    budget: Optional[int],
) -> List[ReadReq]:
    """Emit the read(s) for one saved-shard fetch, splitting an
    over-budget fetch into dim-0 row-range tiles.

    ``read_box`` is always a dim-0 row range of the saved shard (the
    whole box, or the covering row range of the dim-0-slab fast path),
    and shards are stored C-order — so consecutive rows are consecutive
    payload bytes, and a row range is an exact byte range.  That makes
    budgeted tiling a pure re-slicing of the fetch: each tile scatters
    into the same local buffers through the overlap algebra, and peak
    transient host memory per request is O(budget) instead of O(shard)
    (the reference's budget stops at per-shard granularity,
    io_preparers/tensor.py:128-181 applies only to dense tensors; this
    extends the same contract to sharded entries).

    Tiling must not weaken integrity: when the fetch covers the whole
    shard payload (``expected_crc`` set), per-tile crc32s fold in offset
    order back to the recorded whole-payload value (``_TileCrcFold``,
    same VERIFY_ON_RESTORE gate as unbudgeted reads).  A single row
    larger than the budget reads row-at-a-time (the floor; element-level
    splits would tear rows across scatter boxes)."""
    total_bytes = box_nelems(read_box) * itemsize
    rows = read_box[1][0] if read_box[1] else 0
    if (
        budget is None
        or total_bytes <= budget
        or rows <= 1
    ):
        return [
            ReadReq(
                path=location,
                byte_range=byte_range,
                buffer_consumer=_ShardConsumer(
                    read_box=read_box,
                    dtype=dtype,
                    overlaps=overlaps,
                    buffers=buffers,
                    countdown=outer,
                ),
                expected_crc32=expected_crc,
            )
        ]

    # one "element" per dim-0 row: the shared tile math splits the row
    # range exactly as it splits flat element ranges elsewhere
    row_bytes = total_bytes // rows
    base = byte_range[0] if byte_range else 0
    tiles = _plan_flat_tiles(0, rows, row_bytes, budget, base_byte=base)
    fold = _TileCrcFold(
        expected_crc, what=f"sharded payload {location}", then=outer.step
    )
    inner = _Countdown(n=len(tiles), on_zero=fold.finish)
    reqs: List[ReadReq] = []
    for t0, t1, tile_byte_range in tiles:
        offsets = list(read_box[0])
        offsets[0] += t0
        sizes = list(read_box[1])
        sizes[0] = t1 - t0
        tile_box = make_box(offsets, sizes)
        tile_overlaps = []
        for inter, lbox in overlaps:
            sub = box_intersect(inter, tile_box)
            if sub is not None:
                tile_overlaps.append((sub, lbox))
        # gap tiles (covering range between disjoint overlaps) still
        # read so the crc fold sees every payload byte; their scatter
        # list is empty
        reqs.append(
            ReadReq(
                path=location,
                byte_range=list(tile_byte_range),
                buffer_consumer=_ShardConsumer(
                    read_box=tile_box,
                    dtype=dtype,
                    overlaps=tile_overlaps,
                    buffers=buffers,
                    countdown=inner,
                    crc_fold=fold,
                    crc_key=t0,
                ),
            )
        )
    return reqs


class _ShardConsumer(BufferConsumer):
    """Scatter one saved shard's bytes into every overlapping local region
    (reference ShardedTensorBufferConsumer, sharded_tensor.py:301-333)."""

    def __init__(
        self,
        read_box: Box,
        dtype: str,
        overlaps: List[Tuple[Box, Box]],
        buffers: Dict[Box, np.ndarray],
        countdown: _Countdown,
        crc_fold: Optional[Any] = None,
        crc_key: int = 0,
    ) -> None:
        self.read_box = read_box
        self.dtype = dtype
        self.overlaps = overlaps
        self.buffers = buffers
        self.countdown = countdown
        self.crc_fold = crc_fold
        self.crc_key = crc_key

    async def consume_buffer(
        self, buf: Any, executor: Optional[Executor] = None
    ) -> None:
        if self.crc_fold is not None:
            self.crc_fold.record(self.crc_key, buf)
        src = array_from_buffer(buf, self.dtype, self.read_box[1])

        def scatter() -> None:
            for inter, lbox in self.overlaps:
                s_sl = relative_slices(inter, self.read_box)
                d_sl = relative_slices(inter, lbox)
                # 0-d boxes: arr[()] yields a scalar, not a view — use [...]
                s = src[s_sl] if s_sl else src[...]
                d = self.buffers[lbox][d_sl] if d_sl else self.buffers[lbox][...]
                fast_copyto(d, s)

        loop = asyncio.get_running_loop()
        if executor is not None:
            await loop.run_in_executor(executor, scatter)
        else:
            scatter()
        self.countdown.step()

    def get_consuming_cost_bytes(self) -> int:
        return box_nelems(self.read_box) * string_to_dtype(self.dtype).itemsize
