"""Hyperrectangle (box) algebra for shard overlap / resharding.

Reference: the overlap-region math in torchsnapshot/io_preparers/
sharded_tensor.py:80-127 (`_shards_get_overlap_region_wrt_saved_tensor`) and
`_OverlappingRegion.get_views` (:285-298), generalized to N-d boxes given by
(offsets, sizes) — the same algebra covers ShardedTensor, DTensor and any
``jax.sharding.NamedSharding`` layout, including one array dim sharded over
multiple mesh axes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

# A box is (offsets, sizes), one entry per dim.
Box = Tuple[Tuple[int, ...], Tuple[int, ...]]


def make_box(offsets: Sequence[int], sizes: Sequence[int]) -> Box:
    return tuple(int(o) for o in offsets), tuple(int(s) for s in sizes)


def index_to_box(index: Tuple, shape: Sequence[int]) -> Box:
    """Normalize a jax indexing tuple (from
    ``Sharding.devices_indices_map``) into a box."""
    offsets: List[int] = []
    sizes: List[int] = []
    index = tuple(index) + (slice(None),) * (len(shape) - len(index))
    for idx, dim in zip(index, shape):
        if isinstance(idx, slice):
            start, stop, step = idx.indices(int(dim))
            if step != 1:
                raise ValueError(f"strided shard index unsupported: {idx}")
            offsets.append(start)
            sizes.append(stop - start)
        else:  # int index — treat as size-1 slice
            offsets.append(int(idx))
            sizes.append(1)
    return tuple(offsets), tuple(sizes)


def box_nelems(box: Box) -> int:
    n = 1
    for s in box[1]:
        n *= s
    return n


def box_intersect(a: Box, b: Box) -> Optional[Box]:
    offsets: List[int] = []
    sizes: List[int] = []
    for (ao, as_), (bo, bs) in zip(zip(*a), zip(*b)):
        lo = max(ao, bo)
        hi = min(ao + as_, bo + bs)
        if hi <= lo:
            return None
        offsets.append(lo)
        sizes.append(hi - lo)
    return tuple(offsets), tuple(sizes)


def relative_slices(inner: Box, outer: Box) -> Tuple[slice, ...]:
    """Slices selecting ``inner`` within an array whose region is ``outer``."""
    return tuple(
        slice(io - oo, io - oo + isz)
        for io, isz, oo in zip(inner[0], inner[1], outer[0])
    )


def is_dim0_slab(inner: Box, outer: Box) -> bool:
    """True iff ``inner`` spans the full extent of ``outer`` in every dim
    except (possibly) dim 0 — i.e. it is a contiguous row-range of the
    C-contiguous blob storing ``outer``."""
    for d, (io, isz, oo, osz) in enumerate(
        zip(inner[0], inner[1], outer[0], outer[1])
    ):
        if d == 0:
            continue
        if io != oo or isz != osz:
            return False
    return True
