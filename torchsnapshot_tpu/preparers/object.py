"""Object preparer: fallback path for arbitrary Python objects.

Reference: torchsnapshot/io_preparers/object.py:37-95 (torch.save/pickle).
Here the payload goes through the safe msgpack codec first, pickle only
behind the ALLOW_PICKLE_OBJECTS knob (see serialization.py).
"""

from __future__ import annotations

import asyncio
from concurrent.futures import Executor
from typing import Any, List, Optional, Tuple

from ..io_types import BufferConsumer, BufferStager, Future, ReadReq, WriteReq
from ..manifest import ObjectEntry
from ..serialization import deserialize_object, serialize_object


class ObjectBufferStager(BufferStager):
    """Objects are serialized eagerly at plan time: their size is unknown
    until encoded, and the reference treats object payloads as small
    (ObjectBufferStager, object.py:69-82)."""

    def __init__(self, payload: bytes) -> None:
        self.payload = payload

    async def stage_buffer(self, executor: Optional[Executor] = None) -> bytes:
        return self.payload

    def get_staging_cost_bytes(self) -> int:
        return len(self.payload)


class ObjectBufferConsumer(BufferConsumer):
    def __init__(self, entry: ObjectEntry, fut: Future) -> None:
        self.entry = entry
        self.fut = fut

    async def consume_buffer(
        self, buf: Any, executor: Optional[Executor] = None
    ) -> None:
        loop = asyncio.get_running_loop()
        if executor is not None:
            obj = await loop.run_in_executor(
                executor, deserialize_object, buf, self.entry.serializer
            )
        else:
            obj = deserialize_object(buf, self.entry.serializer)
        self.fut.set(obj)

    def get_consuming_cost_bytes(self) -> int:
        return 1  # size unknown before the read; treat as negligible


class ObjectIOPreparer:
    @staticmethod
    def prepare_write(
        obj: Any, location: str, replicated: bool
    ) -> Tuple[ObjectEntry, List[WriteReq]]:
        payload, serializer = serialize_object(obj)
        entry = ObjectEntry(
            location=location, serializer=serializer, replicated=replicated
        )
        return entry, [
            WriteReq(
                path=location,
                buffer_stager=ObjectBufferStager(payload),
                checksum_sinks=[
                    (lambda c, e=entry: setattr(e, "crc32", c), None)
                ],
            )
        ]

    @staticmethod
    def prepare_read(entry: ObjectEntry) -> Tuple[List[ReadReq], Future]:
        fut: Future = Future()
        byte_range = getattr(entry, "byte_range", None)
        return (
            [
                ReadReq(
                    path=entry.location,
                    byte_range=list(byte_range) if byte_range else None,
                    buffer_consumer=ObjectBufferConsumer(entry, fut),
                    expected_crc32=getattr(entry, "crc32", None),
                )
            ],
            fut,
        )
