"""Array preparer: write/read planning for host arrays and single-device
``jax.Array``s, plus the chunked variant for big arrays.

Reference: torchsnapshot/io_preparers/tensor.py:50-409 and
io_preparers/chunked_tensor.py:36-128.  TPU-native differences:

- The device→host copy is ``jax.Array.copy_to_host_async()`` (launched at
  staging-admission time on XLA's transfer stream) followed by
  ``np.asarray`` in a worker thread — the analogue of the reference's CUDA
  DtoH in a thread pool with the GIL released
  (io_preparers/tensor.py:249-255).
- Chunked staging slices the array **on device** (bounded HBM copy) so host
  memory stays bounded by the chunk size while D2H overlaps storage I/O.
- Defensive copies for async snapshots apply only to *host* arrays
  (numpy/torch): a jax.Array is immutable, so its staged bytes can never be
  mutated by training — the reference's hardest async-safety problem
  (io_preparers/tensor.py:283-307) disappears by construction.
"""

from __future__ import annotations

import asyncio
import contextlib
import functools
import threading
from concurrent.futures import Executor
from typing import Any, List, Optional, Tuple

import numpy as np

from .. import knobs, obs
from ..io_types import BufferConsumer, BufferStager, Future, ReadReq, WriteReq
from ..manifest import ArrayEntry, ChunkedArrayEntry, Shard
import logging

from ..serialization import (
    BUFFER_PROTOCOL,
    array_as_memoryview,
    array_from_buffer,
    dtype_to_string,
    fast_copy,
    fast_copyto,
    serialized_size_bytes,
    string_to_dtype,
)

logger = logging.getLogger(__name__)

# gates restore-path H2D transfers when knobs.serialize_transfers() is on
_TRANSFER_LOCK = threading.Lock()


@contextlib.contextmanager
def transfer_gate(gated: "bool | None" = None):
    """Serialize H2D transfers across consumer threads when
    ``knobs.serialize_transfers()`` resolves on (see knobs.py).

    Yields a list the caller appends in-flight arrays to; when gating is
    active the gate blocks on them BEFORE releasing the lock —
    ``device_put`` returns before the DMA completes, so releasing at
    dispatch would let other threads' transfers overlap anyway.

    ``gated`` lets a caller that already read the knob pin the decision
    (a caller branching on its own read while the gate re-reads would
    race a concurrent override into compiling outside the lock)."""
    pending: List[Any] = []
    if gated is None:
        gated = knobs.serialize_transfers()
    if not gated:
        yield pending
        return
    import jax

    with _TRANSFER_LOCK:
        yield pending
        if pending:
            jax.block_until_ready(pending)


@functools.lru_cache(maxsize=256)
def _root_module(tp: type) -> str:
    # called several times per leaf on the planning path (the
    # async_take blocked window); cached on the type object
    return tp.__module__.split(".")[0]


def _is_torch_tensor(obj: Any) -> bool:
    return _root_module(type(obj)) == "torch"


def _is_jax_array(obj: Any) -> bool:
    if _root_module(type(obj)) not in ("jax", "jaxlib"):
        return False
    import jax

    return isinstance(obj, jax.Array)


def donate_template(arr: Any) -> None:
    """Free a jax restore-template's device buffers as soon as its
    replacement has materialized, so restore's device peak stays at ~1x
    payload + one leaf instead of 2x (all templates + all restored) —
    the jax analogue of the reference's in-place load into pre-allocated
    tensors (snapshot.py:743-753, io_preparers/tensor.py:91-126).

    Called strictly AFTER the replacement is visible through the leaf's
    Future (``fut.set`` precedes donation at every call site), never
    before: a restore that fails mid-leaf (transfer wedge, H2D OOM)
    leaves THAT leaf's template intact, and every already-donated
    template has a retrievable replacement.  A failure on a LATER leaf
    of the same stateful therefore cannot strand deleted arrays in the
    caller's live state: the repair path in
    ``Snapshot._restore_stateful`` loads the already-restored leaves
    (non-strict, mixed old/new — the reference's in-place load has the
    same mid-failure semantics, snapshot.py:743-753) before re-raising.

    ``delete()`` frees the buffers while keeping shape/dtype/sharding
    metadata valid, which is all any later step needs.  Aliased leaves
    (one array as the template for several paths) are safe: the second
    donation sees ``is_deleted()`` and no-ops, and each path's restored
    array is built from storage bytes, never from the template."""
    mode = knobs.restore_donation()
    if mode == "off":
        return
    if mode == "auto":
        try:
            on_accel = all(d.platform != "cpu" for d in arr.devices())
        except Exception:  # noqa: BLE001 — e.g. inside a transform
            on_accel = False
        if not on_accel:
            return
    try:
        if not arr.is_deleted():
            arr.delete()
            DONATION_STATS["donated_templates"] += 1
    except Exception as e:  # donation is an optimization, never fatal
        logger.debug("template donation skipped: %r", e)


# observability for the bench's mechanisms block: how many restore
# templates were actually freed (the 1x-restore evidence)
DONATION_STATS = {"donated_templates": 0}


def is_array_like(obj: Any) -> bool:
    if isinstance(obj, np.ndarray):
        return True
    if _is_jax_array(obj):
        return True
    if _is_torch_tensor(obj):
        import torch

        return isinstance(obj, torch.Tensor)
    return False


def _to_host_view(obj: Any) -> np.ndarray:
    """Zero-copy host view when possible (torch CPU → numpy shares memory)."""
    if isinstance(obj, np.ndarray):
        return obj
    if _is_torch_tensor(obj):
        return obj.detach().cpu().numpy()
    raise TypeError(type(obj))


def array_nbytes(obj: Any) -> int:
    if _is_torch_tensor(obj):
        obj = _to_host_view(obj)
    return serialized_size_bytes(obj.shape, obj.dtype)


def array_dtype_str(obj: Any) -> str:
    if _is_torch_tensor(obj):
        obj = _to_host_view(obj)
    return dtype_to_string(obj.dtype)


class JaxArrayBufferStager(BufferStager):
    """Stage a (slice of a) single-device/replicated jax.Array: launch the
    async D2H transfer, then materialize to numpy in a worker thread."""

    def __init__(self, arr: Any, index: Optional[Tuple] = None, nbytes: int = 0):
        self.arr = arr
        self.index = index
        self.nbytes = nbytes or array_nbytes(arr)
        # Set by eager_offload_write_reqs when it re-points ``arr`` at an
        # in-flight pinned-host copy: the original (immutable) device array,
        # kept so an asynchronous offload failure (e.g. pinned-host
        # allocation) degrades to staging straight from the device instead
        # of failing the snapshot.  Cleared the moment the host copy
        # materializes successfully.
        self.fallback_arr: Any = None

    async def stage_buffer(self, executor: Optional[Executor] = None) -> memoryview:
        loop = asyncio.get_running_loop()

        def _materialize(src: Any) -> np.ndarray:
            is_deleted = getattr(src, "is_deleted", None)
            if callable(is_deleted) and is_deleted():
                # A training step deleted the buffer this write was going
                # to stage from — the donate_argnums hazard.  Fail with a
                # diagnosis instead of XLA's bare "Array has been deleted".
                if self.index is not None:
                    why = (
                        "this leaf is a chunk of an array over "
                        "MAX_CHUNK_SIZE_BYTES; chunks slice on device "
                        "and always stage lazily. With donation, call "
                        "pending.wait() before the next step (or raise "
                        "the chunk-size knob so the array is offloaded "
                        "whole)."
                    )
                else:
                    why = (
                        "this leaf staged lazily (eager-offload budget "
                        "exceeded, or host memory kinds unavailable). "
                        "Raise TORCHSNAPSHOT_TPU_PER_RANK_MEMORY_BUDGET_"
                        "BYTES, or call pending.wait() before the next "
                        "step."
                    )
                raise RuntimeError(
                    "device array was deleted before async-snapshot "
                    "staging — usually jit(donate_argnums=...) donated "
                    "the train state on the step after async_take. "
                    "Offloaded leaves are immune; " + why
                )
            a = src if self.index is None else src[self.index]
            try:
                a.copy_to_host_async()
            except Exception as e:
                # some array types (fully replicated committed) decline
                # the async prefetch; np.asarray below does the copy
                # synchronously either way
                obs.swallowed_exception("array_stager.copy_to_host_async", e)
            return np.asarray(a)

        async def _run(src: Any) -> np.ndarray:
            if executor is not None:
                return await loop.run_in_executor(executor, _materialize, src)
            return _materialize(src)

        try:
            np_arr = await _run(self.arr)
        except Exception:
            fallback = self.fallback_arr
            if fallback is None:
                raise
            logger.warning(
                "eager pinned-host offload failed asynchronously; staging "
                "from the device array instead (safe: jax.Array is immutable)",
                exc_info=True,
            )
            np_arr = await _run(fallback)
        self.arr = None  # drop refs as early as possible
        self.fallback_arr = None
        return array_as_memoryview(np_arr)

    def get_staging_cost_bytes(self) -> int:
        return self.nbytes


class HostArrayBufferStager(BufferStager):
    """Stage a host (numpy / torch CPU) array. For async snapshots, take a
    defensive copy at staging time: the caller may mutate the source before
    storage I/O completes (reference io_preparers/tensor.py:283-307)."""

    def __init__(self, arr: np.ndarray, defensive_copy: bool):
        self.arr = arr
        self.defensive_copy = defensive_copy
        # Set when the stager holds a private copy (eager offload took the
        # defensive copy early); staging then drops the ref so the copy is
        # freed as soon as its storage write completes, matching the
        # scheduler's budget credits.
        self.owns_arr = False

    async def stage_buffer(self, executor: Optional[Executor] = None) -> memoryview:
        arr = self.arr
        if self.defensive_copy:
            loop = asyncio.get_running_loop()
            if executor is not None:
                arr = await loop.run_in_executor(executor, fast_copy, arr)
            else:
                arr = fast_copy(arr)
            self.arr = None
        elif self.owns_arr:
            self.arr = None
        return array_as_memoryview(arr)

    # ------------------------------------------------- part streaming
    # A host array is the one source whose bytes exist BEFORE staging,
    # so it can stage per part for the scheduler's stripe stream path:
    # each part is a view (sync take: zero copy) or a part-sized
    # defensive copy (async take: the copy that used to be whole-object
    # now peaks at the stream window), and the part's write dispatches
    # while later parts are still copying.

    def part_plan(self, part_size_bytes: int):
        arr = self.arr
        if self.defensive_copy:
            # an async take that still needs its defensive copy must
            # take it WHOLE at staging time: per-part copies would move
            # the unblock point (staging_done, which streams delay to
            # ~write completion) from one memcpy to the whole upload.
            # Eager offload clears this flag once it owns a private
            # copy, so offloaded async leaves still stream.
            return None
        if (
            arr is None
            or not arr.flags["C_CONTIGUOUS"]
            or arr.dtype.byteorder == ">"
        ):
            # staging whole would copy/normalize anyway — per-part
            # staging on top of that would re-copy the object per part
            return None
        from ..storage.stripe import plan_parts

        return plan_parts(arr.nbytes, part_size_bytes)

    async def stage_part(
        self, span, executor: Optional[Executor] = None
    ):
        lo, hi = span
        view = array_as_memoryview(self.arr)[lo:hi]
        if not self.defensive_copy:
            return view

        def copy() -> np.ndarray:
            dst = np.empty(hi - lo, dtype=np.uint8)
            np.copyto(dst, np.frombuffer(view, dtype=np.uint8))
            return dst

        if executor is not None:
            return await asyncio.get_running_loop().run_in_executor(
                executor, copy
            )
        return copy()

    def release_source(self) -> None:
        self.arr = None

    def get_staging_cost_bytes(self) -> int:
        return self.arr.nbytes if self.arr is not None else 0


def materialize_into_template(np_arr: np.ndarray, obj_out: Any) -> Any:
    """Place host data into/onto the restore template.

    - numpy template: in-place copy (casts if needed) — keeps the 1× memory
      property of the reference's in-place load (snapshot.py:743-753).
    - torch CPU template: in-place copy through the shared-memory view.
    - jax template: ``device_put`` honoring the template's sharding (the
      result is a new immutable array).
    - no template: a fresh numpy array.
    """
    if obj_out is None:
        return np_arr.copy()
    if isinstance(obj_out, np.ndarray):
        fast_copyto(obj_out, np_arr.reshape(obj_out.shape))
        return obj_out
    if _is_torch_tensor(obj_out):
        import torch

        view = obj_out.detach().cpu().numpy()
        fast_copyto(view, np_arr.reshape(view.shape))
        return obj_out
    if _is_jax_array(obj_out):
        import jax

        from .. import knobs

        if np.dtype(np_arr.dtype) != np.dtype(obj_out.dtype):
            np_arr = np_arr.astype(obj_out.dtype)
        shaped = np_arr.reshape(obj_out.shape)
        sharding = obj_out.sharding
        # consumers run on an executor: gate concurrent H2D puts behind
        # one lock — a chip has one DMA engine per direction, and
        # multiplexed transports can interleave concurrent transfers
        # pathologically (observed as a multi-minute wedge on a tunneled
        # PJRT attachment)
        with transfer_gate() as pending:
            out = jax.device_put(shaped, sharding)
            pending.append(out)
        # NOTE: the template is NOT donated here.  Callers donate only
        # after the replacement is visible through the leaf's Future
        # (fut.set then donate_template), so a donated template always
        # implies a retrievable replacement — the invariant the
        # failed-restore repair path in snapshot.py relies on.
        return out
    # Template is some other leaf (e.g. a Python scalar where the saved
    # state had a traced jax scalar, like TrainState.step before/after the
    # first jitted step). Behave like "no template": return fresh host data.
    return np_arr.copy()


class ArrayBufferConsumer(BufferConsumer):
    def __init__(
        self, entry: ArrayEntry, obj_out: Any, fut: Future, into: Any = None
    ):
        self.entry = entry
        self.obj_out = obj_out
        self.fut = fut
        self.into = into

    # below this, the executor thread-hop costs more than the copy —
    # a 20k-tiny-leaf restore spends most of its wall time in loop
    # wakeups and submits without this short-circuit.  HOST templates
    # only: a jax template's materialize enters transfer_gate(), whose
    # blocking lock + block_until_ready must NEVER run on the event
    # loop thread (a gated wedge would freeze all restore I/O).
    _INLINE_CONSUME_MAX = 256 * 1024

    async def consume_buffer(
        self, buf: Any, executor: Optional[Executor] = None
    ) -> None:
        if self.into is not None and buf is self.into:
            # the plugin honored the in-place hint: the template already
            # holds the payload bytes — nothing to copy or cast
            self.fut.set(self.obj_out)
            return
        np_arr = array_from_buffer(
            buf, self.entry.dtype, tuple(self.entry.shape)
        )
        if self.obj_out is None:
            from ..io_types import is_mmap_backed

            if is_mmap_backed(buf):
                # zero-copy materialization: the result IS the mapping
                # (a read-only view over file-backed pages) — no heap
                # copy before the caller's device put.  Pages fault in
                # on first touch and stay kernel-reclaimable, which is
                # what keeps a many-reader cold start's RSS flat.
                self.fut.set(np_arr)
                return
        inline = (
            np_arr.nbytes < self._INLINE_CONSUME_MAX
            and not _is_jax_array(self.obj_out)
        )
        if executor is not None and not inline:
            loop = asyncio.get_running_loop()
            result = await loop.run_in_executor(
                executor, materialize_into_template, np_arr, self.obj_out
            )
        else:
            result = materialize_into_template(np_arr, self.obj_out)
        self.fut.set(result)
        if result is not self.obj_out:
            # strictly after fut.set: donated ⟹ replacement reachable
            donate_template(self.obj_out)

    def get_consuming_cost_bytes(self) -> int:
        return serialized_size_bytes(self.entry.shape, string_to_dtype(self.entry.dtype))


class _TiledConsumer(BufferConsumer):
    """Consume one byte-range tile into a region of the target host buffer
    (reference prepare_read_tiled, io_preparers/tensor.py:128-181)."""

    def __init__(
        self,
        target_flat: np.ndarray,
        elem_range: Tuple[int, int],
        countdown: "_Countdown",
        tile_bytes: int,
        dtype: str,
        crc_fold: Optional["_TileCrcFold"] = None,
    ):
        self.target_flat = target_flat
        self.elem_range = elem_range
        self.countdown = countdown
        self.tile_bytes = tile_bytes
        self.dtype = dtype
        self.crc_fold = crc_fold

    async def consume_buffer(
        self, buf: Any, executor: Optional[Executor] = None
    ) -> None:
        start, end = self.elem_range
        if self.crc_fold is not None:
            self.crc_fold.record(start, buf)
        np_arr = array_from_buffer(buf, self.dtype, (end - start,))
        fast_copyto(self.target_flat[start:end], np_arr)
        self.countdown.step()

    def get_consuming_cost_bytes(self) -> int:
        return self.tile_bytes


class _DeviceTileAcc:
    """Shared flat device accumulator for a budgeted read into a jax
    template: each tile chains a donated ``dynamic_update_slice``
    (``ops.device_pack.tile_update_device``), so device peak stays at
    ~1x the target plus one tile and host peak at O(budget) — the
    reference's bounded-RSS random-access property
    (benchmarks/load_tensor) extended to DEVICE targets, which is the
    TPU-native case.  The user's template seeds the chain and is
    consumed by the first update; on a mid-read failure the template is
    therefore already donated — accessing it raises jax's
    deleted-buffer error, a LOUDER outcome than the host tiled path's
    documented garbage-contents one (_TileCrcFold CONTRACT note).

    Updates are dispatched onto the scheduler's executor (the gate's
    lock + transfer block must NEVER run on the loop thread — see
    ArrayBufferConsumer), so concurrent tiles of the same read race on
    the chain: a per-accumulator lock serializes them.  Tiles cover
    disjoint ranges, so completion order is irrelevant.  Construction
    happens at PLAN time on the caller thread and pre-compiles every
    executable the chain will dispatch — flatten, tile updates, final
    reshape (``warm_tile_updates``) — so worker threads never compile,
    which keeps this path safe on tunneled transports where a
    non-main-thread compile wedges (see knobs.device_unpack_enabled
    for that failure mode)."""

    def __init__(self, template, tile_sigs, payload_dtype) -> None:
        import jax
        from jax.sharding import SingleDeviceSharding

        from ..ops.device_pack import warm_tile_updates

        self.out_shape = tuple(template.shape)
        self.lock = threading.Lock()
        device = list(template.sharding.device_set)[0]
        n = int(np.prod(self.out_shape)) if self.out_shape else 1
        acc_dt = np.dtype(template.dtype)
        sharding = SingleDeviceSharding(device)

        def _aot(fn, *avals):
            return jax.jit(fn, donate_argnums=0).lower(*avals).compile()

        flat_aval = jax.ShapeDtypeStruct((n,), acc_dt, sharding=sharding)
        if self.out_shape != (n,):
            # seed the chain with a DONATED flatten: a plain .reshape(-1)
            # of a multi-d template leaves the caller's array alive for
            # the whole read (2x device peak, no deleted-buffer signal)
            shaped_aval = jax.ShapeDtypeStruct(
                self.out_shape, acc_dt, sharding=sharding
            )
            self.acc = _aot(lambda a: a.reshape((n,)), shaped_aval)(template)
            out_shape = self.out_shape
            self._reshape = _aot(lambda a: a.reshape(out_shape), flat_aval)
        else:
            self.acc = template
            self._reshape = None
        warm_tile_updates(
            n,
            acc_dt,
            tuple(
                (t1 - t0, np.dtype(string_to_dtype(payload_dtype)))
                for t0, t1 in tile_sigs
            ),
            device,
        )

    def update(self, tile_np: np.ndarray, off: int) -> None:
        from ..ops.device_pack import tile_update_device

        with self.lock:
            self.acc = tile_update_device(self.acc, tile_np, off)

    def finish(self):
        if self._reshape is None:
            return self.acc
        return self._reshape(self.acc)


class _DeviceTiledConsumer(BufferConsumer):
    """Consume one byte-range tile into a shared device accumulator
    (the jax-template twin of _TiledConsumer)."""

    def __init__(
        self,
        acc: "_DeviceTileAcc",
        elem_range: Tuple[int, int],
        countdown: "_Countdown",
        tile_bytes: int,
        dtype: str,
        crc_fold: Optional["_TileCrcFold"] = None,
    ):
        self.acc = acc
        self.elem_range = elem_range
        self.countdown = countdown
        self.tile_bytes = tile_bytes
        self.dtype = dtype
        self.crc_fold = crc_fold

    async def consume_buffer(
        self, buf: Any, executor: Optional[Executor] = None
    ) -> None:
        start, end = self.elem_range
        if self.crc_fold is not None:
            self.crc_fold.record(start, buf)
        np_arr = array_from_buffer(buf, self.dtype, (end - start,))
        if executor is not None:
            # the update runs transfer_gate (lock + block on the DMA),
            # which must never block the scheduler loop thread — same
            # rule as ArrayBufferConsumer's materialize dispatch
            await asyncio.get_running_loop().run_in_executor(
                executor, self.acc.update, np_arr, start
            )
        else:
            self.acc.update(np_arr, start)
        self.countdown.step()

    def get_consuming_cost_bytes(self) -> int:
        return self.tile_bytes


class _Countdown:
    """Run ``on_zero`` after N consume steps complete (consumers all run on
    the scheduler's single loop thread, so a plain counter suffices)."""

    def __init__(self, n: int, on_zero) -> None:
        self.n = n
        self.on_zero = on_zero

    def step(self) -> None:
        self.n -= 1
        if self.n == 0:
            self.on_zero()


def _plan_flat_tiles(
    c0: int, c1: int, itemsize: int, budget_bytes: int, base_byte: int = 0
) -> List[Tuple[int, int, List[int]]]:
    """Split flat element range [c0, c1) into budget-sized tiles.

    Returns (t0, t1, byte_range) per tile; byte_range is relative to the
    stored object (``base_byte`` = the region's offset inside it, for
    slab-batched payloads).  Shared by the plain, chunked, and sharded
    (one "element" per dim-0 row) tiled-read paths so the tile math
    cannot drift between them."""
    elems_per_tile = max(1, budget_bytes // itemsize)
    tiles = []
    for t0 in range(c0, c1, elems_per_tile):
        t1 = min(t0 + elems_per_tile, c1)
        tiles.append(
            (
                t0,
                t1,
                [
                    base_byte + (t0 - c0) * itemsize,
                    base_byte + (t1 - c0) * itemsize,
                ],
            )
        )
    return tiles


class _TileCrcFold:
    """Integrity checking for a tiled region: byte-range reads cannot be
    checked individually against the recorded whole-object crc32, so each
    tile contributes the crc32 of its RAW payload bytes (hashed before
    any dtype cast into the target — a float32 payload restored into a
    float64 template must still verify against the stored bytes), and on
    completion the per-tile values fold via crc32_combine in offset order
    (tiles complete out of order).  Work on the scheduler's loop thread
    stays O(tile), never O(region); the final fold is O(tiles·log n)
    integer math.  Same VERIFY_ON_RESTORE gate as io_types.check_read_crc;
    tiling must not silently weaken integrity checking.

    CONTRACT under budgets: tiles are written into the target BEFORE the
    fold can detect corruption (pre-verifying would need an O(region)
    scratch buffer, which the memory budget exists to forbid), so on a
    detected mismatch the read raises but the output buffer's contents
    are unspecified.  The unbudgeted path verifies before any copy and
    leaves templates pristine on failure."""

    def __init__(self, expected_crc32, what: str, then) -> None:
        self.expected = expected_crc32
        self.what = what
        self.then = then
        self.want = expected_crc32 is not None and knobs.verify_on_restore()
        self.pieces: dict = {}  # tile start offset -> (crc32, nbytes)

    def record(self, start: int, buf) -> None:
        if not self.want:
            return
        from ..utils.checksums import crc32_fast

        view = memoryview(buf).cast("B")
        self.pieces[start] = (crc32_fast(view), view.nbytes)

    def finish(self) -> None:
        if self.want:
            from ..utils.checksums import crc32_combine

            actual, _total = 0, 0
            for start in sorted(self.pieces):
                crc, nbytes = self.pieces[start]
                actual = crc32_combine(actual, crc, nbytes)
            if actual != self.expected:
                raise RuntimeError(
                    f"crc32 mismatch for {self.what}: recorded "
                    f"crc32={self.expected}, assembled-from-tiles "
                    f"crc32={actual} — the payload changed after commit "
                    f"(output buffer contents are unspecified)"
                )
        self.then()


class ArrayIOPreparer:
    """Reference TensorIOPreparer (io_preparers/tensor.py:50-126)."""

    @staticmethod
    def prepare_write(
        obj: Any, location: str, replicated: bool, is_async_snapshot: bool
    ) -> Tuple[ArrayEntry, List[WriteReq]]:
        entry = ArrayEntry(
            location=location,
            serializer=BUFFER_PROTOCOL,
            dtype=array_dtype_str(obj),
            shape=list(obj.shape),
            replicated=replicated,
        )
        if _is_jax_array(obj):
            stager: BufferStager = JaxArrayBufferStager(obj)
        else:
            stager = HostArrayBufferStager(
                _to_host_view(obj), defensive_copy=is_async_snapshot
            )
        # codec preconditioning hint: float payloads byte-shuffle before
        # compression (codec.filter_for_dtype; 0 disables the filter)
        from ..codec import filter_for_dtype

        stager.codec_filter_stride = filter_for_dtype(entry.dtype)
        return entry, [
            WriteReq(
                path=location,
                buffer_stager=stager,
                checksum_sinks=[
                    (lambda c, e=entry: setattr(e, "crc32", c), None)
                ],
            )
        ]

    @staticmethod
    def prepare_read(
        entry: ArrayEntry,
        obj_out: Any = None,
        buffer_size_limit_bytes: Optional[int] = None,
    ) -> Tuple[List[ReadReq], Future]:
        fut: Future = Future()
        total = serialized_size_bytes(entry.shape, string_to_dtype(entry.dtype))
        itemsize = string_to_dtype(entry.dtype).itemsize
        can_tile = (
            buffer_size_limit_bytes is not None
            and total > buffer_size_limit_bytes
            and entry.byte_range is None
            and (obj_out is None or isinstance(obj_out, np.ndarray)
                 or _is_torch_tensor(obj_out))
        )
        # jax-template twin: tiles stream through a donated device
        # accumulator chain, keeping host at O(budget) and device at
        # ~1x target + one tile (_DeviceTileAcc).  Single-device,
        # default-memory templates of the exact stored shape only.
        # Safe on every transport: ALL executables the chain dispatches
        # are AOT-compiled at plan time on the caller thread
        # (_DeviceTileAcc.__init__), never lazily on a worker thread
        # (see knobs.device_unpack_enabled for the tunnel wedge that
        # rule avoids).  Element offsets ride int32 dynamic-slice
        # indices, so ≥2^31-element arrays (8GB+ float32 — only
        # reachable with the chunking knob raised) fall back to the
        # whole-buffer path rather than overflow.
        can_device_tile = (
            not can_tile
            and buffer_size_limit_bytes is not None
            and total > buffer_size_limit_bytes
            and entry.byte_range is None
            and _is_jax_array(obj_out)
            and len(obj_out.sharding.device_set) == 1
            and getattr(obj_out.sharding, "memory_kind", None)
            in (None, "device")
            and tuple(obj_out.shape) == tuple(entry.shape)
            and total // itemsize < np.iinfo(np.int32).max
        )
        if can_tile or can_device_tile:
            # Tile the flat element range so host memory stays O(limit).
            if can_device_tile:
                n_elems = int(np.prod(entry.shape)) if entry.shape else 1
            else:
                if obj_out is None:
                    target = np.empty(
                        tuple(entry.shape), dtype=string_to_dtype(entry.dtype)
                    )
                elif isinstance(obj_out, np.ndarray):
                    target = obj_out
                else:
                    target = obj_out.detach().cpu().numpy()
                target_flat = target.reshape(-1)
                n_elems = target_flat.shape[0]
            tiles = _plan_flat_tiles(
                0, n_elems, itemsize, buffer_size_limit_bytes
            )
            if can_device_tile:
                acc = _DeviceTileAcc(
                    obj_out,
                    {(t0, t1) for t0, t1, _ in tiles},
                    entry.dtype,
                )
                on_all_tiles = lambda: fut.set(acc.finish())  # noqa: E731
            else:
                on_all_tiles = lambda: fut.set(  # noqa: E731
                    target
                    if obj_out is None or isinstance(obj_out, np.ndarray)
                    else obj_out
                )
            fold = _TileCrcFold(
                getattr(entry, "crc32", None),
                f"{entry.location} (tiled)",
                on_all_tiles,
            )
            countdown = _Countdown(n=len(tiles), on_zero=fold.finish)
            read_reqs: List[ReadReq] = []
            for start, end, byte_range in tiles:
                if can_device_tile:
                    consumer: BufferConsumer = _DeviceTiledConsumer(
                        acc=acc,
                        elem_range=(start, end),
                        countdown=countdown,
                        tile_bytes=(end - start) * itemsize,
                        dtype=entry.dtype,
                        crc_fold=fold,
                    )
                else:
                    consumer = _TiledConsumer(
                        target_flat=target_flat,
                        elem_range=(start, end),
                        countdown=countdown,
                        tile_bytes=(end - start) * itemsize,
                        dtype=entry.dtype,
                        crc_fold=fold,
                    )
                read_reqs.append(
                    ReadReq(
                        path=entry.location,
                        byte_range=byte_range,
                        buffer_consumer=consumer,
                    )
                )
            return read_reqs, fut
        # In-place hint: a numpy template with the stored dtype and
        # exactly the payload's bytes lets an honoring plugin read
        # straight into the template (one pass, no intermediate buffer
        # and no copy — the reference's read-into-preallocated-tensor
        # property, io_preparers/tensor.py:91-126).  Consumers detect
        # honor by identity, so plugins without the fast path are
        # unaffected.
        into = None
        if (
            isinstance(obj_out, np.ndarray)
            and obj_out.dtype == string_to_dtype(entry.dtype)
            and obj_out.flags["C_CONTIGUOUS"]
            and not obj_out.flags["WRITEBACKIFCOPY"]
            and obj_out.nbytes == total
            # VERIFY_ON_RESTORE's unbudgeted contract is verify-before-
            # copy (templates stay pristine on a crc mismatch); reading
            # in place would dirty the template before the check runs
            and not knobs.verify_on_restore()
        ):
            into = obj_out
        return (
            [
                ReadReq(
                    path=entry.location,
                    byte_range=list(entry.byte_range) if entry.byte_range else None,
                    buffer_consumer=ArrayBufferConsumer(
                        entry, obj_out, fut, into=into
                    ),
                    expected_crc32=getattr(entry, "crc32", None),
                    into=into,
                )
            ],
            fut,
        )


def _chunk_dim0(shape: List[int], dtype: Any, max_chunk_bytes: int) -> List[Tuple[int, int]]:
    """Row ranges [(start, end), ...] such that each chunk ≤ max_chunk_bytes
    (reference chunk_tensor, io_preparers/chunked_tensor.py:36-65)."""
    if not shape or shape[0] == 0:
        return [(0, shape[0] if shape else 0)]
    row_bytes = serialized_size_bytes(shape[1:], dtype) if len(shape) > 1 else np.dtype(dtype).itemsize
    rows_per_chunk = max(1, max_chunk_bytes // max(1, row_bytes))
    return [
        (r, min(r + rows_per_chunk, shape[0]))
        for r in range(0, shape[0], rows_per_chunk)
    ]


class ChunkedArrayIOPreparer:
    """Reference ChunkedTensorIOPreparer (io_preparers/chunked_tensor.py)."""

    @staticmethod
    def prepare_write(
        obj: Any, location: str, replicated: bool, is_async_snapshot: bool
    ) -> Tuple[ChunkedArrayEntry, List[WriteReq]]:
        dtype = obj.dtype
        shape = list(obj.shape)
        ndim = len(shape)
        chunks: List[Shard] = []
        write_reqs: List[WriteReq] = []
        for (r0, r1) in _chunk_dim0(shape, dtype, knobs.get_max_chunk_size_bytes()):
            chunk_location = f"{location}_{r0}_{r1}"
            sizes = [r1 - r0] + shape[1:]
            chunks.append(
                Shard(
                    offsets=[r0] + [0] * (ndim - 1),
                    sizes=sizes,
                    location=chunk_location,
                )
            )
            nbytes = serialized_size_bytes(sizes, dtype)
            if _is_jax_array(obj):
                stager: BufferStager = JaxArrayBufferStager(
                    obj, index=(slice(r0, r1),), nbytes=nbytes
                )
            else:
                stager = HostArrayBufferStager(
                    _to_host_view(obj)[r0:r1], defensive_copy=is_async_snapshot
                )
            from ..codec import filter_for_dtype

            stager.codec_filter_stride = filter_for_dtype(
                array_dtype_str(obj)
            )
            write_reqs.append(
                WriteReq(
                    path=chunk_location,
                    buffer_stager=stager,
                    checksum_sinks=[
                        (
                            lambda c, s=chunks[-1]: setattr(s, "crc32", c),
                            None,
                        )
                    ],
                )
            )
        entry = ChunkedArrayEntry(
            dtype=array_dtype_str(obj),
            shape=shape,
            chunks=chunks,
            replicated=replicated,
        )
        return entry, write_reqs

    @staticmethod
    def prepare_read(
        entry: ChunkedArrayEntry,
        obj_out: Any = None,
        buffer_size_limit_bytes: Optional[int] = None,
    ) -> Tuple[List[ReadReq], Future]:
        fut: Future = Future()
        dtype = string_to_dtype(entry.dtype)
        # Host-side assembly buffer; written into by each chunk's consumer.
        if isinstance(obj_out, np.ndarray) and obj_out.dtype == dtype:
            host_buf = obj_out
        else:
            host_buf = np.empty(tuple(entry.shape), dtype=dtype)

        def on_done() -> None:
            if host_buf is obj_out:
                fut.set(obj_out)
            else:
                result = materialize_into_template(host_buf, obj_out)
                fut.set(result)
                if result is not obj_out:
                    donate_template(obj_out)

        # Budget-aware tiling (reference prepare_read_tiled semantics
        # extended to chunks): a chunk is a dim-0 row range, so in flat
        # element space it is CONTIGUOUS — each over-budget chunk splits
        # into byte-range tiles written straight into the target, keeping
        # host memory O(limit) instead of O(chunk) (the reference's
        # load_tensor benchmark contract, benchmarks/load_tensor/main.py).
        # One outer step per chunk; a tiled chunk steps the outer
        # countdown only after its tiles land AND the assembled region
        # passes the recorded crc32 (VERIFY_ON_RESTORE).
        itemsize = dtype.itemsize
        row_elems = 1
        for s in entry.shape[1:]:
            row_elems *= s
        can_tile_into = (
            buffer_size_limit_bytes is not None
            and host_buf.flags["C_CONTIGUOUS"]
        )
        outer = _Countdown(n=len(entry.chunks), on_zero=on_done)
        host_flat = host_buf.reshape(-1) if can_tile_into else None
        read_reqs: List[ReadReq] = []
        for chunk in entry.chunks:
            r0 = chunk.offsets[0]
            r1 = r0 + chunk.sizes[0]
            chunk_bytes = serialized_size_bytes(chunk.sizes, dtype)
            if can_tile_into and chunk_bytes > buffer_size_limit_bytes:
                c0 = r0 * row_elems
                c1 = r1 * row_elems
                tiles = _plan_flat_tiles(
                    c0,
                    c1,
                    itemsize,
                    buffer_size_limit_bytes,
                    base_byte=chunk.byte_range[0] if chunk.byte_range else 0,
                )
                fold = _TileCrcFold(
                    chunk.crc32, f"{chunk.location} (tiled)", outer.step
                )
                inner = _Countdown(n=len(tiles), on_zero=fold.finish)
                for t0, t1, byte_range in tiles:
                    read_reqs.append(
                        ReadReq(
                            path=chunk.location,
                            byte_range=byte_range,
                            buffer_consumer=_TiledConsumer(
                                target_flat=host_flat,
                                elem_range=(t0, t1),
                                countdown=inner,
                                tile_bytes=(t1 - t0) * itemsize,
                                dtype=entry.dtype,
                                crc_fold=fold,
                            ),
                        )
                    )
            else:
                read_reqs.append(
                    ReadReq(
                        path=chunk.location,
                        byte_range=list(chunk.byte_range)
                        if chunk.byte_range
                        else None,
                        buffer_consumer=_ChunkConsumer(
                            host_buf=host_buf,
                            row_range=(r0, r1),
                            sizes=list(chunk.sizes),
                            dtype=entry.dtype,
                            countdown=outer,
                        ),
                        expected_crc32=chunk.crc32,
                    )
                )
        return read_reqs, fut


class _ChunkConsumer(BufferConsumer):
    def __init__(self, host_buf, row_range, sizes, dtype, countdown):
        self.host_buf = host_buf
        self.row_range = row_range
        self.sizes = sizes
        self.dtype = dtype
        self.countdown = countdown

    async def consume_buffer(
        self, buf: Any, executor: Optional[Executor] = None
    ) -> None:
        r0, r1 = self.row_range
        np_arr = array_from_buffer(buf, self.dtype, tuple(self.sizes))

        def copy() -> None:
            fast_copyto(self.host_buf[r0:r1], np_arr)

        loop = asyncio.get_running_loop()
        if executor is not None:
            await loop.run_in_executor(executor, copy)
        else:
            copy()
        self.countdown.step()

    def get_consuming_cost_bytes(self) -> int:
        return serialized_size_bytes(self.sizes, string_to_dtype(self.dtype))
