"""The user-facing Snapshot API: take / async_take / restore / read_object.

TPU-native rebuild of the reference's top layer (torchsnapshot/
snapshot.py:112-1072).  The orchestration mirrors the reference call stacks
(SURVEY §3) with JAX-native replacements:

- control plane (path coalescing, key gathers, manifests) goes through a
  ``Coordinator`` — the jax.distributed KV service, not NCCL collectives,
- device→host staging is XLA async transfer inside the budgeted scheduler,
- the commit point is identical: ``.snapshot_metadata`` written by rank 0
  only after every rank finished its writes (reference snapshot.py:202-209)
  — a snapshot without it is by definition incomplete (snapshot.py:849-854),
- ``async_take`` returns as soon as the pending buffers are independent of
  training state: one batched device→pinned_host transfer plus eager
  defensive copies of mutable host arrays (host_offload.
  eager_offload_write_reqs) — *before* staging, not after it like the
  reference (its CUDA tensors are mutable; jax.Arrays are not).  Staging
  and storage I/O drain on the scheduler's loop thread and a background
  thread runs the commit barrier purely over KV — no collectives, so it
  can never race with training's ICI traffic (the reference's constraint
  at snapshot.py:1010 holds by construction).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import knobs, obs
from .batcher import batch_read_requests, batch_write_requests
from .coordination import Coordinator, get_default_coordinator
from .event import Event
from .event_handlers import log_event
from .flatten import flatten, inflate
from .io_types import Future, ReadReq, WriteIO, WriteReq
from .manifest import (
    MANIFEST_VERSION,
    ChunkedArrayEntry,
    Entry,
    Manifest,
    PrimitiveEntry,
    ShardedArrayEntry,
    SnapshotMetadata,
    entry_from_dict,
    is_container_entry,
)
from .manifest_ops import consolidate_manifests, get_manifest_for_rank
from .partitioner import elect_takeover_writers, partition_replicated_writes
from .preparers import (
    estimate_write_bytes,
    path_is_replicated,
    prepare_read,
    prepare_write,
)
from .preparers.sharded import is_multi_device_jax_array
from .resilience import SnapshotAbortedError
from .resilience.liveness import (
    DegradedSnapshotError,
    LivenessSession,
    RankDeadError,
)
from .serialization import serialize_object
from .scheduler import (
    PendingIOWork,
    get_process_memory_budget_bytes,
    sync_execute_read_reqs,
    sync_execute_write_reqs,
)
from .stateful import (
    Replicated,
    RNGState,
    Stateful,
    load_with_strict,
    unwrap,
)
from .storage import url_to_storage_plugin
from . import topology as topology_mod
from . import transport as transport_mod

logger = logging.getLogger(__name__)

def _storage_for(path: str, options: Optional[Dict[str, Any]]):
    """Build the storage plugin, passing storage_options only when set —
    tests and third parties monkeypatch ``url_to_storage_plugin`` with
    single-argument factories, which must keep working."""
    if options:
        return url_to_storage_plugin(path, options)
    return url_to_storage_plugin(path)


SNAPSHOT_METADATA_FNAME = ".snapshot_metadata"
AppState = Dict[str, Stateful]


def _read_priority_for(lpath: str, priority_globs: Sequence[str]) -> int:
    """Read-ordering class for a logical path under a ``priority`` glob
    list (restore/materialize): the index of the FIRST matching glob —
    lower executes earlier — with unmatched leaves after every named
    class.  Same fnmatch dialect as the ``paths`` filter."""
    import fnmatch

    for i, g in enumerate(priority_globs):
        if fnmatch.fnmatch(lpath, g):
            return i
    return len(priority_globs)


def _replication_fingerprint(obj: Any, mode: str = "full") -> Tuple:
    """Per-leaf fingerprint used to verify that state claimed replicated
    actually matches across ranks (reference intersects the per-rank
    *path* sets only, snapshot.py:637-670; this additionally fingerprints
    content, the failure mode most prone to silent divergence — e.g.
    per-rank optimizer scalars).

    ``mode`` (knob ``TORCHSNAPSHOT_TPU_REPLICATION_VERIFY``): "full" CRCs
    array content; "shape" checks arrays by dtype+shape only (O(1) per
    array — the knob exists for giant replicated host arrays; small
    non-array leaves keep their content check in every mode, since
    per-rank scalar drift is exactly what verification is for).  "off"
    is handled by the caller (no fingerprinting at all).

    - numpy / torch-CPU arrays: dtype, shape + crc32 of the FULL buffer
      (zlib.crc32 runs at ~3 GB/s; host replicated state is typically
      small — large state is jax arrays). A sampled check would miss
      divergence between windows, which is exactly the silent corruption
      this exists to prevent. Non-contiguous arrays are CRC'd in row
      blocks so the copy stays bounded.
    - jax arrays: dtype + shape only — content verification would force a
      device sync on the save path, and replication of jax arrays is
      already explicit in their sharding;
    - primitives: small values verbatim; floats by bit pattern (NaN would
      never compare equal to itself); long str/bytes by length + crc32 so
      multi-MB blobs never ride the coordination KV;
    - anything else: crc32 of its serialized form (content-verified, not
      just the type name).
    """
    import struct
    import zlib

    import numpy as np

    from .preparers.array import _is_jax_array, _is_torch_tensor, _to_host_view

    if isinstance(obj, float):
        return ("prim_f", struct.pack("<d", obj))
    if isinstance(obj, (str, bytes)):
        raw = obj.encode("utf-8", "surrogatepass") if isinstance(obj, str) else obj
        if len(raw) > 4096:
            return ("prim_big", type(obj).__name__, len(raw), zlib.crc32(raw))
        return ("prim", type(obj).__name__, obj)
    if isinstance(obj, (int, bool, type(None))):
        # concrete type in the tag: True == 1 but bool-vs-int divergence
        # across ranks must still demote
        return ("prim", type(obj).__name__, obj)
    if _is_jax_array(obj):
        return ("jax", str(obj.dtype), tuple(obj.shape))
    if isinstance(obj, np.ndarray) or _is_torch_tensor(obj):
        if mode == "shape":
            return ("arr", str(obj.dtype), tuple(obj.shape))
        view = _to_host_view(obj)
        if view.flags["C_CONTIGUOUS"]:
            crc = zlib.crc32(view.reshape(-1).view(np.uint8))
        elif view.ndim >= 1 and view.shape[0] > 1:
            crc = 0
            rows_per = max(1, (16 << 20) // max(1, view[:1].nbytes))
            for i in range(0, view.shape[0], rows_per):
                block = np.ascontiguousarray(view[i : i + rows_per])
                crc = zlib.crc32(block.reshape(-1).view(np.uint8), crc)
        else:
            block = np.ascontiguousarray(view)
            crc = zlib.crc32(block.reshape(-1).view(np.uint8))
        return ("arr", str(obj.dtype), tuple(obj.shape), crc)
    try:
        payload, _ = serialize_object(obj)
        return ("obj", type(obj).__name__, len(payload), zlib.crc32(payload))
    except Exception:
        return ("obj", type(obj).__name__)


def _safe_replication_verify_mode() -> str:
    """Resolve the knob WITHOUT raising: an invalid value on one rank must
    not diverge the collective protocol mid-take — fall back to the
    strict default with a warning instead."""
    try:
        return knobs.get_replication_verify()
    except ValueError as e:
        logger.warning("%s; falling back to 'full'", e)
        return "full"


def _strictest_mode(modes: Sequence[str]) -> str:
    return (
        "full" if "full" in modes
        else ("shape" if "shape" in modes else "off")
    )


def _verify_replicated_paths(
    flattened: Dict[str, Any],
    replicated_globs: Sequence[str],
    coordinator: Coordinator,
    mode: str,
) -> set:
    """The set of logical paths that are *verifiably* replicated: matched
    by the agreed globs on every rank, with identical fingerprints.
    Mismatches are demoted to per-rank entries with a warning — a corrupt
    'replicated' save (only one rank's copy persisted) is strictly worse
    than a larger correct one."""
    if not replicated_globs:
        # nothing can match: skip the KV round-trip entirely (all ranks
        # agree on the globs by this point, so all branch identically)
        return set()
    # "off" trusts content (fingerprint None) but still intersects path
    # PRESENCE across ranks: the partitioner requires its item list
    # identical on every rank, and a path only one rank has would
    # otherwise be assigned to a rank that can't write it (silently
    # dropping it from the snapshot).
    local = {
        lpath: (
            None if mode == "off" else _replication_fingerprint(obj, mode)
        )
        for lpath, obj in flattened.items()
        if path_is_replicated(lpath, replicated_globs)
    }
    if coordinator.world_size <= 1:
        return set(local)
    gathered = coordinator.all_gather_object(local)
    missing = object()
    verified = set()
    for lpath, fp in gathered[0].items():
        if all(peer.get(lpath, missing) == fp for peer in gathered[1:]):
            verified.add(lpath)
    demoted = set(local) - verified
    if demoted:
        logger.warning(
            "rank %d: %d path(s) matched replicated globs but differ "
            "across ranks; saving per-rank instead: %s",
            coordinator.rank,
            len(demoted),
            sorted(demoted)[:10],
        )
    return verified


def _ddp_module(stateful: Any) -> Optional[Any]:
    """The torch DDP instance behind ``stateful``, if there is one
    (directly, or wrapped in a ``TorchModuleAdapter``-style adapter
    exposing ``.module``)."""
    try:
        from torch.nn.parallel import DistributedDataParallel as DDP
    except Exception:  # torch absent/broken: nothing to infer
        return None
    for cand in (stateful, getattr(stateful, "module", None)):
        if isinstance(cand, DDP):
            return cand
    return None


def _infer_replicated(
    replicated: Sequence[str], app_state: Dict[str, Any]
) -> List[str]:
    """Auto-infer replication globs from the app state (reference
    _infer_replicated, snapshot.py:896-918).

    jax.Arrays need no help — replication is explicit in their sharding
    and handled by the sharded preparer.  This covers HOST state:

    - statefuls marked ``Replicated(...)`` (or any object with a truthy
      ``replicated`` attribute) contribute ``key/**``;
    - torch DDP-wrapped modules (directly or behind an adapter with a
      ``.module``) contribute ``key/**``, honoring
      ``parameters_to_ignore`` by enumerating per-name globs instead
      when any parameter is excluded from replication.

    Inference runs per-rank BEFORE the glob intersection gather, so a
    rank that didn't wrap its module gets the glob dropped by the
    intersection; content verification then guards the rest.
    """
    globs = list(replicated)
    if "**" in globs:
        return globs
    for key, val in app_state.items():
        # class-level marker only: an INSTANCE attribute named
        # "replicated" (e.g. an nn.Module buffer surfaced via
        # __getattr__) must neither crash the truthiness test nor
        # silently claim the state replicated
        if isinstance(val, Replicated) or (
            getattr(type(val), "replicated", None) is True
        ):
            globs.append(f"{key}/**")
            continue
        ddp = _ddp_module(val)
        if ddp is None:
            continue
        ignored = set(getattr(ddp, "parameters_to_ignore", ()) or ())
        if not ignored:
            globs.append(f"{key}/**")
            continue
        # adapters strip DDP's "module." prefix from state-dict keys while
        # ``parameters_to_ignore`` holds UNPREFIXED names; the stateful's
        # own state_dict is authoritative for the names that will appear
        # as logical paths, so strip the prefix before the membership test
        for name in val.state_dict().keys():
            bare = name[7:] if name.startswith("module.") else name
            if bare not in ignored and name not in ignored:
                globs.append(f"{key}/{name}")
    return globs


def _crc_key(location: str, byte_range: Any) -> str:
    br = f"{byte_range[0]}-{byte_range[1]}" if byte_range else ""
    return f"{location}|{br}"


def _collect_local_crcs(local_entries: Dict[str, Entry]) -> Dict[str, int]:
    """(location|byte_range) → crc32 for every locally-written payload
    whose checksum sink fired during staging.  Keyed by physical extent
    (rank-agnostic and unique), so merging needs no knowledge of how
    consolidation re-keyed the logical paths."""
    out: Dict[str, int] = {}
    for e in local_entries.values():
        crc = getattr(e, "crc32", None)
        loc = getattr(e, "location", None)
        if crc is not None and isinstance(loc, str):
            out[_crc_key(loc, getattr(e, "byte_range", None))] = crc
        for attr in ("shards", "chunks"):
            for s in getattr(e, attr, None) or ():
                if s.crc32 is not None:
                    out[_crc_key(s.location, s.byte_range)] = s.crc32
    return out


def _merge_crcs(
    manifest: Dict[str, Entry], crc_maps: Sequence[Dict[str, int]]
) -> None:
    """Stamp gathered content checksums onto the manifest in place (the
    manifest was serialized across ranks BEFORE staging computed them)."""
    merged: Dict[str, int] = {}
    for m in crc_maps:
        merged.update(m or {})
    if not merged:
        return
    for e in manifest.values():
        loc = getattr(e, "location", None)
        if isinstance(loc, str) and hasattr(e, "crc32"):
            crc = merged.get(_crc_key(loc, getattr(e, "byte_range", None)))
            if crc is not None:
                e.crc32 = crc
        for attr in ("shards", "chunks"):
            for s in getattr(e, attr, None) or ():
                crc = merged.get(_crc_key(s.location, s.byte_range))
                if crc is not None:
                    s.crc32 = crc


def _crc_payload(
    local_entries: Dict[str, Entry],
    object_crcs: Dict[str, int],
    object_codecs: Optional[Dict[str, Any]] = None,
    object_cas: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """One rank's post-staging checksum contribution: per-payload entry
    crcs + whole-object crcs (the incremental-dedup table) + codec frame
    tables for objects this rank stored compressed (codec.py) + chunk
    tables for objects this rank routed through the chunk store
    (cas/)."""
    out = {
        "entries": _collect_local_crcs(local_entries),
        "objects": dict(object_crcs),
    }
    if object_codecs:
        out["codecs"] = dict(object_codecs)
    if object_cas:
        out["cas"] = dict(object_cas)
    return out


def _merge_crc_payloads(
    metadata: SnapshotMetadata, payloads: Sequence[Dict[str, Any]]
) -> None:
    _merge_crcs(
        metadata.manifest, [p.get("entries") or {} for p in payloads]
    )
    for p in payloads:
        metadata.objects.update(p.get("objects") or {})
        metadata.codecs.update(p.get("codecs") or {})
        if p.get("cas"):
            # the root/chunk_size envelope was rank-agreed at planning
            # time (set in _take_impl_inner); only the per-rank chunk
            # tables merge here
            metadata.cas.setdefault("chunks", {}).update(p["cas"])


_STRIPE_EVENT_COUNTERS = (
    obs.STRIPE_WRITES,
    obs.STRIPE_READS,
    obs.STRIPE_PARTS_WRITTEN,
    obs.STRIPE_PARTS_READ,
    obs.STRIPE_BYTES_WRITTEN,
    obs.STRIPE_BYTES_READ,
    obs.STRIPE_ABORTS,
    # codec layer (codec.py): raw bytes in vs stored bytes out is the
    # operation's achieved compression ratio; parts_raw_fallback says
    # how much of the payload was incompressible
    obs.CODEC_BYTES_IN,
    obs.CODEC_BYTES_OUT,
    obs.CODEC_PARTS_ENCODED,
    obs.CODEC_PARTS_RAW_FALLBACK,
    obs.CODEC_PARTS_DECODED,
)


def _stripe_event_stamp():
    """Capture the stripe + codec counters now; the returned stamp
    writes the DELTAS into a take/restore event's metadata — how much of
    the operation's I/O moved through striped paths (and whether any
    multipart write had to abort), plus what the codec layer did to the
    byte volume, lands next to duration_s in the event stream, where a
    throughput incident review will look first."""
    before = {n: obs.counter(n).value for n in _STRIPE_EVENT_COUNTERS}

    def stamp(event: "Event") -> None:
        for n in _STRIPE_EVENT_COUNTERS:
            delta = obs.counter(n).value - before[n]
            if delta:
                event.metadata[n] = delta

    return stamp


def _normalize_cas_config(cas: Any, path: str) -> Optional[Dict[str, Any]]:
    """Resolve a take's ``cas`` argument to ``{"root", "chunk_size"}``
    (or None = off).  ``True`` places the pool next to the snapshot
    (``<parent>/cas`` — the manager layout); a string names the root
    URL; a dict may override ``chunk_size_bytes``."""
    if not cas:
        return None
    cfg: Dict[str, Any] = {}
    if isinstance(cas, str):
        cfg["root"] = cas
    elif isinstance(cas, dict):
        cfg.update(cas)
    if not cfg.get("root"):
        snap = path.rstrip("/")
        parent = snap.rsplit("/", 1)[0] if "/" in snap else ""
        if not parent:
            raise ValueError(
                f"cas=True needs a parent directory to place the pool "
                f"next to {path!r}; pass an explicit root instead"
            )
        cfg["root"] = f"{parent}/cas"
    cfg["chunk_size"] = int(
        cfg.pop("chunk_size_bytes", None)
        or cfg.get("chunk_size")
        or knobs.get_cas_chunk_size_bytes()
    )
    return {"root": cfg["root"].rstrip("/"), "chunk_size": cfg["chunk_size"]}


def _cas_commit_refs(
    metadata: SnapshotMetadata, path: str, store: Any = None
) -> None:
    """Register this take's chunk references in the shared index —
    strictly BEFORE the ``.snapshot_metadata`` marker, on the same
    (rank 0) code path, so a committed step's chunks can never be
    unprotected.  A failure here fails the commit (a marker whose
    chunks GC could reap would be a corrupt-by-construction snapshot)."""
    from . import cas as cas_mod

    tables = (metadata.cas or {}).get("chunks") or {}
    if not tables:
        return
    owned = store is None
    if owned:
        root = cas_mod.resolve_root(path, metadata.cas["root"])
        store = cas_mod.ChunkStore(root)
    try:
        cas_mod.commit_refs(store, path, tables)
    finally:
        if owned:
            store.sync_close()


# ------------------------------------------------------------- takeover
# Surviving rank death mid-commit (docs/resilience.md, "surviving rank
# death").  The liveness layer (resilience/liveness.py) turns a
# SIGKILLed/hung peer into a typed RankDeadError at the commit path's
# death-aware waits; the machinery below then finishes the commit
# WITHOUT the dead rank: survivors re-write its replicated objects from
# their own copies (every rank planned write reqs for every replicated
# object and normally discards the non-elected ones), and sharded state
# only the dead rank held is recorded in the metadata's ``degraded``
# section instead of failing the take.

_RECOVERY_POLL_S = 0.1
# recovery's own wait bound — generous, because survivors may be
# re-staging and re-writing the dead rank's replicated objects while
# their peers wait on the takeover keys
_RECOVERY_TIMEOUT_S = 600.0


@dataclasses.dataclass
class _TakeoverContext:
    """Planning-time facts the commit path keeps so survivors can finish
    a take after a peer dies mid-commit.  Every field is either
    rank-agreed (topo/preloads/assignment/repl_items/gathered_manifests
    — pure functions of gathered inputs) or rank-local write material
    (repl_reqs/repl_chunk_reqs: the un-elected write reqs this rank
    planned and would normally discard; exactly what a takeover writer
    replays).  ``repl_entries`` are the UNBATCHED entry objects captured
    before non-writers drop theirs and before batching re-points the
    writer's at rank-local slabs — their ``replicated/`` locations are
    rank-independent, so a survivor's re-write lands where the manifest
    fix-up says it does."""

    topo: Any
    preloads: List[int]
    assignment: Dict[str, int]
    repl_reqs: Dict[str, List[WriteReq]]
    repl_chunk_reqs: Dict[str, WriteReq]
    chunk_parent: Dict[str, str]
    repl_items: List[Tuple[str, int]]
    repl_entries: Dict[str, Entry]
    gathered_manifests: List[Dict[str, Any]]


def _recovery_kv_get(
    coordinator: Coordinator,
    monitor: Any,
    key: str,
    expected_dead: set,
    timeout_s: float = _RECOVERY_TIMEOUT_S,
) -> str:
    """A KV wait for the recovery protocol itself: the ranks in
    ``expected_dead`` STAY dead (the liveness monitor keeps reporting
    them), so only NEW deaths raise — a scoped ``kv_get`` would re-raise
    on the known-dead set forever."""
    deadline = time.monotonic() + timeout_s
    while True:
        value = coordinator.kv_try_get(key)
        if value is not None:
            return value
        newly = [r for r in monitor.dead_ranks() if r not in expected_dead]
        if newly:
            raise RankDeadError(
                newly[0],
                set(newly) | set(expected_dead),
                ns=getattr(monitor, "ns", ""),
            )
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"takeover recovery timed out after {timeout_s}s "
                f"waiting for {key!r}"
            )
        time.sleep(_RECOVERY_POLL_S)


def _recover_commit_after_death(
    *,
    coordinator: Coordinator,
    commit_uid: str,
    path: str,
    metadata: SnapshotMetadata,
    storage: Any,
    local_entries: Dict[str, Entry],
    object_crcs: Dict[str, Any],
    object_codecs: Dict[str, Any],
    object_cas: Dict[str, Any],
    cas_store: Any,
    ctx: _TakeoverContext,
    monitor: Any,
    dead_err: RankDeadError,
    already_committed: bool = False,
) -> SnapshotMetadata:
    """Finish a take's commit after ``dead_err`` declared peer rank(s)
    dead.  Runs OUTSIDE the abort/liveness scopes (they would re-raise
    on the known-dead set); all cross-rank traffic is explicit-key KV —
    no collectives, no uid minting — so survivors' op counters stay
    aligned for whatever runs next.

    Protocol: (1) agree on the dead set via a leader-published plan,
    (2) deterministically re-elect writers for the dead ranks' orphaned
    replicated objects (``elect_takeover_writers`` — pure, so no extra
    agreement round), (3) takeover writers replay their kept
    un-elected write reqs, (4) every survivor applies the same manifest
    fix-up and computes the same degraded set, (5) checksums re-exchange
    under takeover keys, (6) the leader writes the metadata marker
    (with a ``degraded`` section when sharded state died with its only
    holder) and signals commit.

    ``already_committed``: rank 0 had already written the marker when
    death surfaced (a peer died between the two commit barriers) — the
    snapshot is complete; the leader skips the rewrite and just drives
    the protocol so survivors converge.
    """
    rank, world = coordinator.rank, coordinator.world_size
    my_dead = set(dead_err.dead_ranks or [dead_err.rank])
    logger.warning(
        "rank %d: peer rank(s) %s declared dead during commit %s; "
        "entering write takeover", rank, sorted(my_dead), commit_uid,
    )

    # --- agree on the dead set -----------------------------------------
    # Survivors can observe death at different times (or observe
    # different sets).  The lowest live rank in MY view is my leader
    # candidate; it publishes an authoritative plan under a
    # LEADER-SUFFIXED key.  If the candidate itself turns out dead while
    # we wait, fold the new deaths in and re-elect — the dead set
    # strictly grows, so at most ``world`` rounds.
    plan_dead: Optional[List[int]] = None
    for _ in range(world):
        live = [r for r in range(world) if r not in my_dead]
        if not live:
            raise RuntimeError(
                f"takeover for {commit_uid}: every rank is in the dead "
                f"set {sorted(my_dead)}"
            )
        candidate = live[0]
        plan_key = f"{commit_uid}/takeover/plan/{candidate}"
        if candidate == rank:
            coordinator.kv_set(plan_key, json.dumps(sorted(my_dead)))
            plan_dead = sorted(my_dead)
            break
        try:
            plan_dead = json.loads(
                _recovery_kv_get(coordinator, monitor, plan_key, my_dead)
            )
            break
        except RankDeadError as e:
            my_dead |= set(e.dead_ranks or [e.rank])
    if plan_dead is None:
        raise RuntimeError(
            f"takeover for {commit_uid}: no live leader converged"
        )
    dead = set(plan_dead)
    if rank in dead:
        # the fleet declared US dead (our heartbeats stalled past the
        # timeout) and has moved on; our writes may have been taken
        # over — refuse to race the survivors
        raise RuntimeError(
            f"rank {rank} was declared dead by the takeover plan for "
            f"{commit_uid}; aborting locally"
        )
    live = [r for r in range(world) if r not in dead]
    leader = live[0]

    # --- re-elect writers for the orphaned replicated objects ----------
    # Pure + deterministic (same dead set in → same election out), so
    # every survivor computes who writes what with zero extra traffic.
    orphans: List[Tuple[str, int]] = []
    origin_of: Dict[str, int] = {}
    for k, nbytes in ctx.repl_items:
        w = ctx.assignment.get(k)
        if w in dead:
            orphans.append((k, nbytes))
            origin_of[k] = w
    takeover: Dict[str, int] = {}
    if orphans:
        takeover = elect_takeover_writers(
            orphans, sorted(dead), world,
            preloads=ctx.preloads, topology=ctx.topo, origin_of=origin_of,
        )

    # --- replay my taken-over write reqs -------------------------------
    mine = sorted(k for k, w in takeover.items() if w == rank)
    taken_paths: set = set()
    for k in mine:
        taken_paths.add(ctx.chunk_parent.get(k, k))
    if mine and not already_committed:
        reqs: List[WriteReq] = []
        for k in mine:
            if k in ctx.repl_reqs:
                reqs.extend(ctx.repl_reqs[k])
            else:
                reqs.append(ctx.repl_chunk_reqs[k])
        cost_of = dict(ctx.repl_items)
        my_bytes = sum(cost_of.get(k, 0) for k in mine)
        # digest/codec sinks were only attached to the originally-elected
        # writer's reqs; the replayed ones need their own so the objects
        # table and codec frame tables cover the re-written copies.
        # (No ``wr.cas``: taken-over payloads are written plain at their
        # locations even under a cas take — a location absent from the
        # chunk tables reads through the plain path.)
        cksum = knobs.write_checksums_enabled()
        for wr in reqs:
            def _codec_sink(table: dict, wr=wr) -> None:
                object_codecs[wr.path] = table

            wr.codec_sink = _codec_sink
            if cksum:
                def _object_sink(digest: List[int], wr=wr) -> None:
                    wr.object_digest = tuple(digest)
                    object_crcs[wr.path] = list(digest)

                wr.digest_sink = _object_sink
        logger.warning(
            "rank %d: taking over %d replicated write unit(s) "
            "(%d bytes) from dead rank(s) %s",
            rank, len(mine), my_bytes, sorted(dead),
        )
        sync_execute_write_reqs(
            reqs, storage, get_process_memory_budget_bytes(), rank,
        ).sync_complete()
        obs.counter(obs.TAKEOVER_OBJECTS).inc(len(mine))
        obs.counter(obs.TAKEOVER_BYTES).inc(my_bytes)

    # --- manifest fix-up + degraded set (identical on every survivor) --
    degraded: Dict[str, Dict[str, Any]] = {}
    if not already_committed:
        for k in sorted(takeover):
            w = takeover[k]
            lp = ctx.chunk_parent.get(k, k)
            # consolidation kept each replicated entry under ONE rank; if
            # that carrier died, re-home the UNBATCHED entry under the new
            # writer (the dead carrier's copy may point at a slab it never
            # finished).  A live carrier (e.g. a surviving chunk-writer of
            # a split entry) keeps carrying it — only dead keys move.
            removed = False
            for d in sorted(dead):
                if metadata.manifest.pop(f"{d}/{lp}", None) is not None:
                    removed = True
            carried = any(f"{r}/{lp}" in metadata.manifest for r in live)
            if removed or not carried:
                entry = ctx.repl_entries.get(lp)
                if entry is not None:
                    metadata.manifest.setdefault(f"{w}/{lp}", entry)
        # state only the dead rank held: everything in its gathered
        # manifest except containers, in-manifest primitives and the
        # replicated paths just taken over.  Conservative — payloads the
        # dead rank DID land before dying are still marked (we cannot
        # know), and verify/repair heal the marker afterwards.  The dead
        # rank's manifest keys stay: repair and partial restores need
        # the shapes and locations.
        taken_over_lps = {ctx.chunk_parent.get(k, k) for k in takeover}
        for d in sorted(dead):
            per_rank = (
                ctx.gathered_manifests[d]
                if d < len(ctx.gathered_manifests)
                else {}
            )
            for lp, ed in per_rank.items():
                if lp in taken_over_lps:
                    continue
                try:
                    entry = entry_from_dict(ed)
                except Exception:  # noqa: BLE001
                    continue
                if is_container_entry(entry) or isinstance(
                    entry, PrimitiveEntry
                ):
                    continue
                degraded.setdefault(
                    lp,
                    {
                        "origin_rank": d,
                        "kind": getattr(entry, "type", "?"),
                    },
                )

    # --- checksum re-exchange among survivors --------------------------
    # The normal all_gather would block on the dead rank; explicit
    # takeover keys carry the same _crc_payload JSON instead.  Taken-over
    # entries ride each writer's payload (their staging sinks fired on
    # the captured unbatched entry objects during the replay above).
    aug_entries = dict(local_entries)
    for lp in taken_paths:
        e = ctx.repl_entries.get(lp)
        if e is not None:
            aug_entries[lp] = e
    payload = _crc_payload(
        aug_entries, object_crcs, object_codecs, object_cas
    )
    coordinator.kv_set(
        f"{commit_uid}/takeover/crcs/{rank}", json.dumps(payload)
    )
    payloads: List[Dict[str, Any]] = []
    for r in live:
        if r == rank:
            payloads.append(payload)
            continue
        # fast path first: a peer that published before us costs one
        # try-get instead of entering the death-aware poll loop
        raw = coordinator.kv_try_get(f"{commit_uid}/takeover/crcs/{r}")
        if raw is None:
            raw = _recovery_kv_get(
                coordinator, monitor,
                f"{commit_uid}/takeover/crcs/{r}", dead,
            )
        payloads.append(json.loads(raw))
    if not already_committed:
        _merge_crc_payloads(metadata, payloads)
        if degraded:
            metadata.degraded = dict(degraded)

    # --- leader commits and signals ------------------------------------
    commit_key = f"{commit_uid}/takeover/commit/{leader}"
    if rank == leader:
        try:
            if not already_committed:
                # same invariants as the clean path: never commit a
                # poisoned take, chunk refs strictly before the marker
                coordinator.raise_if_poisoned(commit_uid)
                _cas_commit_refs(metadata, path, cas_store)
                if degraded:
                    obs.counter(obs.TAKEOVER_DEGRADED_COMMITS).inc()
                storage.sync_write(
                    WriteIO(
                        path=SNAPSHOT_METADATA_FNAME,
                        buf=metadata.to_yaml().encode(),
                        durable=True,
                    )
                )
            coordinator.kv_set(commit_key, "ok")
        except BaseException as e:
            try:
                coordinator.kv_set(commit_key, f"failed: {e!r}")
            except Exception as signal_exc:  # noqa: BLE001
                # best-effort failure signal: survivors time out on the
                # commit key instead if the KV store is down too
                obs.swallowed_exception(
                    "takeover.commit_failure_signal", signal_exc
                )
            raise
    else:
        status = _recovery_kv_get(coordinator, monitor, commit_key, dead)
        if status != "ok":
            raise RuntimeError(
                f"takeover leader rank {leader} failed to commit "
                f"{path!r}: {status}"
            )
    logger.warning(
        "rank %d: takeover commit for %r done — %s (%d write unit(s) "
        "re-written fleet-wide, %d degraded path(s))",
        rank, path, "DEGRADED" if degraded else "complete",
        len(takeover), len(degraded),
    )
    return metadata


def _validate_app_state(app_state: Dict[str, Any]) -> None:
    # reference snapshot.py:672-690
    for key, value in app_state.items():
        if not (hasattr(value, "state_dict") and hasattr(value, "load_state_dict")):
            raise TypeError(
                f"app_state[{key!r}] (type {type(value)}) does not implement "
                "the Stateful protocol (state_dict/load_state_dict); wrap "
                "plain values in StateDict or pytrees in PyTreeState"
            )


class Snapshot:
    def __init__(
        self,
        path: str,
        coordinator: Optional[Coordinator] = None,
        storage_options: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.path = path
        self._coordinator = coordinator or get_default_coordinator()
        self._metadata_cache: Optional[SnapshotMetadata] = None
        # forwarded to the storage plugin constructor on every access
        # (reference storage_options, snapshot.py:118)
        self._storage_options = storage_options

    # ------------------------------------------------------------------ take

    @classmethod
    def take(
        cls,
        path: str,
        app_state: AppState,
        replicated: Sequence[str] = (),
        coordinator: Optional[Coordinator] = None,
        base: Optional[str] = None,
        leaf_transform: Optional[Callable[[str, Any], Any]] = None,
        storage_options: Optional[Dict[str, Any]] = None,
        cas: Optional[Any] = None,
    ) -> "Snapshot":
        """Synchronous distributed save (reference Snapshot.take,
        snapshot.py:112-228).

        ``leaf_transform(logical_path, leaf) -> leaf``: applied to every
        flattened leaf before planning — cast to lower precision for the
        checkpoint, quantize, redact, etc.  It must RETURN a leaf for
        every path (dropping is not supported — the container structure
        is already fixed; restore a subset with ``restore(paths=...)``
        instead).  The analogue of the reference's
        ``_custom_tensor_prepare_func`` (snapshot.py:120-122), applied
        uniformly to all leaves, not just tensors.  Must be deterministic
        and rank-agreed (the transformed content is what replication
        verification fingerprints).

        ``base`` (beyond-parity, incremental takes): path of a previous
        committed snapshot.  Staged objects whose content checksum
        matches the base's object at the same location are hardlinked /
        server-side-copied instead of rewritten — near-free checkpoints
        of mostly-unchanged state (frozen layers, embeddings, dataloader
        state).  Requires WRITE_CHECKSUMS on both takes; each snapshot
        owns its objects, so deleting the base never corrupts this one.

        ``cas`` (chunk-level incremental takes, cas/): ``True`` (pool at
        ``<parent>/cas``), a root URL, or a config dict.  Payload bytes
        go to a shared content-addressed chunk pool: any chunk an
        earlier committed step under the same pool already stored is
        skipped, the manifest records chunk references, and retention
        becomes refcounted GC (``SnapshotManager``).  Subsumes ``base``
        (chunk-level beats whole-object-vs-previous-step) and disables
        the codec layer for chunked objects (keys are raw digests).
        Requires WRITE_CHECKSUMS on every rank.
        """
        coordinator = coordinator or get_default_coordinator()
        with log_event(
            Event("take", {"path": path, "rank": coordinator.rank})
        ) as take_event:
            stamp_stripe = _stripe_event_stamp()
            # flight-record window + goodput clock both start here so
            # the persisted record describes exactly this take
            obs_before = obs.aggregate.capture()
            gp_begin = obs.goodput.take_begin(path)
            # Death-aware take (resilience/liveness.py): the heartbeat
            # PUBLISHER starts before planning — so a rank legitimately
            # slow in staging keeps stamping and is never falsely
            # declared dead — while the MONITOR is only consulted by
            # the commit-phase waits below (liveness_scope).  The uid
            # is minted here so the session can stamp under it.
            commit_uid = coordinator._next_uid("commit")
            session = LivenessSession(coordinator, commit_uid)
            session.start()
            try:
                (
                    metadata, pending_io, storage, commit_uid,
                    local_entries, object_crcs, object_codecs,
                    object_cas, cas_store, takeover_ctx,
                ) = cls._take_impl(
                    path, app_state, replicated, coordinator,
                    is_async=False, base=base,
                    leaf_transform=leaf_transform,
                    storage_options=storage_options, cas=cas,
                    commit_uid=commit_uid,
                )
            except BaseException:
                session.stop()
                raise
            # Abort-aware commit (resilience/abort.py): a rank hitting
            # an unrecoverable error here poisons the commit scope and
            # re-raises its ORIGINAL error; peers blocked in the gathers
            # and barriers below raise a typed SnapshotAbortedError
            # naming the origin rank within seconds instead of wedging
            # to the barrier timeout.  Rank 0 re-checks the poison key
            # immediately before the metadata write, so a poisoned take
            # can never produce a committed snapshot.
            #
            # Death-aware commit: the liveness scope makes every
            # barrier/kv wait below raise a typed RankDeadError when a
            # peer's stamp goes stale — a SIGKILLed rank can never
            # reach its poison call — and the handler finishes the
            # commit via write takeover instead of aborting.
            #
            # ``committed`` is mutable so the RankDeadError handler can
            # see whether rank 0 already wrote the marker (a peer dying
            # between the two commit barriers must not degrade a
            # complete snapshot).
            committed = {"done": False}
            try:
                with coordinator.abort_scope(commit_uid), \
                        coordinator.liveness_scope(session.monitor):
                    pending_io.sync_complete()
                    # tiered storage: replicate fast-tier payloads to
                    # peers and enqueue write-back promotion, strictly
                    # after this rank's writes landed and strictly
                    # before the commit barrier (so the durable commit
                    # marker can only ever trail the data)
                    finalize = getattr(storage, "finalize_take", None)
                    if finalize is not None:
                        finalize(coordinator, commit_uid)
                    # content checksums became final when staging
                    # finished above; gather them (foreground path:
                    # collectives are fine) and merge into every rank's
                    # metadata copy
                    local_crcs = _crc_payload(
                        local_entries, object_crcs, object_codecs,
                        object_cas,
                    )
                    if coordinator.world_size > 1:
                        crc_maps = coordinator.all_gather_object(local_crcs)
                    else:
                        crc_maps = [local_crcs]
                    _merge_crc_payloads(metadata, crc_maps)
                    # flight record, publish half: this rank's metrics
                    # delta + phase rollup ride the KV under explicit
                    # keys.  Best-effort — a lost payload degrades the
                    # record to a partial one, never the commit.
                    obs.aggregate.publish(
                        coordinator,
                        commit_uid,
                        obs.aggregate.rank_payload(
                            coordinator.rank, "take", obs_before
                        ),
                    )
                    # commit: all ranks done writing → rank 0 writes
                    # metadata (reference snapshot.py:202-209)
                    coordinator.barrier()
                    if coordinator.rank == 0:
                        coordinator.raise_if_poisoned(commit_uid)
                        # chunk-store index update STRICTLY before the
                        # commit marker (and strictly after the poison
                        # re-check): a committed step's chunk refs are
                        # registered before any reader can consider the
                        # step committed, so refcounted GC can never
                        # reap a committed step's chunks.  A crash in
                        # the gap leaves refs for an uncommitted step —
                        # mark-phase fodder, reclaimed after the grace
                        # window.
                        _cas_commit_refs(metadata, path, cas_store)
                        # flight record, merge half: every surviving
                        # rank published before the barrier above, so
                        # the merge sees them all; the record lands
                        # strictly BEFORE the commit marker (an
                        # interrupted write leaves an uncommitted
                        # snapshot with a record, never the reverse)
                        try:
                            obs.aggregate.write_obsrecord(
                                storage,
                                obs.aggregate.collect_and_merge(
                                    coordinator, commit_uid,
                                    op="take", path=path,
                                ),
                            )
                        except Exception as e:  # noqa: BLE001
                            obs.swallowed_exception("take.obsrecord", e)
                        # durable: the commit point must survive a host
                        # crash — a synced metadata file is the
                        # definition of "committed"
                        storage.sync_write(
                            WriteIO(
                                path=SNAPSHOT_METADATA_FNAME,
                                buf=metadata.to_yaml().encode(),
                                durable=True,
                            )
                        )
                        committed["done"] = True
                    coordinator.barrier()
            except SnapshotAbortedError:
                raise
            except RankDeadError as dead_err:
                # a peer died mid-commit.  Recovery runs OUTSIDE the
                # abort/liveness scopes (a scoped wait would re-raise
                # on the known-dead set forever) and finishes the
                # commit without the dead rank — complete when its
                # replicated objects could be re-written by survivors,
                # typed-degraded otherwise.
                if not knobs.takeover_enabled() or coordinator.world_size <= 1:
                    coordinator.poison(
                        commit_uid,
                        cause=repr(dead_err),
                        site=f"take/rank{coordinator.rank}",
                    )
                    raise
                try:
                    metadata = _recover_commit_after_death(
                        coordinator=coordinator,
                        commit_uid=commit_uid,
                        path=path,
                        metadata=metadata,
                        storage=storage,
                        local_entries=local_entries,
                        object_crcs=object_crcs,
                        object_codecs=object_codecs,
                        object_cas=object_cas,
                        cas_store=cas_store,
                        ctx=takeover_ctx,
                        monitor=session.monitor,
                        dead_err=dead_err,
                        already_committed=committed["done"],
                    )
                except BaseException as e:
                    coordinator.poison(
                        commit_uid,
                        cause=repr(e),
                        site=f"takeover/rank{coordinator.rank}",
                    )
                    raise
            except BaseException as e:
                coordinator.poison(
                    commit_uid,
                    cause=repr(e),
                    site=f"take/rank{coordinator.rank}",
                )
                raise
            finally:
                session.stop()
                stamp_stripe(take_event)
                storage.sync_close()
                if cas_store is not None:
                    cas_store.sync_close()
            # goodput: a sync take's unblock point is its return; the
            # durable commit just happened too — except under a
            # write-back tier, where the promoter reports it when the
            # DURABLE metadata marker lands
            if getattr(storage, "policy", None) != "write_back":
                obs.goodput.durable_commit(path)
            obs.goodput.take_unblocked(path, gp_begin)
            obs.maybe_write_metrics_textfile()
        snapshot = cls(path, coordinator, storage_options=storage_options)
        snapshot._metadata_cache = metadata
        return snapshot

    @classmethod
    def async_take(
        cls,
        path: str,
        app_state: AppState,
        replicated: Sequence[str] = (),
        coordinator: Optional[Coordinator] = None,
        base: Optional[str] = None,
        leaf_transform: Optional[Callable[[str, Any], Any]] = None,
        storage_options: Optional[Dict[str, Any]] = None,
        cas: Optional[Any] = None,
    ) -> "PendingSnapshot":
        """Unblock-early save (reference Snapshot.async_take,
        snapshot.py:229-318).  Returns once the snapshot content is
        independent of training state: device arrays are offloaded to
        pinned host memory in one batched DMA transfer and mutable host
        arrays are defensively copied.  Staging, storage I/O and the
        commit all happen in the background.  With
        TORCHSNAPSHOT_TPU_DISABLE_EAGER_HOST_STAGING=1 this reverts to
        the reference semantics (return after staging completes)."""
        coordinator = coordinator or get_default_coordinator()
        with log_event(
            Event("async_take", {"path": path, "rank": coordinator.rank})
        ):
            obs_before = obs.aggregate.capture()
            gp_begin = obs.goodput.take_begin(path)
            # liveness publisher from the very start (see take()); the
            # session hands off to the PendingSnapshot commit thread,
            # which stops it when the background commit resolves
            commit_uid = coordinator._next_uid("commit")
            session = LivenessSession(coordinator, commit_uid)
            session.start()
            try:
                (
                    metadata, pending_io, storage, commit_uid,
                    local_entries, object_crcs, object_codecs,
                    object_cas, cas_store, takeover_ctx,
                ) = cls._take_impl(
                    path, app_state, replicated, coordinator,
                    is_async=True, base=base,
                    leaf_transform=leaf_transform,
                    storage_options=storage_options, cas=cas,
                    commit_uid=commit_uid,
                )
            except BaseException:
                session.stop()
                raise
        pending = PendingSnapshot(
            path=path,
            metadata=metadata,
            pending_io_work=pending_io,
            storage=storage,
            coordinator=coordinator,
            commit_uid=commit_uid,
            local_entries=local_entries,
            object_crcs=object_crcs,
            object_codecs=object_codecs,
            storage_options=storage_options,
            obs_before=obs_before,
            object_cas=object_cas,
            cas_store=cas_store,
            takeover_ctx=takeover_ctx,
            liveness_session=session,
        )
        # goodput: the unblock point IS this return — training state is
        # independent of the snapshot from here; staging/IO/commit (and
        # the flight-record exchange) drain in the background
        obs.goodput.take_unblocked(path, gp_begin)
        return pending

    @classmethod
    def _take_impl(
        cls,
        path: str,
        app_state: AppState,
        replicated: Sequence[str],
        coordinator: Coordinator,
        is_async: bool,
        base: Optional[str] = None,
        leaf_transform: Optional[Callable[[str, Any], Any]] = None,
        storage_options: Optional[Dict[str, Any]] = None,
        cas: Optional[Any] = None,
        commit_uid: Optional[str] = None,
    ) -> Tuple[
        SnapshotMetadata, PendingIOWork, Any, str,
        Dict[str, Entry], Dict[str, int], Dict[str, Any],
        Dict[str, Any], Any, "_TakeoverContext",
    ]:
        # reference _take_impl, snapshot.py:517-635
        rank, world = coordinator.rank, coordinator.world_size
        _validate_app_state(app_state)

        # Take must never perturb the host RNG streams, and the RNG state
        # that gets *saved* must be the state at entry (reference
        # _pop_rng_state, snapshot.py:532-574).  Mechanism: capture every
        # RNGState instance's state NOW — via the instance, so subclasses
        # capturing extra streams (e.g. torch's) are honored — and have
        # the serialization loop below substitute these entry captures
        # for those keys instead of re-calling state_dict() mid-loop.
        # On exit each instance restores its own entry state, plus a base
        # restore covering takes with no RNGState in app_state at all.
        rng_at_entry = RNGState().state_dict()
        rng_states_at_entry = {
            k: v.state_dict()
            for k, v in app_state.items()
            if isinstance(v, RNGState)
        }
        # The commit uid doubles as the abort scope and is minted BEFORE
        # planning (same per-instance counter position on every rank),
        # so even a rank dying in the planning gathers — storage
        # construction, glob/key/manifest exchanges — poisons a scope
        # its peers are already watching instead of wedging them.
        # Callers that run a liveness session mint it even earlier and
        # pass it in, so heartbeats cover planning and staging too.
        if commit_uid is None:
            commit_uid = coordinator._next_uid("commit")
        try:
            with coordinator.abort_scope(commit_uid):
                return cls._take_impl_inner(
                    path, app_state, replicated, coordinator, is_async,
                    rank, world, rng_states_at_entry, commit_uid, base,
                    leaf_transform=leaf_transform,
                    storage_options=storage_options, cas=cas,
                )
        except SnapshotAbortedError:
            raise
        except BaseException as e:
            coordinator.poison(
                commit_uid, cause=repr(e), site=f"take_plan/rank{rank}"
            )
            raise
        finally:
            for k, v in app_state.items():
                if isinstance(v, RNGState):
                    v.load_state_dict(rng_states_at_entry[k])
            RNGState().load_state_dict(rng_at_entry)

    @classmethod
    def _take_impl_inner(
        cls,
        path: str,
        app_state: AppState,
        replicated: Sequence[str],
        coordinator: Coordinator,
        is_async: bool,
        rank: int,
        world: int,
        rng_states_at_entry: Dict[str, Dict[str, Any]],
        commit_uid: str,
        base: Optional[str] = None,
        leaf_transform: Optional[Callable[[str, Any], Any]] = None,
        storage_options: Optional[Dict[str, Any]] = None,
        cas: Optional[Any] = None,
    ) -> Tuple[
        SnapshotMetadata, PendingIOWork, Any, str,
        Dict[str, Entry], Dict[str, int], Dict[str, Any],
        Dict[str, Any], Any, "_TakeoverContext",
    ]:

        # path + replicated coalescing across ranks
        # (reference _coalesce_path_and_replicated, snapshot.py:858-894)
        replicated = _infer_replicated(replicated, app_state)
        path0 = coordinator.broadcast_object(path, src=0)
        if path0 != path:
            logger.warning(
                "rank %d: snapshot path %r differs from rank 0's %r; using "
                "rank 0's", rank, path, path0
            )
            path = path0
        # the verification mode rides the same gather as the globs: it
        # gates what each rank contributes to the fingerprint gather, so
        # it must be rank-agreed (strictest wins) without paying an extra
        # KV round
        local_mode = _safe_replication_verify_mode()
        local_cksum = knobs.write_checksums_enabled()
        local_cas = _normalize_cas_config(cas, path)
        if world > 1:
            gathered = coordinator.all_gather_object(
                (
                    sorted(set(replicated)), local_mode, base,
                    local_cksum, local_cas,
                )
            )
            gathered_globs = [g for g, _, _, _, _ in gathered]
            modes = [m for _, m, _, _, _ in gathered]
            # incremental base + cas config + checksum participation
            # must be rank-agreed: they gate later broadcasts (the
            # base's object table / the chunk index's key set), and
            # divergent branches would deadlock them.  Rank 0's base
            # and cas win (like the path); dedup needs checksums on
            # EVERY rank (each rank stages its own objects).
            base = gathered[0][2]
            cas_cfg = gathered[0][4]
            checksums_all = all(c for _, _, _, c, _ in gathered)
            if not checksums_all and base is not None:
                logger.warning(
                    "rank %d: WRITE_CHECKSUMS off on some rank; "
                    "incremental dedup disabled for this take", rank,
                )
                base = None
            if not checksums_all and cas_cfg is not None:
                logger.warning(
                    "rank %d: WRITE_CHECKSUMS off on some rank; content "
                    "addressing needs whole-pipeline digests — taking a "
                    "plain (per-step object) snapshot", rank,
                )
                cas_cfg = None
            replicated_globs = sorted(
                set(gathered_globs[0]).intersection(*map(set, gathered_globs[1:]))
            )
            if set(replicated) != set(replicated_globs):
                logger.warning(
                    "rank %d: replicated globs differ across ranks; using the "
                    "intersection %r", rank, replicated_globs
                )
            verify_mode = _strictest_mode(modes)
            if len(set(modes)) > 1:
                logger.warning(
                    "rank %d: REPLICATION_VERIFY differs across ranks (%s); "
                    "using the strictest: %r",
                    rank, sorted(set(modes)), verify_mode,
                )
        else:
            replicated_globs = sorted(set(replicated))
            verify_mode = local_mode
            cas_cfg = local_cas
            if cas_cfg is not None and not local_cksum:
                logger.warning(
                    "take(cas=...) needs WRITE_CHECKSUMS=1; taking a "
                    "plain (per-step object) snapshot"
                )
                cas_cfg = None

        storage = _storage_for(path, storage_options)

        # gather the global key list; serialize per-key state_dict() calls
        # with barriers in case a Stateful's state_dict performs collectives
        # (reference _gather_keys, snapshot.py:552-568)
        local_keys = sorted(app_state.keys())
        if world > 1:
            global_keys = sorted(
                set().union(*coordinator.all_gather_object(local_keys))
            )
        else:
            global_keys = local_keys
        # RNGState keys serialize the state captured at take ENTRY
        # (``rng_states_at_entry``, taken before any collective or
        # storage init could touch the streams), so the saved stream is
        # exact even when an alphabetically-earlier stateful's
        # state_dict() consumes RNG.  Keys are NOT reordered: the
        # barrier-aligned loop below must run in the same order on every
        # rank, and a rank-local sort key (which keys are RNGState here)
        # could diverge across ranks.
        manifest: Manifest = {}
        flattened: Dict[str, Any] = {}
        for key in global_keys:
            if key in app_state:
                state = (
                    rng_states_at_entry[key]
                    if key in rng_states_at_entry
                    else app_state[key].state_dict()
                )
                m, f = flatten(state, prefix=key)
                manifest.update(m)
                flattened.update(f)
            if world > 1:
                coordinator.barrier()

        if leaf_transform is not None:
            # before replication verification, so fingerprints (and the
            # written bytes) reflect the TRANSFORMED content
            flattened = {
                p: leaf_transform(p, v) for p, v in flattened.items()
            }

        # plan writes per leaf (reference prepare_write dispatch,
        # io_preparer.py:82-147)
        entries: Dict[str, Entry] = {}
        write_reqs: List[WriteReq] = []
        repl_reqs: Dict[str, List[WriteReq]] = {}
        repl_items: List[Tuple[str, int]] = []
        # chunk-granular items for replicated CHUNKED entries: a multi-GB
        # replicated host array is split across writer ranks per chunk
        # instead of riding one rank (reference partitioner.py:40-47)
        repl_chunk_reqs: Dict[str, WriteReq] = {}
        chunk_parent: Dict[str, str] = {}
        local_bytes = 0
        verified_repl = _verify_replicated_paths(
            flattened, replicated_globs, coordinator, verify_mode
        )
        # Per-rank host-state weight feeds the sharded-box balancer as a
        # pre-load, so a process carrying heavy per-rank host state (e.g.
        # a data-loader rank's buffers) is assigned fewer sharded boxes —
        # the two balancers compose (reference partitioner.py:266-270).
        # The gathered vector is identical on every controller, keeping
        # box assignment collective-free and deterministic; it is then
        # MUTATED by each sharded leaf's assignment so sharded leaves
        # also compose with each other.
        host_est = sum(
            estimate_write_bytes(obj)
            for lp, obj in flattened.items()
            if lp not in verified_repl and not is_multi_device_jax_array(obj)
        )
        writer_loads = list(
            coordinator.all_gather_object(host_est)
            if world > 1
            else [host_est]
        )
        # rank → host → slice placement (topology/): identical on every
        # rank (explicit spec, or one kv_exchange of per-process hints
        # under the commit uid), so the topology-aware writer elections
        # below stay pure deterministic functions — replicated state is
        # written once per FLEET with writers spread across slices and
        # hosts to balance per-slice durable egress
        topo = topology_mod.detect_topology(
            coordinator, exchange_prefix=f"{commit_uid}/topo"
        )
        # resolve the chunking knob ONCE for the whole take and pass it
        # down: one env resolution instead of one per leaf (measurable
        # in the blocked window at tens of thousands of leaves), a
        # mid-take env change can't split chunking behavior across
        # leaves, and no global override state is touched (concurrent
        # takes from different threads must not interleave overrides)
        chunk_size_bytes = knobs.get_max_chunk_size_bytes()
        # planning (prepare_write fan-out) is the dominant blocked-path
        # CPU cost at high leaf counts — first-class in traces
        with obs.span("take/plan", leaves=len(flattened), rank=rank):
            for lpath in sorted(flattened.keys()):
                obj = flattened[lpath]
                repl = lpath in verified_repl
                entry, reqs = prepare_write(
                    obj=obj,
                    logical_path=lpath,
                    rank=rank,
                    replicated=repl,
                    is_async_snapshot=is_async,
                    process_index=rank,
                    process_count=world,
                    writer_loads=writer_loads,
                    chunk_size_bytes=chunk_size_bytes,
                    topology=topo,
                )
                entries[lpath] = entry
                cost = sum(
                    r.buffer_stager.get_staging_cost_bytes() for r in reqs
                )
                if repl and not isinstance(entry, ShardedArrayEntry):
                    if isinstance(entry, ChunkedArrayEntry) and len(reqs) > 1:
                        for ci, r in enumerate(reqs):
                            k = f"{lpath}\x00{ci}"  # \x00 can't be in paths
                            repl_chunk_reqs[k] = r
                            chunk_parent[k] = lpath
                            repl_items.append(
                                (k, r.buffer_stager.get_staging_cost_bytes())
                            )
                    else:
                        repl_reqs[lpath] = reqs
                        repl_items.append((lpath, cost))
                else:
                    write_reqs.extend(reqs)
                    local_bytes += cost

        # takeover (resilience): capture the UNBATCHED replicated entry
        # objects on every rank — before non-writers drop theirs below
        # and before batching re-points the writer's at rank-local
        # slabs.  Their ``replicated/`` locations are rank-independent,
        # so if this rank is later elected to re-write a dead peer's
        # object, the re-homed manifest entry describes exactly what it
        # wrote.  Object references (not dicts): the replay's staging
        # sinks stamp crc32 onto these same objects.
        repl_entry_objs: Dict[str, Entry] = {
            lp: entries[lp]
            for lp in set(repl_reqs) | set(chunk_parent.values())
        }

        # balance replicated host-state writes across ranks
        # (reference partition_write_reqs, partitioner.py:216-310)
        split_repl_paths: set = set()
        preloads: List[int] = [0] * world
        assignment: Dict[str, int] = {}
        if repl_items:
            preloads = list(
                coordinator.all_gather_object(local_bytes)
                if world > 1
                else [local_bytes]
            )
            assignment = partition_replicated_writes(
                repl_items, world, preloads, topology=topo
            )
            # per-slice egress attribution: each writer rank counts the
            # replicated write units/bytes it carries; the flight
            # record groups ranks by slice for the doctor rollup.
            # Explicit topologies only — a flat job ran the flat
            # greedy, and stamping topology.* counters on it would
            # make doctor/stats render a topology section nobody
            # configured.
            cost_of = dict(repl_items)
            count_writers = topo.explicit
            m_repl_objs = obs.counter(
                obs.TOPOLOGY_REPLICATED_OBJECTS_WRITTEN
            )
            m_repl_bytes = obs.counter(
                obs.TOPOLOGY_REPLICATED_BYTES_WRITTEN
            )
            for lpath, reqs in repl_reqs.items():
                if assignment[lpath] == rank:
                    write_reqs.extend(reqs)
                    if count_writers:
                        m_repl_objs.inc()
                        m_repl_bytes.inc(cost_of[lpath])
                else:
                    # Only the writer keeps the entry: batching may re-point
                    # the writer's entry at a slab location, and the global
                    # manifest must carry exactly the written copy
                    # (consolidation dedups replicated entries to one rank).
                    del entries[lpath]
            writes_chunk_of: Dict[str, bool] = {}
            counted_chunk_parents: set = set()
            for k, req in repl_chunk_reqs.items():
                lp = chunk_parent[k]
                mine = assignment[k] == rank
                writes_chunk_of[lp] = writes_chunk_of.get(lp, False) or mine
                if mine:
                    write_reqs.append(req)
                    if count_writers:
                        # bytes per chunk, but the OBJECT counts once
                        # per rank carrying any of its chunks — the
                        # doctor row says "objects", not chunks
                        m_repl_bytes.inc(cost_of[k])
                        if lp not in counted_chunk_parents:
                            counted_chunk_parents.add(lp)
                            m_repl_objs.inc()
            for lp, any_mine in writes_chunk_of.items():
                if any_mine:
                    # every chunk-writing rank carries an IDENTICAL copy
                    # of the whole entry (chunk locations are rank-
                    # independent under replicated/); restore dedups
                    split_repl_paths.add(lp)
                else:
                    del entries[lp]

        # coalesce small writes into slabs (reference batcher.py:204-355)
        if not knobs.is_batching_disabled():
            # shield split replicated entries: slab-packing a chunk would
            # re-point it to a rank-LOCAL location, silently diverging the
            # per-rank copies of the shared entry
            shielded = {
                lp: entries.pop(lp) for lp in split_repl_paths if lp in entries
            }
            entries, write_reqs = batch_write_requests(entries, write_reqs, rank)
            entries.update(shielded)

        # whole-object digests feed the metadata objects table and the
        # incremental-dedup decision; attached AFTER batching so slab
        # objects are covered at their final paths
        object_crcs: Dict[str, List[int]] = {}
        # codec frame tables (codec.py): filled by the scheduler for
        # every object it stores compressed; rides the crc gather into
        # SnapshotMetadata.codecs.  Sinks are attached unconditionally
        # (one closure per request) — whether anything encodes is the
        # scheduler's per-run CODEC-knob decision.
        object_codecs: Dict[str, Any] = {}
        for wr in write_reqs:
            def _codec_sink(table: dict, wr=wr) -> None:
                object_codecs[wr.path] = table

            wr.codec_sink = _codec_sink
        if cas_cfg is not None and base is not None:
            # chunk-level addressing dedups against EVERY committed step
            # sharing the pool — the whole-object base link is strictly
            # weaker, and mixing the two storage models in one take
            # would split ownership semantics
            logger.info(
                "rank %d: take(cas=...) supersedes base=%r; using "
                "chunk-level content addressing", rank, base,
            )
            base = None
        if base is not None and base.rstrip("/") == path.rstrip("/"):
            # self-dedup would link an object onto itself (and the fs
            # fallback's unlink-before-link would destroy the only copy)
            logger.warning(
                "rank %d: incremental base equals the target path %r; "
                "performing a full save", rank, path,
            )
            base = None
        if knobs.write_checksums_enabled():
            base_objects: Dict[str, Any] = {}
            base_codecs: Dict[str, Any] = {}
            if base is not None:
                # rank 0 reads the base's object table once and shares it
                # (every rank GETting a multi-MB metadata object from
                # cloud storage at the start of each take is a
                # thundering herd); branch participation is rank-agreed
                # by the gather above
                if rank == 0:
                    try:
                        base_meta = Snapshot(base).metadata
                        base_objects = base_meta.objects or {}
                        # a dedup link copies the base's STORED bytes —
                        # if those were codec frames, the frame table
                        # must carry into this snapshot's manifest
                        base_codecs = base_meta.codecs or {}
                    except Exception as e:  # noqa: BLE001
                        logger.warning(
                            "rank 0: incremental base %r unusable (%r); "
                            "performing a full save", base, e,
                        )
                if world > 1:
                    base_objects, base_codecs = coordinator.broadcast_object(
                        (base_objects, base_codecs), src=0
                    )
            for wr in write_reqs:
                def _object_sink(digest: List[int], wr=wr) -> None:
                    wr.object_digest = tuple(digest)
                    object_crcs[wr.path] = list(digest)

                wr.digest_sink = _object_sink
                base_digest = base_objects.get(wr.path)
                # dedup compares (crc32, adler32, size) — two independent
                # checksums + exact length, so a lone crc32 collision
                # can't silently link stale content
                if (
                    base is not None
                    and isinstance(base_digest, (list, tuple))
                    and len(base_digest) == 3
                ):
                    wr.dedup = (base, tuple(int(x) for x in base_digest))
                    wr.dedup_codec = base_codecs.get(wr.path)
        elif base is not None:
            logger.warning(
                "rank %d: take(base=...) needs WRITE_CHECKSUMS=1; "
                "performing a full save", rank,
            )

        # content-addressed chunk store (cas/): rank 0 reads the
        # committed index's LIVE key set once and shares it (same
        # thundering-herd economics as the base objects table above);
        # every write request gets a context routing it through the
        # pool, with one shared written-this-take set so intra-take
        # repeats (tied weights, identical slabs on two reqs) dedup too
        object_cas: Dict[str, Any] = {}
        cas_store = None
        if cas_cfg is not None:
            from . import cas as cas_mod

            cas_store = cas_mod.ChunkStore(cas_cfg["root"])
            known_keys: set = set()
            if rank == 0:
                try:
                    known_keys = cas_mod.ChunkIndex.load(
                        cas_store
                    ).live_keys()
                except cas_mod.ChunkIndexCorruptError as e:
                    logger.warning(
                        "corrupt chunk index under %r (%r); rebuilding "
                        "via fsck before this take", cas_cfg["root"], e,
                    )
                    try:
                        cas_mod.fsck(cas_cfg["root"])
                        known_keys = cas_mod.ChunkIndex.load(
                            cas_store
                        ).live_keys()
                    except Exception as e2:  # noqa: BLE001
                        logger.warning(
                            "chunk-index fsck under %r failed (%r); "
                            "this take writes every chunk (correct, "
                            "just not deduplicated)", cas_cfg["root"], e2,
                        )
                        known_keys = set()
            if world > 1:
                known_keys = coordinator.broadcast_object(
                    known_keys, src=0
                )
            written_this_take: set = set()
            for wr in write_reqs:
                def _cas_sink(table: dict, wr=wr) -> None:
                    object_cas[wr.path] = table

                wr.cas = cas_mod.CasWriteContext(
                    store=cas_store,
                    known_keys=known_keys,
                    chunk_size=cas_cfg["chunk_size"],
                    sink=_cas_sink,
                    written_this_take=written_this_take,
                )

        # gather per-rank manifests; every rank can build the global view
        # deterministically (reference _gather_manifest, snapshot.py:948-961)
        # NOTE: this serializes entry objects BEFORE staging runs, so
        # checksum sinks (which fire during staging) mutate only the
        # LOCAL objects below — the commit paths re-gather crc maps
        # post-staging and merge them into the metadata (_merge_crcs).
        local_entry_objs = {**manifest, **entries}
        local_manifest_d = {
            lpath: e.to_dict() for lpath, e in local_entry_objs.items()
        }
        if world > 1:
            gathered_manifests = coordinator.all_gather_object(local_manifest_d)
        else:
            gathered_manifests = [local_manifest_d]
        global_manifest = consolidate_manifests(
            [
                {k: entry_from_dict(v) for k, v in md.items()}
                for md in gathered_manifests
            ]
        )
        metadata = SnapshotMetadata(
            version=MANIFEST_VERSION, world_size=world, manifest=global_manifest
        )
        if cas_cfg is not None:
            # the rank-agreed envelope; per-rank chunk tables merge in
            # at commit (_merge_crc_payloads).  The root is recorded
            # relative ("../cas") under the manager layout so a rehomed
            # checkpoint tree keeps restoring.
            from . import cas as cas_mod

            metadata.cas = {
                "root": cas_mod.record_root(path, cas_cfg["root"]),
                "chunk_size": cas_cfg["chunk_size"],
                "chunks": {},
            }

        budget = get_process_memory_budget_bytes()

        # TPU-native unblock-early point: one batched device→pinned_host
        # transfer (plus eager defensive copies of mutable host arrays)
        # makes every pending buffer independent of training state, so the
        # async path returns *before* staging instead of after it — the
        # reference must wait for staged-in-host-RAM because CUDA tensors
        # are mutable (reference scheduler.py:299, io_preparers/
        # tensor.py:283-307); jax.Array immutability moves the safety
        # point to the end of this call.
        unblock_early = is_async and not knobs.is_eager_host_staging_disabled()
        if unblock_early:
            from .host_offload import eager_offload_write_reqs

            # Cap the pinned-host claim at half the staging budget so
            # offloaded-but-unstaged buffers plus in-flight staged copies
            # stay within host RAM; arrays past the cap stage lazily in
            # the background (safe: jax.Array is immutable).
            eager_offload_write_reqs(write_reqs, budget_bytes=budget // 2)
        pending_io = sync_execute_write_reqs(
            write_reqs, storage, budget, rank,
            wait_for_staging=not unblock_early,
        )
        takeover_ctx = _TakeoverContext(
            topo=topo,
            preloads=preloads,
            assignment=assignment,
            repl_reqs=repl_reqs,
            repl_chunk_reqs=repl_chunk_reqs,
            chunk_parent=chunk_parent,
            repl_items=repl_items,
            repl_entries=repl_entry_objs,
            gathered_manifests=gathered_manifests,
        )
        return (
            metadata, pending_io, storage, commit_uid,
            local_entry_objs, object_crcs, object_codecs, object_cas,
            cas_store, takeover_ctx,
        )

    # --------------------------------------------------------------- restore

    @property
    def metadata(self) -> SnapshotMetadata:
        # reference snapshot.py:96-110,842-854
        if self._metadata_cache is None:
            from .io_types import ReadIO

            storage = _storage_for(self.path, self._storage_options)
            try:
                read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
                storage.sync_read(read_io)
            except FileNotFoundError as e:
                # Missing outright (cold start / never committed) is
                # distinguishable from unreadable, so resumable-training
                # loops can `except FileNotFoundError` to cold-start.
                raise FileNotFoundError(
                    f"no {SNAPSHOT_METADATA_FNAME} under {self.path!r} — "
                    f"not a committed snapshot (a snapshot without "
                    f"metadata was aborted before commit)"
                ) from e
            except Exception as e:
                raise RuntimeError(
                    f"failed to read {SNAPSHOT_METADATA_FNAME} under "
                    f"{self.path!r} — the snapshot is incomplete or was "
                    f"aborted before commit ({e!r})"
                ) from e
            finally:
                storage.sync_close()
            self._metadata_cache = SnapshotMetadata.from_yaml(
                bytes(read_io.buf).decode()
            )
        return self._metadata_cache

    def get_manifest(self) -> Dict[str, Entry]:
        return dict(self.metadata.manifest)

    def publish_to(self, publisher: Any, step: int) -> str:
        """Publish this committed snapshot to a live-weight publication
        root (publish/Publisher) so serving subscribers can delta-swap
        to it; returns the publication record path.  ``step`` orders
        the publication (snapshots don't carry one themselves — the
        manager's publish hook passes its index step).  Unlike the
        manager/continuous hooks this is the EXPLICIT path and raises
        on failure."""
        return publisher.publish_snapshot(
            self.path, step, metadata=self.metadata
        )

    def _prime_tier_digests(self, storage: Any) -> None:
        """Tiered storage: install the committed metadata's whole-object
        digest table on the plugin so fast/peer-tier reads verify before
        they are trusted (and silently fall back + repair on mismatch).
        No-op for ordinary plugins.

        Codec-encoded objects (codec.py) verify against their STORED
        digest from the codec table — the bytes on disk are frames, so
        the raw digest in ``objects`` would flag every intact copy as
        corrupt.  An encoded object whose table carries no stored digest
        is left unprimed (trust the read; the frame structure and the
        entry crcs above still catch corruption)."""
        prime = getattr(storage, "prime_digests", None)
        if prime is None:
            return
        digests = dict(self.metadata.objects or {})
        for loc, tbl in (self.metadata.codecs or {}).items():
            stored = tbl.get("digest") if isinstance(tbl, dict) else None
            if (
                isinstance(stored, (list, tuple)) and len(stored) == 3
            ):
                digests[loc] = [int(x) for x in stored]
            else:
                digests.pop(loc, None)
        prime(digests)

    def _codec_tables(self) -> Optional[Dict[str, Any]]:
        """location → validated codec frame table for objects this
        snapshot stored compressed; None when nothing is encoded (the
        common case — reads skip the lookup entirely).  Structurally
        invalid entries (version skew) are dropped with a warning: the
        read then sees stored frame bytes where raw bytes were expected
        and fails loudly at the digest/parse layer instead of silently
        misdecoding."""
        from . import codec as codec_mod

        codecs = self.metadata.codecs or {}
        if not codecs:
            return None
        tables = {}
        for loc, tbl in codecs.items():
            if codec_mod.validate_table(tbl):
                tables[loc] = tbl
            else:
                logger.warning(
                    "manifest codec table for %r is structurally invalid "
                    "(version skew?); treating the object as raw", loc,
                )
        return tables or None

    def _cas_reads(self) -> Optional[Tuple[Any, Dict[str, Any]]]:
        """``(ChunkStore, {location → validated chunk table})`` for
        objects this snapshot stored as chunk references (cas/), or
        None when nothing is chunk-ref'd — pre-CAS snapshots (no
        ``cas`` key at all) restore through the unchanged per-step
        path.  The caller owns closing the returned store."""
        from . import cas as cas_mod

        meta_cas = self.metadata.cas or {}
        if not meta_cas:
            return None
        tables = cas_mod.chunk_tables_from_metadata(self.metadata)
        if not tables:
            return None
        root = cas_mod.resolve_root(self.path, str(meta_cas.get("root")))
        return cas_mod.ChunkStore(root), tables

    @staticmethod
    def _close_cas_reads(cas_reads: Optional[Tuple[Any, Any]]) -> None:
        if cas_reads is not None:
            cas_reads[0].sync_close()

    def restore(
        self,
        app_state: AppState,
        strict: bool = True,
        paths: Optional[Sequence[str]] = None,
        priority: Optional[Sequence[str]] = None,
    ) -> None:
        """Distributed load/reshard into the given app state (reference
        Snapshot.restore, snapshot.py:319-396).

        ``paths`` (beyond-parity): restore only leaves whose logical path
        matches one of the fnmatch globs — e.g. ``["model/params/**"]``
        to warm-start parameters from a pretrained snapshot while the
        optimizer state keeps its fresh values.  Unmatched leaves are
        left untouched (the reference's only alternatives are
        all-or-nothing restore or per-leaf ``read_object``).  Filtering
        implies non-strict inflation for the skipped leaves; ``strict``
        still governs whether app_state keys absent from the snapshot
        raise.

        ``priority`` (serving): an ordered list of fnmatch globs — reads
        whose logical path matches an earlier glob execute first
        (unmatched leaves last), so a server can restore its
        first-requested layers first and begin serving before the full
        snapshot lands.  Ordering only; every leaf is still restored."""
        coordinator = self._coordinator
        rank, world = coordinator.rank, coordinator.world_size
        _validate_app_state(app_state)
        with log_event(
            Event("restore", {"path": self.path, "rank": rank})
        ) as restore_event:
            stamp_stripe = _stripe_event_stamp()
            obs_before = obs.aggregate.capture()
            # abort-aware restore: the scope uid is agreed up front (the
            # per-instance uid counter runs in the same program order on
            # every rank), and covers EVERYTHING that can fail — even a
            # rank dying on the metadata read poisons before its peers
            # enter the key gather, so nobody wedges to a wait timeout.
            # The failing rank re-raises its own error; peers raise a
            # typed SnapshotAbortedError naming it.
            abort_uid = coordinator._next_uid("restore")
            storage = None
            cas_reads = None
            # death-aware restore (resilience/liveness.py): a peer that
            # dies mid-restore surfaces as a typed RankDeadError at the
            # barriers/kv waits within LIVENESS_TIMEOUT_S instead of a
            # full wait-timeout wedge.  No takeover on the read path —
            # restore holds no state its peers need re-created; failing
            # fast with the dead rank named is the whole contract.
            session = LivenessSession(coordinator, abort_uid)
            try:
                session.start()
                with coordinator.abort_scope(abort_uid), \
                        coordinator.liveness_scope(session.monitor):
                    metadata = self.metadata
                    manifest_for_rank = get_manifest_for_rank(metadata, rank)
                    storage = _storage_for(self.path, self._storage_options)
                    self._prime_tier_digests(storage)
                    cas_reads = self._cas_reads()
                    # fan-out restore (topology/fanout.py): per-slice
                    # designated readers pull each replicated object
                    # from the durable tier exactly once and
                    # redistribute over the coordination KV — restore
                    # cost O(objects) per slice, not O(objects × ranks).
                    # The wrapper goes OUTSIDE any host cache, so the
                    # one GET per slice is itself host-deduped; all
                    # ranks must call restore with rank-agreed
                    # paths/priority arguments (the same SPMD contract
                    # every other restore collective already assumes).
                    topo = topology_mod.detect_topology(
                        coordinator, exchange_prefix=f"{abort_uid}/topo"
                    )
                    transport = None
                    if topology_mod.fanout_enabled(topo):
                        shared = topology_mod.shared_read_locations(
                            metadata.manifest
                        )
                        if shared:
                            # payload transport (transport/): the
                            # capability-probed engine the fan-out's
                            # redistribution bytes ride — collectives
                            # when the runtime supports them, the KV
                            # blob path otherwise
                            transport = transport_mod.resolve_transport(
                                coordinator, topology=topo
                            )
                            storage = topology_mod.FanoutReadPlugin(
                                storage, coordinator, topo,
                                f"{abort_uid}/fan", shared,
                                transport=transport,
                            )
                    local_keys = sorted(app_state.keys())
                    if world > 1:
                        global_keys = sorted(
                            set().union(
                                *coordinator.all_gather_object(local_keys)
                            )
                        )
                    else:
                        global_keys = local_keys
                    # RNG state is restored last so earlier restores
                    # cannot perturb it (reference snapshot.py:371-381)
                    global_keys.sort(
                        key=lambda k: isinstance(app_state.get(k), RNGState)
                    )
                    # collective fan-out session: whole shared objects
                    # move as ordered broadcasts over the live jax
                    # runtime instead of KV blobs.  Requires a session-
                    # capable transport, every slice fanning out
                    # (fanout_world_uniform — the gate protocol needs
                    # all world ranks), and a FULL restore (a paths
                    # filter makes "which shared objects get read" a
                    # per-rank question the pre-agreed schedule cannot
                    # answer).  The plan rides the global key order so
                    # the schedule advances with the per-key barriers.
                    if (
                        transport is not None
                        and getattr(transport, "mode", None) == "session"
                        and isinstance(
                            storage, topology_mod.FanoutReadPlugin
                        )
                        and paths is None
                        and topology_mod.fanout_world_uniform(topo)
                    ):
                        try:
                            plan_paths = (
                                topology_mod.ordered_shared_locations(
                                    metadata.manifest,
                                    storage.shared_paths,
                                    global_keys,
                                )
                            )
                            storage.transport_session = (
                                transport.open_fanout_session(
                                    topo, f"{abort_uid}/fan", plan_paths
                                )
                            )
                        except Exception as e:  # noqa: BLE001 — the
                            # restore proceeds on the KV path
                            transport_mod.count_fallback(
                                "session-open", e
                            )
                    for key in global_keys:
                        if key in app_state:
                            self._load_stateful(
                                key, app_state[key], manifest_for_rank,
                                storage, strict, rank, paths=paths,
                                cas_reads=cas_reads, priority=priority,
                            )
                        if world > 1:
                            coordinator.barrier()
                    # fan-out blob cleanup: the per-key barriers above
                    # prove every rank is past its reads, so the
                    # transient publications — KV blobs, collective
                    # session gate keys, device-registry entries — can
                    # be reclaimed (a restore must not permanently grow
                    # the coordination service's store)
                    tsession = getattr(
                        storage, "transport_session", None
                    )
                    if tsession is not None:
                        tsession.close()
                    cleanup = getattr(storage, "cleanup_published", None)
                    if cleanup is not None:
                        cleanup()
                    # restore flight record: cross-rank merge only (no
                    # persistence — the snapshot may live on read-only
                    # storage); rank 0 keeps the merged record
                    # in-process (obs.aggregate.last_record("restore")).
                    # All ranks just left the final barrier, so the
                    # single-phase exchange converges in one KV round.
                    obs.aggregate.exchange_and_merge(
                        coordinator,
                        abort_uid,
                        obs.aggregate.rank_payload(
                            rank, "restore", obs_before
                        ),
                        op="restore",
                        path=self.path,
                    )
            except SnapshotAbortedError:
                raise
            except BaseException as e:
                coordinator.poison(
                    abort_uid, cause=repr(e), site=f"restore/rank{rank}"
                )
                raise
            finally:
                session.stop()
                stamp_stripe(restore_event)
                if storage is not None:
                    # error-path transport teardown (idempotent after
                    # the happy path's close above): the session thread
                    # must not outlive the restore, and the device
                    # registry must not accrete across restores
                    tsession = getattr(
                        storage, "transport_session", None
                    )
                    if tsession is not None:
                        try:
                            tsession.close()
                        except Exception as e:  # noqa: BLE001
                            obs.swallowed_exception(
                                "restore.transport_close", e
                            )
                    transport = getattr(storage, "transport", None)
                    if transport is not None:
                        try:
                            transport.close()
                        except Exception as e:  # noqa: BLE001
                            obs.swallowed_exception(
                                "restore.transport_close", e
                            )
                    storage.sync_close()
                self._close_cas_reads(cas_reads)
            obs.maybe_write_metrics_textfile()

    def _load_stateful(
        self,
        key: str,
        stateful: Any,
        manifest_for_rank: Manifest,
        storage: Any,
        strict: bool,
        rank: int,
        paths: Optional[Sequence[str]] = None,
        cas_reads: Optional[Tuple[Any, Dict[str, Any]]] = None,
        priority: Optional[Sequence[str]] = None,
    ) -> None:
        # reference _load_stateful, snapshot.py:727-782
        with obs.span("restore/load_stateful", key=key, rank=rank):
            self._load_stateful_impl(
                key, stateful, manifest_for_rank, storage, strict, rank,
                paths=paths, cas_reads=cas_reads, priority=priority,
            )

    def _load_stateful_impl(
        self,
        key: str,
        stateful: Any,
        manifest_for_rank: Manifest,
        storage: Any,
        strict: bool,
        rank: int,
        paths: Optional[Sequence[str]] = None,
        cas_reads: Optional[Tuple[Any, Dict[str, Any]]] = None,
        priority: Optional[Sequence[str]] = None,
    ) -> None:
        key_manifest = {
            p: e
            for p, e in manifest_for_rank.items()
            if p == key or p.startswith(key + "/")
        }
        if not key_manifest:
            if strict:
                raise KeyError(
                    f"app_state key {key!r} not found in snapshot manifest"
                )
            logger.warning("skipping %r: not in snapshot", key)
            return
        if paths is not None and not any(
            not is_container_entry(e) and path_is_replicated(p, paths)
            for p, e in key_manifest.items()
        ):
            return  # nothing under this key matches the filter
        # degraded snapshot (takeover, docs/resilience.md): logical
        # paths only a dead rank held are typed-missing, not silently
        # zero.  A marker blocks THIS restore only when this rank's view
        # would actually source the dead rank's bytes: its own rank IS
        # the origin (per-rank private state), the entry is sharded (the
        # merged view includes the dead rank's lost boxes), or it is
        # replicated and was not taken over (every view overlays the
        # dead writer's copy).  A peer's intact private copy of the same
        # logical path restores normally.  Steer around the gap with
        # restore(paths=...), or heal it first (SnapshotManager.repair()
        # / the next take).
        degraded = getattr(self.metadata, "degraded", None) or {}
        if degraded:
            hits = sorted(
                p
                for p, e in key_manifest.items()
                if p in degraded
                and not is_container_entry(e)
                and (paths is None or path_is_replicated(p, paths))
                and (
                    rank == degraded[p].get("origin_rank")
                    or isinstance(e, ShardedArrayEntry)
                    or bool(getattr(e, "replicated", False))
                )
            )
            if hits:
                raise DegradedSnapshotError(self.path, hits)
        # current state provides in-place/sharding templates
        # (reference snapshot.py:754-762)
        _, targets = flatten(stateful.state_dict(), prefix=key)
        self._map_legacy_leaf_targets(key, stateful, key_manifest, targets)

        container_entries: Manifest = {}
        read_reqs: List[ReadReq] = []
        futures: Dict[str, Future] = {}
        for lpath, entry in key_manifest.items():
            if is_container_entry(entry):
                container_entries[lpath] = entry
                continue
            if paths is not None and not path_is_replicated(lpath, paths):
                # partial restore: no read for unmatched leaves — but
                # list/tuple structure must survive inflation, so seed
                # the slot with the CURRENT value instead of dropping it
                # (a dropped ListEntry child would compact the list and
                # shift later elements onto wrong indices).  Membership,
                # not is-None: a present-but-None leaf still holds its
                # list slot.
                if lpath in targets:
                    fut: Future = Future(targets[lpath])
                    fut.set(targets[lpath])
                    futures[lpath] = fut
                continue
            reqs, fut = prepare_read(entry, obj_out=targets.get(lpath))
            if priority:
                pri = _read_priority_for(lpath, priority)
                for r in reqs:
                    r.priority = pri
            read_reqs.extend(reqs)
            futures[lpath] = fut
        if not knobs.is_batching_disabled():
            read_reqs = batch_read_requests(read_reqs)
        budget = get_process_memory_budget_bytes()
        try:
            sync_execute_read_reqs(
                read_reqs, storage, budget, rank,
                codec_tables=self._codec_tables(),
                cas_reads=cas_reads,
                # fan-out: front-load the reads THIS rank must publish
                # for its slice siblings, so their waits are minimal
                publish_first=getattr(storage, "local_publish_paths", None),
            )
            restored = {lpath: fut.obj for lpath, fut in futures.items()}
            state_dict = inflate(
                container_entries,
                restored,
                prefix=key,
                allow_missing=(not strict) or paths is not None,
            )
            # propagate strict to load_state_dict when the stateful
            # accepts it (reference snapshot.py:775-778 for nn.Module); a
            # paths filter implies non-strict (unmatched leaves keep
            # current values)
            load_with_strict(
                stateful, state_dict, strict and paths is None
            )
        except BaseException:
            self._repair_after_failed_restore(
                key, stateful, container_entries, futures, targets
            )
            raise

    @staticmethod
    def _repair_after_failed_restore(
        key: str,
        stateful: Any,
        container_entries: Manifest,
        futures: Dict[str, Future],
        targets: Dict[str, Any],
    ) -> None:
        """Keep the caller's live state free of deleted arrays after a
        mid-stateful restore failure.

        Restore donation (1x device peak, see
        ``preparers/array.py:donate_template``) frees each template's
        buffers as soon as its replacement materializes.  A failure on a
        LATER leaf would otherwise leave earlier templates deleted while
        still reachable from the caller's state — any use raises XLA's
        "Array has been deleted".  Every donation happens strictly after
        ``fut.set``, so each donated template has a retrievable
        replacement: load the already-restored leaves (keeping intact
        templates for the rest, non-strict) so the state is mixed
        old/new but entirely VALID — the same mid-failure semantics as
        the reference's in-place tensor load (snapshot.py:743-753).
        No-op when no template was actually donated (donation off, host
        templates, or the failure hit the first leaf)."""
        def _is_deleted(t: Any) -> bool:
            is_deleted = getattr(t, "is_deleted", None)
            if callable(is_deleted):
                try:
                    return bool(is_deleted())
                except Exception:  # noqa: BLE001 — e.g. inside a transform
                    return False
            return False

        deleted = sum(1 for t in targets.values() if _is_deleted(t))
        if not deleted:
            return
        # One array object can be the template for several paths (tied
        # weights).  Map template identity → its restored replacement so
        # a path whose OWN read never finished but whose (shared)
        # template was donated by a sibling path gets the sibling's
        # replacement — never the deleted array itself.
        replacement_by_template: Dict[int, Any] = {}
        for lpath, fut in futures.items():
            if fut.done and lpath in targets and fut.obj is not targets[lpath]:
                replacement_by_template[id(targets[lpath])] = fut.obj
        restored: Dict[str, Any] = {}
        for lpath, fut in futures.items():
            if fut.done:
                restored[lpath] = fut.obj
            elif lpath in targets:
                t = targets[lpath]
                if not _is_deleted(t):
                    restored[lpath] = t
                elif id(t) in replacement_by_template:
                    restored[lpath] = replacement_by_template[id(t)]
                # else: deleted with no known replacement (cannot happen
                # given donate-after-fut.set ordering) — omit the path
                # rather than load a dead array; allow_missing keeps the
                # structure intact
        try:
            state_dict = inflate(
                container_entries, restored, prefix=key, allow_missing=True
            )
            load_with_strict(stateful, state_dict, False)
            logger.warning(
                "restore of %r failed after donation freed %d template(s); "
                "loaded the partially-restored state so live arrays remain "
                "valid — the state is now MIXED (restored leaves + prior "
                "values). Set TORCHSNAPSHOT_TPU_RESTORE_DONATE=0 to keep "
                "templates fully intact on failure (2x device peak).",
                key, deleted,
            )
        except Exception:
            logger.exception(
                "restore of %r failed after donation freed %d template(s), "
                "and repairing the live state also failed — state for this "
                "key may reference deleted arrays", key, deleted,
            )

    @staticmethod
    def _map_legacy_leaf_targets(
        key: str, stateful: Any, key_manifest: Manifest, targets: Dict[str, Any]
    ) -> None:
        """Snapshots written before PyTreeState rendered NAMED paths store
        leaves as ``<key>/leaves/<i>``; a current PyTreeState's named
        targets would never match them, losing the in-place/sharding
        templates (full-array host reads, no device placement).  Map the
        template's leaves onto the legacy paths positionally — the same
        order both formats derive from ``jax.tree_util`` flattening."""
        import re

        from .stateful import PyTreeState, _tree_path_keys

        stateful = unwrap(stateful)
        if not isinstance(stateful, PyTreeState):
            return
        pat = re.compile(re.escape(key) + r"/leaves/(\d+)$")
        legacy = {
            int(m.group(1)): p
            for p in key_manifest
            if (m := pat.fullmatch(p)) and not is_container_entry(key_manifest[p])
        }
        if not legacy or any(p in targets for p in legacy.values()):
            return
        pairs, _ = _tree_path_keys(stateful.tree)
        for i, (_, leaf) in enumerate(pairs):
            if i in legacy:
                targets[legacy[i]] = leaf

    # ----------------------------------------------------------- read_object

    def verify(self, deep: bool = False) -> "Any":
        """Integrity audit of this rank's view (beyond-parity; see
        verify.py): every referenced object must exist with at least the
        byte extent the manifest claims; ``deep=True`` additionally
        dry-run-restores every entry.  Returns a ``VerifyResult``."""
        from .verify import verify_snapshot

        # no bracket here: verify_snapshot brackets itself with
        # log_event(Event("verify", ...)) (verify.py) — a second one
        # would double-count the operation for every handler
        return verify_snapshot(self, deep=deep)

    def repair_degraded(
        self,
        sources: Sequence[str],
        paths: Optional[Sequence[str]] = None,
    ) -> List[str]:
        """Heal a degraded snapshot IN PLACE from continuous peer
        stores (docs/resilience.md).

        A snapshot committed degraded lost state only the dead rank
        held.  The continuous checkpoint loop keeps a per-rank RAM/disk
        mirror under ``<host-root>/r<rank>`` on every peer the dead
        rank replicated to — this re-reads the lost leaves from those
        mirrors (content-verified), re-writes them at their manifest
        locations, drops them from the ``degraded`` section and
        rewrites the commit marker.  Single-process ops tool: no
        coordination — only the dead rank's entries and the marker are
        touched, marker rewritten strictly last.

        ``sources``: continuous host roots (the per-rank ``r<d>``
        subdir is probed) and/or direct per-rank store roots ending in
        ``/r<d>``.  ``paths``: restrict to these logical paths.

        Returns the logical paths repaired.  Sharded device state
        cannot be rebuilt from a host mirror (the mesh is gone) — such
        paths are skipped with a warning; only a fresh complete take
        heals them."""
        with log_event(
            Event("repair_degraded", {"path": self.path})
        ), obs.span("snapshot/repair_degraded", path=self.path):
            return self._repair_degraded_impl(sources, paths)

    def _read_peer_leaves(
        self, sources: Sequence[str], origin: int, lpaths: Sequence[str]
    ) -> Dict[str, Any]:
        """Materialize the wanted logical paths from the first usable
        continuous mirror of rank ``origin``.  Only roots NAMESPACED to
        that rank are probed — a same-shaped leaf from some other
        rank's mirror would be the wrong rank's data."""
        from .continuous.store import ContinuousStore, decode_leaf

        wanted = set(lpaths)
        for src in sources:
            src = str(src).rstrip("/")
            root = src if src.endswith(f"/r{origin}") else f"{src}/r{origin}"
            store = ContinuousStore(root)
            try:
                head = store.read_head()
                if head is None:
                    continue
                manifest = store.read_step_manifest(str(head["manifest"]))
                recs = {
                    lp: rec
                    for lp, rec in manifest["leaves"].items()
                    if lp in wanted
                }
                if not recs:
                    continue
                chunks = store.read_chunks(
                    [k for rec in recs.values() for k in rec["keys"]]
                )
                out: Dict[str, Any] = {}
                for lp, rec in recs.items():
                    data = b"".join(chunks[k] for k in rec["keys"])
                    if len(data) != int(rec["size"]):
                        raise IOError(
                            f"leaf {lp!r}: assembled {len(data)} bytes, "
                            f"manifest says {rec['size']}"
                        )
                    out[lp] = decode_leaf(rec, data)
                logger.info(
                    "repair: recovered %d/%d leaves of dead rank %d from "
                    "%r (step %d)",
                    len(out), len(wanted), origin, root, int(head["step"]),
                )
                return out
            except Exception as e:  # noqa: BLE001 — ladder to next source
                logger.warning(
                    "repair source %r unusable for rank %d (%r); trying "
                    "the next one", root, origin, e,
                )
            finally:
                store.sync_close()
        return {}

    def _repair_degraded_impl(
        self, sources: Sequence[str], paths: Optional[Sequence[str]]
    ) -> List[str]:
        metadata = self.metadata
        degraded = dict(getattr(metadata, "degraded", None) or {})
        if not degraded:
            return []
        if isinstance(sources, str):
            sources = [sources]
        wanted = {
            p: info
            for p, info in degraded.items()
            if paths is None or p in set(paths)
        }
        by_origin: Dict[int, List[str]] = {}
        for p, info in wanted.items():
            by_origin.setdefault(int(info.get("origin_rank", -1)), []).append(p)
        cksum = knobs.write_checksums_enabled()
        storage = _storage_for(self.path, self._storage_options)
        repaired: List[str] = []
        try:
            for d, lpaths in sorted(by_origin.items()):
                leaves = self._read_peer_leaves(sources, d, lpaths)
                reqs: List[WriteReq] = []
                staged: List[Tuple[str, Entry]] = []
                for lp in sorted(set(lpaths) & set(leaves)):
                    old = metadata.manifest.get(f"{d}/{lp}")
                    if isinstance(old, ShardedArrayEntry):
                        logger.warning(
                            "repair: %r is sharded device state — a host "
                            "mirror cannot rebuild the mesh layout; only "
                            "a fresh take heals it", lp,
                        )
                        continue
                    entry, ereqs = prepare_write(
                        obj=leaves[lp], logical_path=lp, rank=d,
                    )
                    for wr in ereqs:
                        # plain writes (no codec_sink): a repaired object
                        # must read through the raw path, so stale codec
                        # tables for its locations are dropped below
                        if cksum:
                            def _sink(digest: List[int], wr=wr) -> None:
                                wr.object_digest = tuple(digest)
                                metadata.objects[wr.path] = list(digest)

                            wr.digest_sink = _sink
                    reqs.extend(ereqs)
                    staged.append((lp, entry))
                if not staged:
                    continue
                sync_execute_write_reqs(
                    reqs, storage, get_process_memory_budget_bytes(),
                    self._coordinator.rank,
                ).sync_complete()
                for lp, entry in staged:
                    old = metadata.manifest.get(f"{d}/{lp}")
                    if old is not None:
                        # the dead rank's never-landed locations leave
                        # the objects/codecs tables with the entry
                        old_locs = [
                            loc
                            for loc in [getattr(old, "location", None)]
                            if isinstance(loc, str)
                        ] + [
                            s.location
                            for attr in ("shards", "chunks")
                            for s in getattr(old, attr, None) or ()
                        ]
                        for loc in old_locs:
                            metadata.codecs.pop(loc, None)
                            if cksum:
                                # keep only digests the repair re-stamped
                                new_locs = {r.path for r in reqs}
                                if loc not in new_locs:
                                    metadata.objects.pop(loc, None)
                    metadata.manifest[f"{d}/{lp}"] = entry
                    metadata.degraded.pop(lp, None)
                    repaired.append(lp)
            if repaired:
                # marker strictly last: a crash mid-repair leaves a
                # still-committed (still-degraded) snapshot, never a
                # marker pointing at unwritten repairs
                storage.sync_write(
                    WriteIO(
                        path=SNAPSHOT_METADATA_FNAME,
                        buf=metadata.to_yaml().encode(),
                        durable=True,
                    )
                )
                obs.counter(obs.TAKEOVER_PATHS_REPAIRED).inc(len(repaired))
                logger.warning(
                    "repair: healed %d degraded path(s) of %r; %d still "
                    "degraded", len(repaired), self.path,
                    len(metadata.degraded),
                )
        finally:
            storage.sync_close()
        return sorted(repaired)

    def materialize(
        self, rank: Optional[int] = None,
        priority: Optional[Sequence[str]] = None,
    ) -> Dict[str, Any]:
        """Read one rank's ENTIRE view into a nested state dict of host
        values — no templates, no app_state (beyond-parity; the
        reference's only template-free access is per-leaf read_object,
        snapshot.py:397-501).  Arrays come back as numpy; move them to
        device with ``jax.tree.map(jnp.asarray, ...)``.

        With the MMAP knob on (the default) and a local/cached source,
        arrays come back as READ-ONLY mmap-backed views — zero heap
        copies, pages fault in from the page cache on first touch.
        Call ``np.copy`` on a leaf if you need a private writable
        buffer.  ``priority`` orders the reads like ``restore``'s
        (first-matching-glob first).

        For inspection, migration and tooling; a training restore should
        keep using ``restore`` (sharded templates, in-place semantics,
        donation).  Note: PyTreeState records stringified pytree paths
        (its treedef owns the structure), so its list/tuple nodes come
        back as index-keyed dicts here; StateDict trees keep real
        lists."""
        if rank is None:
            rank = self._coordinator.rank
        world = self.metadata.world_size
        if not 0 <= rank < world:
            # get_manifest_for_rank's grown-world semantics would return
            # a replicated-only view — silently missing rank-private
            # leaves is exactly wrong for an inspection API
            raise ValueError(
                f"rank {rank} out of range for world_size={world}"
            )
        with log_event(
            Event("materialize", {"path": self.path, "rank": rank})
        ):
            manifest = get_manifest_for_rank(self.metadata, rank)
            containers = {
                p: e for p, e in manifest.items() if is_container_entry(e)
            }
            futures: Dict[str, Future] = {}
            read_reqs: List[ReadReq] = []
            for p, e in manifest.items():
                if not is_container_entry(e):
                    reqs, fut = prepare_read(e, obj_out=None)
                    if priority:
                        pri = _read_priority_for(p, priority)
                        for r in reqs:
                            r.priority = pri
                    read_reqs.extend(reqs)
                    futures[p] = fut
            if not knobs.is_batching_disabled():
                read_reqs = batch_read_requests(read_reqs)
            storage = _storage_for(self.path, self._storage_options)
            self._prime_tier_digests(storage)
            cas_reads = self._cas_reads()
            try:
                sync_execute_read_reqs(
                    read_reqs, storage, get_process_memory_budget_bytes(),
                    rank, codec_tables=self._codec_tables(),
                    cas_reads=cas_reads,
                )
            finally:
                storage.sync_close()
                self._close_cas_reads(cas_reads)
            leaves = {p: fut.obj for p, fut in futures.items()}
            return {
                key: inflate(containers, leaves, prefix=key)
                for key in sorted({p.split("/", 1)[0] for p in manifest})
            }

    def read_object(
        self,
        path: str,
        obj_out: Optional[Any] = None,
        memory_budget_bytes: Optional[int] = None,
    ) -> Any:
        """Random access to a single object: ``path`` is
        ``"<rank>/<logical_path>"`` (reference Snapshot.read_object,
        snapshot.py:397-501)."""
        with log_event(Event("read_object", {"path": path})):
            rank_str, _, lpath = path.partition("/")
            manifest = get_manifest_for_rank(self.metadata, int(rank_str))
            if lpath not in manifest:
                raise KeyError(f"{lpath!r} not in snapshot manifest")
            entry = manifest[lpath]
            if isinstance(entry, PrimitiveEntry):
                return entry.get_value()
            reqs, fut = prepare_read(
                entry, obj_out=obj_out, buffer_size_limit_bytes=memory_budget_bytes
            )
            storage = _storage_for(self.path, self._storage_options)
            self._prime_tier_digests(storage)
            cas_reads = self._cas_reads()
            try:
                sync_execute_read_reqs(
                    reqs,
                    storage,
                    memory_budget_bytes or get_process_memory_budget_bytes(),
                    rank=0,
                    codec_tables=self._codec_tables(),
                    cas_reads=cas_reads,
                )
            finally:
                storage.sync_close()
                self._close_cas_reads(cas_reads)
            return fut.obj


class PendingSnapshot:
    """Handle for an in-flight async snapshot (reference PendingSnapshot,
    snapshot.py:962-1065).

    The background thread performs storage-I/O drain + a KV-only commit
    barrier: every rank reports done-or-error under the commit uid; rank 0
    writes ``.snapshot_metadata`` iff every rank succeeded, then releases
    the barrier.  Metadata is NEVER written on failure (asserted by
    fault-injection tests, reference tests/test_async_take.py:96-117).
    """

    def __init__(
        self,
        path: str,
        metadata: SnapshotMetadata,
        pending_io_work: PendingIOWork,
        storage: Any,
        coordinator: Coordinator,
        commit_uid: str,
        local_entries: Optional[Dict[str, Entry]] = None,
        object_crcs: Optional[Dict[str, int]] = None,
        object_codecs: Optional[Dict[str, Any]] = None,
        storage_options: Optional[Dict[str, Any]] = None,
        obs_before: Optional[Dict[str, Any]] = None,
        object_cas: Optional[Dict[str, Any]] = None,
        cas_store: Optional[Any] = None,
        takeover_ctx: Optional[_TakeoverContext] = None,
        liveness_session: Optional[LivenessSession] = None,
    ) -> None:
        self.path = path
        self._storage_options = storage_options
        # metrics capture at async_take entry: the commit thread deltas
        # against it after the background drain, so the flight record
        # covers staging + I/O that ran after the caller unblocked
        self._obs_before = obs_before or obs.aggregate.capture()
        self._metadata = metadata
        self._pending_io_work = pending_io_work
        self._storage = storage
        self._coordinator = coordinator
        self._commit_uid = commit_uid
        self._local_entries = local_entries or {}
        self._object_crcs = object_crcs if object_crcs is not None else {}
        # codec frame tables (codec.py): filled by the background
        # staging/write work as objects store compressed; read at
        # commit time on the same thread that runs sync_complete(), so
        # every sink has fired before the payload is built
        self._object_codecs = (
            object_codecs if object_codecs is not None else {}
        )
        # chunk tables (cas/): same lifecycle as the codec tables — read
        # at commit time on the thread that ran sync_complete(), so
        # every sink has fired; the store handle closes with the commit
        self._object_cas = object_cas if object_cas is not None else {}
        self._cas_store = cas_store
        # write takeover (resilience): planning-time context + the
        # liveness session (handed off by async_take, already stamping
        # since before planning), so a peer rank dying during the
        # background commit is survived the same way as in the sync
        # path.  Assigned HERE (before the thread starts) so there is
        # no attribute race with the commit thread.
        self._takeover_ctx = takeover_ctx
        self._liveness_session = liveness_session or LivenessSession(
            coordinator, commit_uid
        )
        self._committed = False
        self._exc: Optional[BaseException] = None
        self._snapshot: Optional[Snapshot] = None
        self._thread = threading.Thread(
            target=self._complete_snapshot, name="tsnp-commit", daemon=True
        )
        self._thread.start()

    def _complete_snapshot(self) -> None:
        # KV ops only — never collectives, never uid-counter-based gathers
        # (those belong to the foreground thread's program order)
        coord = self._coordinator
        uid = self._commit_uid
        rank, world = coord.rank, coord.world_size
        status = "ok"
        try:
            self._pending_io_work.sync_complete()
            # tiered storage: peer replication + write-back promotion
            # hand-off.  KV-only (explicit keys), so it is legal here;
            # runs only when this rank's writes all succeeded.
            finalize = getattr(self._storage, "finalize_take", None)
            if finalize is not None:
                finalize(coord, uid)
        except BaseException as e:  # noqa: BLE001
            self._exc = e
            status = f"err:{e!r}"
            # poison FIRST: peers blocked in the abort-aware waits below
            # learn of this failure in one poll interval even before the
            # arrive/depart protocol rounds complete
            coord.poison(uid, cause=repr(e), site=f"async_commit/rank{rank}")
        # death-aware background commit: heartbeat under the commit uid
        # and run the protocol's kv waits with the liveness monitor, so
        # a SIGKILLed peer surfaces as RankDeadError (handled inside the
        # protocol via write takeover) instead of a full wait timeout
        try:
            self._liveness_session.start()
            with coord.abort_scope(uid), coord.liveness_scope(
                self._liveness_session.monitor
            ):
                self._complete_snapshot_protocol(
                    coord, uid, rank, world, status
                )
        finally:
            self._liveness_session.stop()

    def _complete_snapshot_protocol(
        self, coord: Coordinator, uid: str, rank: int, world: int, status: str
    ) -> None:
        try:
            # content checksums finalized during background staging ride
            # the KV channel (collectives are forbidden here); set BEFORE
            # arrive so rank 0's post-arrival read always finds them
            import json as _json

            if status == "ok":
                try:
                    coord.kv_set(
                        f"{uid}/crcs/{rank}",
                        _json.dumps(
                            _crc_payload(
                                self._local_entries,
                                self._object_crcs,
                                self._object_codecs,
                                self._object_cas,
                            )
                        ),
                    )
                except Exception as e:  # noqa: BLE001
                    if self._object_codecs or self._object_cas:
                        # codec frame tables and chunk tables ride this
                        # channel and are the DECODE/ASSEMBLY RECIPE for
                        # this rank's compressed/chunk-ref'd objects —
                        # committing without them produces a durable
                        # snapshot that cannot be restored, so this rank
                        # must fail the commit (arrive carries the
                        # error; rank 0 withholds the marker).  Plain
                        # checksums stay best-effort.
                        status = f"err:codec/chunk tables lost: {e!r}"
                        if self._exc is None:
                            self._exc = e
                    coord.kv_set(f"{uid}/crcs/{rank}", "{}")
            else:
                coord.kv_set(f"{uid}/crcs/{rank}", "{}")
            # flight record, publish half: before arrive, so rank 0's
            # post-arrival merge always finds every surviving rank's
            # payload.  Best-effort by contract.
            obs.aggregate.publish(
                coord,
                uid,
                obs.aggregate.rank_payload(rank, "take", self._obs_before),
            )
            coord.kv_set(f"{uid}/arrive/{rank}", status)
            if rank == 0:
                # ALWAYS set the depart key, even if the metadata write
                # itself raises — otherwise peers block until timeout with
                # a misleading error.
                try:
                    statuses = [
                        coord.kv_get(f"{uid}/arrive/{r}") for r in range(world)
                    ]
                    failed = [s for s in statuses if s != "ok"]
                    if not failed:
                        raw_payloads = None
                        try:
                            raw_payloads = [
                                coord.kv_get(f"{uid}/crcs/{r}")
                                for r in range(world)
                            ]
                            _merge_crc_payloads(
                                self._metadata,
                                [_json.loads(p) for p in raw_payloads],
                            )
                        except Exception:  # noqa: BLE001
                            # plain checksums are best-effort, but codec
                            # frame tables / chunk tables in these
                            # payloads are the decode/assembly recipe
                            # for compressed/chunk-ref'd objects — if
                            # any rank reported one (or the reads failed
                            # so we cannot tell), the commit must fail
                            # rather than durably strand unreadable
                            # bytes behind a raw-path manifest
                            if raw_payloads is None or any(
                                '"codecs"' in p or '"cas"' in p
                                for p in raw_payloads
                            ):
                                raise
                            logger.warning(
                                "crc merge failed; committing without "
                                "checksums", exc_info=True,
                            )
                        # chunk-store index update STRICTLY before the
                        # commit marker (poison re-checked just below,
                        # before the marker — same invariant as the
                        # sync path)
                        _cas_commit_refs(
                            self._metadata, self.path, self._cas_store
                        )
                        # flight record, merge half: every surviving
                        # rank published before its arrive key, and
                        # all arrive keys were read above — persist
                        # the merged record BEFORE the commit marker
                        try:
                            obs.aggregate.write_obsrecord(
                                self._storage,
                                obs.aggregate.collect_and_merge(
                                    coord, uid, op="take", path=self.path,
                                ),
                            )
                        except Exception as e:  # noqa: BLE001
                            obs.swallowed_exception(
                                "async_commit.obsrecord", e
                            )
                        # durable-commit invariant: never write the
                        # commit marker after the scope was poisoned
                        coord.raise_if_poisoned(uid)
                        self._storage.sync_write(
                            WriteIO(
                                path=SNAPSHOT_METADATA_FNAME,
                                buf=self._metadata.to_yaml().encode(),
                                durable=True,
                            )
                        )
                        self._committed = True
                        depart = "ok"
                    else:
                        depart = f"peers failed: {failed}"
                except BaseException as e:  # noqa: BLE001
                    depart = f"rank 0 commit failed: {e!r}"
                    coord.kv_set(f"{uid}/depart", depart)
                    raise
                coord.kv_set(f"{uid}/depart", depart)
            depart = coord.kv_get(f"{uid}/depart")
            if depart != "ok" and self._exc is None:
                self._exc = RuntimeError(
                    f"async snapshot commit failed: {depart}"
                )
            if depart == "ok" and (
                getattr(self._storage, "policy", None) != "write_back"
            ):
                # goodput: the durable marker just landed (write-back
                # tiers report from the promoter's metadata copy
                # instead)
                obs.goodput.durable_commit(self.path)
        except RankDeadError as dead_err:
            # a peer died during the background commit.  Recovery uses
            # only kv_set/kv_try_get (no scoped waits), so running it
            # here — scopes still active — is safe; tolerance for the
            # known-dead set lives in _recovery_kv_get.
            try:
                if status != "ok":
                    # this rank already failed and poisoned; a dead peer
                    # on top of that doesn't change the local outcome
                    raise dead_err
                self._recover_after_death(coord, uid, rank, world, dead_err)
            except BaseException as e:  # noqa: BLE001
                coord.poison(
                    uid, cause=repr(e), site=f"takeover/rank{rank}"
                )
                if self._exc is None:
                    self._exc = e
        except BaseException as e:  # noqa: BLE001
            if self._exc is None:
                self._exc = e
        finally:
            # the drained work pins the staged host buffers through its
            # starter/future closures; a PendingSnapshot handle may
            # outlive the commit arbitrarily (e.g. held by a manager's
            # sweep list), so drop them the moment they're consumed
            self._pending_io_work = None
            obs.maybe_write_metrics_textfile()
            if self._cas_store is not None:
                try:
                    self._cas_store.sync_close()
                except Exception:  # noqa: BLE001 — teardown only
                    logger.warning(
                        "chunk-store close after async commit failed",
                        exc_info=True,
                    )
            try:
                self._storage.sync_close()
            except Exception:
                # the commit outcome is already decided (self._exc);
                # a teardown failure must not overwrite it — but a
                # leaked executor/fd is worth a visible warning
                logger.warning(
                    "storage close after async commit failed",
                    exc_info=True,
                )

    def _recover_after_death(
        self,
        coord: Coordinator,
        uid: str,
        rank: int,
        world: int,
        dead_err: RankDeadError,
    ) -> None:
        """Finish the background commit without the dead peer(s) — same
        machinery as the sync path.  Async caveat (documented in
        docs/resilience.md): a takeover writer re-stages the orphaned
        replicated objects from the live application state, which may
        have advanced since async_take returned; the re-written copies
        are self-consistent but can be newer than the dead rank's."""
        if (
            self._takeover_ctx is None
            or not knobs.takeover_enabled()
            or world <= 1
        ):
            raise dead_err
        _recover_commit_after_death(
            coordinator=coord,
            commit_uid=uid,
            path=self.path,
            metadata=self._metadata,
            storage=self._storage,
            local_entries=self._local_entries,
            object_crcs=self._object_crcs,
            object_codecs=self._object_codecs,
            object_cas=self._object_cas,
            cas_store=self._cas_store,
            ctx=self._takeover_ctx,
            monitor=self._liveness_session.monitor,
            dead_err=dead_err,
            already_committed=self._committed,
        )
        self._committed = True
        if getattr(self._storage, "policy", None) != "write_back":
            obs.goodput.durable_commit(self.path)

    def wait(self) -> Snapshot:
        """Block until the background commit finishes; re-raise any error
        (reference snapshot.py:1056-1065)."""
        self._thread.join()
        if self._exc is not None:
            raise self._exc
        if self._snapshot is None:
            self._snapshot = Snapshot(
                self.path,
                self._coordinator,
                storage_options=self._storage_options,
            )
            if self._coordinator.rank == 0:
                # rank 0's commit thread merged the gathered checksums
                # into this manifest before writing it
                self._snapshot._metadata_cache = self._metadata
            # other ranks lazy-load the COMMITTED metadata: their local
            # copy never saw the crc merge, and a handle whose manifest
            # silently lacks checksums would make verify(deep=True) skip
            # every content check
        return self._snapshot

    def done(self) -> bool:
        return not self._thread.is_alive()
