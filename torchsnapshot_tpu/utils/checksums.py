"""Pure-Python crc32/adler32 combination (zlib's crc32_combine /
adler32_combine, which the stdlib does not expose).

Why: a slab write needs BOTH per-member crc32s (manifest entries) and the
whole-object (crc32, adler32, size) digest (incremental dedup, verify).
Computing them independently costs three full passes over every staged
byte; combining the per-member values costs O(members · log(len)) integer
math instead, so the staged buffer is touched once per checksum kind.

crc32_combine: crc32 is a linear function over GF(2); appending ``len2``
zero bytes to a message multiplies its crc (as a 32-bit GF(2) vector) by
a fixed matrix to the ``len2``-th power — applied via binary matrix
squaring exactly like zlib's crc32_combine_.

adler32_combine: adler's two 16-bit sums shift by closed-form modular
arithmetic (mod 65521), matching zlib's adler32_combine_.
"""

from __future__ import annotations

from typing import Sequence, Tuple

_CRC_POLY = 0xEDB88320
_ADLER_MOD = 65521


def _gf2_matrix_times(mat: Sequence[int], vec: int) -> int:
    total = 0
    i = 0
    while vec:
        if vec & 1:
            total ^= mat[i]
        vec >>= 1
        i += 1
    return total


def _gf2_matrix_square(square: list, mat: Sequence[int]) -> None:
    for n in range(32):
        square[n] = _gf2_matrix_times(mat, mat[n])


def crc32_combine(crc1: int, crc2: int, len2: int) -> int:
    """crc32 of A+B given crc32(A), crc32(B), len(B)."""
    if len2 <= 0:
        return crc1 & 0xFFFFFFFF
    even = [0] * 32
    odd = [0] * 32
    # odd = the "advance one zero byte... actually one BIT" operator
    odd[0] = _CRC_POLY
    row = 1
    for n in range(1, 32):
        odd[n] = row
        row <<= 1
    # even = advance 2 bits; odd (re-derived) = advance 4 bits; then the
    # loop squares alternately, applying the operator for each set bit
    # of len2 (len2 is in BYTES: start by advancing 8 bits per unit)
    _gf2_matrix_square(even, odd)  # 2 bits
    _gf2_matrix_square(odd, even)  # 4 bits
    crc1 &= 0xFFFFFFFF
    crc2 &= 0xFFFFFFFF
    while True:
        _gf2_matrix_square(even, odd)  # 8, 32, 128... bits
        if len2 & 1:
            crc1 = _gf2_matrix_times(even, crc1)
        len2 >>= 1
        if not len2:
            break
        _gf2_matrix_square(odd, even)
        if len2 & 1:
            crc1 = _gf2_matrix_times(odd, crc1)
        len2 >>= 1
        if not len2:
            break
    return (crc1 ^ crc2) & 0xFFFFFFFF


def adler32_combine(ad1: int, ad2: int, len2: int) -> int:
    """adler32 of A+B given adler32(A), adler32(B), len(B)."""
    if len2 <= 0:
        return ad1 & 0xFFFFFFFF
    rem = len2 % _ADLER_MOD
    sum1 = ad1 & 0xFFFF
    sum2 = (rem * sum1) % _ADLER_MOD
    sum1 += (ad2 & 0xFFFF) + _ADLER_MOD - 1
    sum2 += ((ad1 >> 16) & 0xFFFF) + ((ad2 >> 16) & 0xFFFF) + _ADLER_MOD - rem
    if sum1 >= _ADLER_MOD:
        sum1 -= _ADLER_MOD
    if sum1 >= _ADLER_MOD:
        sum1 -= _ADLER_MOD
    if sum2 >= (_ADLER_MOD << 1):
        sum2 -= _ADLER_MOD << 1
    if sum2 >= _ADLER_MOD:
        sum2 -= _ADLER_MOD
    return (sum1 | (sum2 << 16)) & 0xFFFFFFFF


def combine_piece_digests(
    pieces: Sequence[Tuple[int, int, int]],
) -> Tuple[int, int, int]:
    """Fold per-piece (crc32, adler32, nbytes) — in buffer order, exactly
    tiling the object — into the whole object's digest."""
    crc, adler, total = 0, 1, 0
    for pc, pa, pn in pieces:
        crc = crc32_combine(crc, pc, pn)
        adler = adler32_combine(adler, pa, pn)
        total += pn
    return crc, adler, total
