"""Pure-Python crc32/adler32 combination (zlib's crc32_combine /
adler32_combine, which the stdlib does not expose).

Why: a slab write needs BOTH per-member crc32s (manifest entries) and the
whole-object (crc32, adler32, size) digest (incremental dedup, verify).
Computing them independently costs three full passes over every staged
byte; combining the per-member values costs O(members · log(len)) integer
math instead, so the staged buffer is touched once per checksum kind.

crc32_combine: crc32 is a linear function over GF(2); appending ``len2``
zero bytes to a message multiplies its crc (as a 32-bit GF(2) vector) by
a fixed matrix to the ``len2``-th power — applied via binary matrix
squaring exactly like zlib's crc32_combine_.

adler32_combine: adler's two 16-bit sums shift by closed-form modular
arithmetic (mod 65521), matching zlib's adler32_combine_.
"""

from __future__ import annotations

import threading
from typing import Sequence, Tuple

_CRC_POLY = 0xEDB88320
_ADLER_MOD = 65521


def _gf2_matrix_times(mat: Sequence[int], vec: int) -> int:
    total = 0
    i = 0
    while vec:
        if vec & 1:
            total ^= mat[i]
        vec >>= 1
        i += 1
    return total


def _gf2_matrix_square(square: list, mat: Sequence[int]) -> None:
    for n in range(32):
        square[n] = _gf2_matrix_times(mat, mat[n])


# cache of "advance crc by 2^k zero BYTES" operators.  The matrices
# depend only on k, so they are built once and shared: rebuilding +
# re-squaring them per combine made folding 20k slab pieces cost ~8s
# (measured) — cached application is popcount(len2) matrix·vector
# products of 32 xors each.  Extension is LOCKED: crc32_combine runs on
# executor worker threads (scheduler digesting), and an unsynchronized
# check-then-append lets two threads append the same square, after
# which index k no longer holds the 2^k operator and every later
# combine is silently wrong.  Reads of already-built entries are
# lock-free (entries are immutable once published).
_SHIFT_BY_POW2_BYTES: list = []
_SHIFT_LOCK = threading.Lock()


def _shift_matrix(k: int) -> Sequence[int]:
    if len(_SHIFT_BY_POW2_BYTES) > k:
        return _SHIFT_BY_POW2_BYTES[k]
    with _SHIFT_LOCK:
        while len(_SHIFT_BY_POW2_BYTES) <= k:
            if not _SHIFT_BY_POW2_BYTES:
                odd = [0] * 32  # advance-1-bit operator
                odd[0] = _CRC_POLY
                row = 1
                for n in range(1, 32):
                    odd[n] = row
                    row <<= 1
                m = [0] * 32
                _gf2_matrix_square(m, odd)  # 2 bits
                m2 = [0] * 32
                _gf2_matrix_square(m2, m)  # 4 bits
                one_byte = [0] * 32
                _gf2_matrix_square(one_byte, m2)  # 8 bits = 1 byte
                _SHIFT_BY_POW2_BYTES.append(one_byte)
            else:
                nxt = [0] * 32
                _gf2_matrix_square(nxt, _SHIFT_BY_POW2_BYTES[-1])
                _SHIFT_BY_POW2_BYTES.append(nxt)
        return _SHIFT_BY_POW2_BYTES[k]


def crc32_combine(crc1: int, crc2: int, len2: int) -> int:
    """crc32 of A+B given crc32(A), crc32(B), len(B)."""
    if len2 <= 0:
        return crc1 & 0xFFFFFFFF
    crc1 &= 0xFFFFFFFF
    k = 0
    while len2:
        if len2 & 1:
            crc1 = _gf2_matrix_times(_shift_matrix(k), crc1)
        len2 >>= 1
        k += 1
    return (crc1 ^ crc2) & 0xFFFFFFFF


def adler32_combine(ad1: int, ad2: int, len2: int) -> int:
    """adler32 of A+B given adler32(A), adler32(B), len(B)."""
    if len2 <= 0:
        return ad1 & 0xFFFFFFFF
    rem = len2 % _ADLER_MOD
    sum1 = ad1 & 0xFFFF
    sum2 = (rem * sum1) % _ADLER_MOD
    sum1 += (ad2 & 0xFFFF) + _ADLER_MOD - 1
    sum2 += ((ad1 >> 16) & 0xFFFF) + ((ad2 >> 16) & 0xFFFF) + _ADLER_MOD - rem
    if sum1 >= _ADLER_MOD:
        sum1 -= _ADLER_MOD
    if sum1 >= _ADLER_MOD:
        sum1 -= _ADLER_MOD
    if sum2 >= (_ADLER_MOD << 1):
        sum2 -= _ADLER_MOD << 1
    if sum2 >= _ADLER_MOD:
        sum2 -= _ADLER_MOD
    return (sum1 | (sum2 << 16)) & 0xFFFFFFFF


def combine_piece_digests(
    pieces: Sequence[Tuple[int, int, int]],
) -> Tuple[int, int, int]:
    """Fold per-piece (crc32, adler32, nbytes) — in buffer order, exactly
    tiling the object — into the whole object's digest."""
    crc, adler, total = 0, 1, 0
    for pc, pa, pn in pieces:
        crc = crc32_combine(crc, pc, pn)
        adler = adler32_combine(adler, pa, pn)
        total += pn
    return crc, adler, total


def crc32_fast(data, seed: int = 0) -> int:
    """zlib-compatible crc32 preferring the native PCLMUL path (GIL
    released, ~2x system zlib); transparent zlib fallback."""
    from .. import _csrc

    c = _csrc.crc32z(data, seed)
    if c is not None:
        return c
    import zlib

    return zlib.crc32(data, seed) & 0xFFFFFFFF


def adler32_fast(data, seed: int = 1) -> int:
    """zlib-compatible adler32 preferring the native AVX2 path (GIL
    released, ~3x system zlib); transparent zlib fallback."""
    from .. import _csrc

    a = _csrc.adler32(data, seed)
    if a is not None:
        return a
    import zlib

    return zlib.adler32(data, seed) & 0xFFFFFFFF
