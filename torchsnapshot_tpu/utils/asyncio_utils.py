"""Event-loop helpers: run coroutines from sync code, including inside
Jupyter/async contexts (reference torchsnapshot/asyncio_utils.py:14-159).

Instead of vendoring nest-asyncio's re-entrant monkey patch, we run the
coroutine on a dedicated short-lived loop in a helper thread when a loop is
already running in the caller's thread — simpler, and safe with JAX (no
global loop state is mutated).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading
from typing import Any, Coroutine


def run_in_fresh_loop(coro: Coroutine) -> Any:
    """Run ``coro`` to completion and return its result, regardless of
    whether the calling thread already has a running event loop."""
    try:
        asyncio.get_running_loop()
    except RuntimeError:
        return asyncio.run(coro)
    # A loop is running (e.g. Jupyter). Run on a private loop in a thread.
    with concurrent.futures.ThreadPoolExecutor(
        max_workers=1, thread_name_prefix="tsnp-loop"
    ) as pool:
        return pool.submit(asyncio.run, coro).result()
