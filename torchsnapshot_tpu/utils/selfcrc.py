"""Self-checksum trailer shared by ``.snapshot_metadata`` and
``.snapshot_obsrecord``.

One construction, one set of subtle rules, two files: the serialized
document gets a trailing comment line carrying the crc32 of everything
before it.  The marker starts with ``\\n#`` — ``json.dumps`` escapes
newlines inside strings, so the raw byte sequence can never occur in
the JSON body, and a plain-YAML/JSON reader treats the trailer as a
comment / trailing garbage rather than data.

Read-side rules (the every-bit-flip-fails property):

- the trailer hex must be EXACTLY 8 lowercase hex digits (the writer's
  ``%08x``) — a sloppy ``int(x, 16)`` would accept case-flipped,
  ``0x``-prefixed, signed or ``_``-separated variants;
- a file whose final line is trailer-SHAPED (``#...``) but fails the
  exact-marker match is corruption inside the marker bytes, not a
  legacy trailer-less file — it must be rejected, never silently
  downgraded to an unverified parse.
"""

from __future__ import annotations

import re
import zlib
from typing import Tuple

_HEX8 = re.compile(r"[0-9a-f]{8}")


def append_crc_trailer(body: str, marker: str) -> str:
    """``body`` + the marker + the crc32 of body, ``%08x``."""
    return f"{body}{marker}{zlib.crc32(body.encode()):08x}"


def strip_crc_trailer(
    s: str, marker: str, label: str, fname: str
) -> Tuple[str, bool]:
    """Verify and remove the trailer; returns ``(body, had_trailer)``.

    Raises ``RuntimeError`` on checksum mismatch, unparseable trailer
    hex, or a trailer-shaped final line that fails the marker match;
    ``(s, False)`` for a genuinely trailer-less (legacy) document.
    ``label``/``fname`` only shape the error message (e.g.
    ``"metadata"`` / ``".snapshot_metadata"``)."""
    body, m, trailer = s.rpartition(marker)
    if m:
        t = trailer.strip()
        recorded = int(t, 16) if _HEX8.fullmatch(t) else None
        actual = zlib.crc32(body.encode())
        if recorded != actual:
            shown = (
                f"recorded {recorded:#010x}"
                if recorded is not None
                else f"unparseable trailer {t[:24]!r}"
            )
            raise RuntimeError(
                f"{label} checksum mismatch: {fname} is "
                f"corrupt ({shown}, actual {actual:#010x})"
            )
        return body, True
    last_line = s[s.rfind("\n") + 1:].strip()
    if last_line.startswith("#"):
        raise RuntimeError(
            f"{label} checksum mismatch: final line is "
            "trailer-shaped but does not match the expected "
            f"marker — corrupt {fname} trailer"
        )
    return s, False
