"""Small shared utilities.

``domain_private`` is the concurrency lint's reviewed escape hatch
(tools/lint: lockset-race / domain-crossing): a class whose instances
are confined to one execution domain at a time — built, handed through
a pipeline stage, released, never shared between concurrent flows —
may keep its fields unlocked, and the decorator records WHY in the
code next to the class it exempts.  The justification must be a real
sentence (>= 20 characters); the linter rejects token excuses, and the
runtime check below keeps the written contract from silently rotting
into ``@domain_private("")``.
"""

from __future__ import annotations

__all__ = ["domain_private"]

_MIN_JUSTIFICATION_CHARS = 20  # mirrored in tools/lint/core.py


def domain_private(justification: str):
    """Class decorator: exempt the class's fields from the multi-domain
    lockset checks, with a written justification.

    Runtime no-op by design — the contract is documentation plus static
    checking, not enforcement.  The justification lands on the class as
    ``__domain_private__`` so it is introspectable in a debugger.
    """
    if (
        not isinstance(justification, str)
        or len(justification.strip()) < _MIN_JUSTIFICATION_CHARS
    ):
        raise ValueError(
            "domain_private needs a written justification of at least "
            f"{_MIN_JUSTIFICATION_CHARS} characters saying why the "
            "class is single-domain"
        )

    def _apply(cls):
        cls.__domain_private__ = justification
        return cls

    return _apply
