"""File-like adapter over a memoryview for zero-copy uploads.

Reference: torchsnapshot/memoryview_stream.py:1-87 — cloud SDK upload APIs
want a readable stream; wrapping the staged memoryview avoids copying the
whole buffer into a bytes object first.
"""

from __future__ import annotations

import io
from typing import Optional

from . import domain_private


@domain_private(
    "a stream instance is owned by exactly one upload call at a time: "
    "the SDK that reads it never shares a cursor across threads, so "
    "_pos needs no lock"
)
class MemoryviewStream(io.RawIOBase):
    def __init__(self, view) -> None:
        self._view = memoryview(view).cast("B")
        self._pos = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def readinto(self, b) -> int:
        n = min(len(b), self._view.nbytes - self._pos)
        if n <= 0:
            return 0
        b[:n] = self._view[self._pos : self._pos + n]
        self._pos += n
        return n

    def read(self, size: int = -1) -> bytes:
        if size is None or size < 0:
            size = self._view.nbytes - self._pos
        n = min(size, self._view.nbytes - self._pos)
        out = bytes(self._view[self._pos : self._pos + n])
        self._pos += n
        return out

    def seek(self, pos: int, whence: int = io.SEEK_SET) -> int:
        if whence == io.SEEK_SET:
            self._pos = pos
        elif whence == io.SEEK_CUR:
            self._pos += pos
        elif whence == io.SEEK_END:
            self._pos = self._view.nbytes + pos
        else:
            raise ValueError(f"invalid whence {whence}")
        self._pos = max(0, min(self._pos, self._view.nbytes))
        return self._pos

    def tell(self) -> int:
        return self._pos

    def __len__(self) -> int:
        return self._view.nbytes
