"""Benchmark I/O hygiene helpers shared by benchmarks/*/main.py.

``warm_up_snapshot_runtime``: absorb the runtime's one-time costs (thread
pools, the private event loop, storage-plugin imports) with one tiny
async_take so timed phases reflect steady state.

``settle_dir``: fsync every file under a directory.  Benchmarks with two
timed phases (naive-vs-snapshot, sync-vs-async, save-then-load) need the
first phase's dirty pages flushed before timing the second, or the
kernel's writeback throttling charges phase 1's bytes to phase 2's clock.
Scoped to the benchmark's own files — a machine-wide ``os.sync()`` would
block on unrelated writers on shared hosts.
"""

from __future__ import annotations

import os
import shutil
import tempfile

import numpy as np


def warm_up_snapshot_runtime() -> None:
    from torchsnapshot_tpu import Snapshot, StateDict

    root = tempfile.mkdtemp(prefix="tsnp_warm_")
    try:
        Snapshot.async_take(
            root, {"w": StateDict(x=np.zeros(1024, np.float32))}
        ).wait()
    finally:
        shutil.rmtree(root, ignore_errors=True)


def settle_dir(path: str) -> None:
    for dirpath, _dirnames, filenames in os.walk(path):
        for name in filenames:
            try:
                fd = os.open(os.path.join(dirpath, name), os.O_RDONLY)
            except OSError:
                continue
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
