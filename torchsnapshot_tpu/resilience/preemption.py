"""Preemption-notice hook: SIGTERM → bounded drain → exit as before.

Spot/preemptible fleets deliver a termination notice (SIGTERM on GCE,
the same convention on most orchestrators) a short grace window before
the kill.  Everything this library keeps only in RAM at that moment —
the in-flight continuous-checkpoint replication of the current step
(continuous/loop.py) — is exactly one bounded flush away from being
safe on a peer host, so the hook's contract is narrow on purpose:

1. ``on_preemption(drain)`` registers a drain callback
   (``drain(deadline_monotonic) -> None``) and installs a process
   SIGTERM handler on first use (main thread only — Python refuses
   signal handlers elsewhere; registration still works from any thread
   and ``notify_preemption()`` runs the same drains without a signal,
   for tests and orchestrators that deliver notices over an API).
2. On SIGTERM, every registered drain runs under ONE shared deadline
   (``TORCHSNAPSHOT_TPU_CONTINUOUS_GRACE_S`` from now) — a drain that
   overruns forfeits the remainder, it cannot eat a sibling's window.
   Drain errors are swallowed and counted: a telemetry-grade bug in a
   drain must not turn a clean preemption into a hang.
3. The signal is then RE-DELIVERED through whatever handler was
   installed before ours (default disposition included), so the
   process still dies a normal SIGTERM death and the orchestrator's
   accounting sees exactly what it expects.

The hook never *prevents* the exit — it spends the grace window the
platform already granted finishing the one replication that turns
"lost the last N minutes" into "lost at most one step".
"""

from __future__ import annotations

import itertools
import logging
import os
import signal
import threading
import time
from typing import Callable, Dict, Optional

from .. import knobs, obs

logger = logging.getLogger(__name__)

# reentrant: the SIGTERM handler runs ON the main thread and may land
# while main-thread code (on_preemption/remove_handler/uninstall/close
# paths) already holds the lock — a plain Lock would deadlock the
# handler against its own thread
_LOCK = threading.RLock()
_DRAINS: Dict[int, Callable[[float], None]] = {}
_IDS = itertools.count(1)
_PREV_HANDLER: Optional[object] = None
_INSTALLED = False
_REQUESTED = threading.Event()


def preemption_requested() -> bool:
    """True once a preemption notice has been observed in this process
    (training loops can poll this to stop scheduling new steps)."""
    return _REQUESTED.is_set()


def on_preemption(drain: Callable[[float], None]) -> int:
    """Register ``drain(deadline)`` to run inside the SIGTERM grace
    window; returns a handle for ``remove_handler``.  Installs the
    process signal handler on first call when possible (main thread);
    otherwise registration still takes effect for
    ``notify_preemption()`` and a warning is logged."""
    global _INSTALLED, _PREV_HANDLER
    with _LOCK:
        handle = next(_IDS)
        _DRAINS[handle] = drain
        need_install = not _INSTALLED
    if need_install:
        try:
            prev = signal.signal(signal.SIGTERM, _sigterm_handler)
            with _LOCK:
                _PREV_HANDLER = prev
                _INSTALLED = True
        except ValueError as e:
            # not the main thread: the drains still run via
            # notify_preemption; say so rather than silently shrinking
            # the preemption story
            logger.warning(
                "cannot install SIGTERM preemption handler off the "
                "main thread (%r); call notify_preemption() from your "
                "own notice watcher", e,
            )
    return handle


def remove_handler(handle: int) -> None:
    with _LOCK:
        _DRAINS.pop(handle, None)


def uninstall() -> None:
    """Restore the pre-hook SIGTERM disposition and drop every
    registered drain (tests)."""
    global _INSTALLED, _PREV_HANDLER
    with _LOCK:
        prev = _PREV_HANDLER
        installed = _INSTALLED
        _DRAINS.clear()
        _PREV_HANDLER = None
        _INSTALLED = False
        _REQUESTED.clear()
    if installed:
        try:
            signal.signal(
                signal.SIGTERM,
                prev if prev is not None else signal.SIG_DFL,
            )
        except (ValueError, TypeError) as e:
            logger.warning("could not restore SIGTERM handler: %r", e)


def notify_preemption(grace_s: Optional[float] = None) -> int:
    """Run every registered drain under one shared grace deadline (the
    signal-free entry point: tests, and orchestrators that deliver
    preemption notices via an API instead of SIGTERM).  Returns the
    number of drains that completed without raising."""
    _REQUESTED.set()
    grace = (
        knobs.get_continuous_grace_s() if grace_s is None else grace_s
    )
    deadline = time.monotonic() + grace
    with _LOCK:
        drains = list(_DRAINS.values())
    completed = 0
    with obs.span(
        "resilience/preemption_drain", drains=len(drains), grace_s=grace
    ):
        for drain in drains:
            try:
                drain(deadline)
                completed += 1
            except Exception as e:  # noqa: BLE001 — a drain bug must
                # not turn a clean preemption into a hang or a crash
                # loop inside a signal handler
                obs.swallowed_exception("resilience.preemption_drain", e)
    if completed:
        obs.counter(obs.CONTINUOUS_PREEMPTION_DRAINS).inc(completed)
    return completed


def _sigterm_handler(signum, frame) -> None:
    logger.warning(
        "SIGTERM preemption notice: draining in-flight work inside a "
        "%.1fs grace window", knobs.get_continuous_grace_s(),
    )
    notify_preemption()
    # re-deliver through the pre-hook disposition so the process still
    # dies a normal SIGTERM death (orchestrator accounting intact)
    with _LOCK:
        prev = _PREV_HANDLER
    if callable(prev):
        prev(signum, frame)
        return
    if prev is signal.SIG_IGN:
        return
    try:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
    except ValueError:
        # delivered on a non-main thread (embedders): exit explicitly
        # with the conventional SIGTERM status instead
        os._exit(128 + int(signum))
    os.kill(os.getpid(), signal.SIGTERM)
