"""Coordination-level rank liveness: heartbeat stamps, dead-rank
detection, and death-aware waits.

The abort protocol (abort.py) covers ranks that FAIL — a rank hitting
an error poisons the scope and its peers raise within a poll interval.
It cannot cover ranks that DIE: a SIGKILLed / OOM-killed / hung process
never reaches its ``poison`` call, so before this module its peers
wedged in their KV waits until the full deadline and then aborted the
whole operation.  At fleet scale some host is always dying, so an
operation that requires a fault-free window never commits — liveness
turns "a rank went silent" into a typed, actionable signal
(``RankDeadError``) within ``LIVENESS_TIMEOUT_S``, early enough for the
survivors to take over the dead rank's work (snapshot.py write
takeover) instead of throwing the step away.

Mechanism — progress stamps, not clocks: each rank runs one
``LivenessSession`` per coordination-heavy operation (the take/restore
commit scope).  A publisher thread stamps ``{ns}/hb/{rank}`` with a
monotonically increasing SEQUENCE every ``LIVENESS_INTERVAL_S``; an
observer tracks, per peer, the last sequence seen and the local
monotonic time at which it last CHANGED.  A peer is dead iff its stamp
stops advancing (or never appears) for longer than
``LIVENESS_TIMEOUT_S``.  No cross-process clock is ever compared — the
coordination KV carries opaque sequence numbers, and staleness is
measured entirely on the observer's own clock, so clock skew between
hosts can never fabricate (or mask) a death.

Death-aware waits: ``Coordinator.liveness_scope`` installs a session's
monitor on the current thread (the same per-thread discipline as
``abort_scope``); every polling KV wait and two-phase barrier checks it
once per poll tick and raises ``RankDeadError`` instead of waiting out
the full deadline.

KV hygiene: ``ns`` is always a caller-supplied operation uid (the
commit uid), never a literal head, and ``stop()`` deletes this rank's
own key — a clean exit leaves no stamp behind, so an ABSENT key is
ambiguous (never published yet, or cleanly finished) while a
present-but-frozen key is the unambiguous SIGKILL signature.  Callers
that must distinguish the two (the tier promoter's done-handshake)
pass ``absent_after_s`` to treat prolonged absence as death as well.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

from .. import knobs, obs

logger = logging.getLogger(__name__)


class RankDeadError(RuntimeError):
    """A peer rank was declared dead: its liveness stamp stopped
    advancing for longer than ``LIVENESS_TIMEOUT_S``.  Carries the
    first dead rank observed (``rank``) and every rank dead at raise
    time (``dead_ranks``) so the takeover path can plan against the
    full set without re-probing."""

    def __init__(self, rank: int, dead_ranks: Optional[Iterable[int]] = None,
                 ns: str = "") -> None:
        self.rank = int(rank)
        self.dead_ranks = sorted(
            set(dead_ranks) if dead_ranks is not None else {self.rank}
        )
        self.ns = ns
        super().__init__(
            f"rank {self.rank} declared dead (no liveness progress under "
            f"{ns or '?'} for > {knobs.get_liveness_timeout_s():g}s; dead "
            f"set {self.dead_ranks})"
        )


class DegradedSnapshotError(RuntimeError):
    """A restore touched logical paths the snapshot's ``degraded``
    manifest section declares missing (a rank died mid-take and its
    exclusively-held state could not be taken over).  Restore the
    intact paths with ``restore(paths=...)``, or heal the snapshot
    first (``SnapshotManager.repair()``)."""

    def __init__(self, path: str, degraded_paths: Iterable[str]) -> None:
        self.path = path
        self.degraded_paths = sorted(degraded_paths)
        shown = self.degraded_paths[:5]
        more = len(self.degraded_paths) - len(shown)
        super().__init__(
            f"snapshot {path!r} is degraded: {len(self.degraded_paths)} "
            f"logical path(s) were lost to a dead rank and not healed — "
            f"{shown}{f' (+{more} more)' if more > 0 else ''}. Restore "
            f"intact paths with restore(paths=...), or run "
            f"SnapshotManager.repair() to heal from continuous peer stores."
        )


class _PeerState:
    __slots__ = ("seq", "changed_at")

    def __init__(self, seq: Optional[int], now: float) -> None:
        self.seq = seq
        self.changed_at = now


class LivenessMonitor:
    """Observer half: samples every OTHER rank's ``{ns}/hb/{r}`` stamp
    (at most once per ``LIVENESS_INTERVAL_S`` — ``check()`` is called
    from hot poll loops) and declares a peer dead when its stamp is
    present but frozen for > ``LIVENESS_TIMEOUT_S``.

    ``absent_after_s``: when set, a peer whose stamp NEVER appeared
    within that many seconds of monitor start is also declared dead —
    for handshakes where every live peer is known to start stamping
    promptly (tier promoter).  Default off, because an absent key is
    ambiguous (a cleanly-finished rank deletes its own stamp)."""

    def __init__(
        self,
        coordinator: Any,
        ns: str,
        absent_after_s: Optional[float] = None,
    ) -> None:
        self._coordinator = coordinator
        self._ns = ns
        self._absent_after_s = absent_after_s
        self._lock = threading.Lock()
        self._started_at = time.monotonic()
        self._last_sample = 0.0
        self._peers: Dict[int, _PeerState] = {}
        self._declared: set = set()

    @property
    def ns(self) -> str:
        return self._ns

    def _sample_locked(self, now: float) -> None:
        interval = knobs.get_liveness_interval_s()
        if now - self._last_sample < interval:
            return
        self._last_sample = now
        coord = self._coordinator
        for r in range(coord.world_size):
            if r == coord.rank:
                continue
            try:
                raw = coord.kv_try_get(f"{self._ns}/hb/{r}")
            except Exception as e:  # noqa: BLE001 — a flaky probe must
                # not fabricate a death; skip this tick
                obs.swallowed_exception("liveness.sample", e)
                continue
            seq: Optional[int]
            try:
                seq = int(raw) if raw is not None else None
            except ValueError:
                seq = None
            st = self._peers.get(r)
            if st is None:
                self._peers[r] = _PeerState(seq, now)
            elif seq != st.seq:
                st.seq = seq
                st.changed_at = now

    def dead_ranks(self) -> List[int]:
        """Every peer currently considered dead (see class docstring
        for the rule).  Samples lazily; pure-local otherwise."""
        now = time.monotonic()
        timeout = knobs.get_liveness_timeout_s()
        out: List[int] = []
        with self._lock:
            self._sample_locked(now)
            for r, st in self._peers.items():
                if st.seq is None:
                    # never appeared (or already cleaned up): dead only
                    # under the opt-in absence rule
                    if (
                        self._absent_after_s is not None
                        and now - self._started_at > self._absent_after_s
                    ):
                        out.append(r)
                elif now - st.changed_at > timeout:
                    out.append(r)
            newly = [r for r in out if r not in self._declared]
            if newly:
                self._declared.update(newly)
                obs.counter(obs.LIVENESS_DEAD_RANKS).inc(len(newly))
                logger.warning(
                    "liveness: rank(s) %s declared dead under %r "
                    "(stamp frozen > %gs)", newly, self._ns, timeout,
                )
        return sorted(out)

    def check(self) -> None:
        """Raise ``RankDeadError`` if any peer is dead — the one call
        the coordinator's poll loops make per tick."""
        dead = self.dead_ranks()
        if dead:
            raise RankDeadError(dead[0], dead, ns=self._ns)


class LivenessSession:
    """Publisher + monitor for one operation scope: starts a daemon
    thread stamping ``{ns}/hb/{rank}`` with an advancing sequence every
    ``LIVENESS_INTERVAL_S``; ``stop()`` joins the thread and deletes
    this rank's stamp (clean exit leaves no key).  Use as a context
    manager; the monitor is exposed for ``Coordinator.liveness_scope``.
    """

    def __init__(
        self,
        coordinator: Any,
        ns: str,
        absent_after_s: Optional[float] = None,
    ) -> None:
        self._coordinator = coordinator
        self._ns = ns
        self.monitor = LivenessMonitor(
            coordinator, ns, absent_after_s=absent_after_s
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _publish_loop(self) -> None:
        coord = self._coordinator
        key = f"{self._ns}/hb/{coord.rank}"
        seq = 0
        while not self._stop.is_set():
            try:
                coord.kv_set(key, str(seq))
                obs.counter(obs.LIVENESS_HEARTBEATS).inc()
            except Exception as e:  # noqa: BLE001 — heartbeat is
                # best-effort: a flaky KV must not crash the publisher
                # (peers see a frozen stamp only if EVERY retry fails
                # for the full timeout, which is a real outage)
                obs.swallowed_exception("liveness.publish", e)
            seq += 1
            self._stop.wait(knobs.get_liveness_interval_s())

    def start(self) -> "LivenessSession":
        if self._thread is None and self._coordinator.world_size > 1:
            self._thread = threading.Thread(
                target=self._publish_loop,
                name=f"tsnp-liveness-{self._coordinator.rank}",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        """Clean shutdown: stop stamping and DELETE this rank's key, so
        peers see absence (ambiguous, not dead) rather than an
        eternally-frozen stamp after this operation ends."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self._coordinator.kv_try_delete(
                f"{self._ns}/hb/{self._coordinator.rank}"
            )
        except Exception as e:  # noqa: BLE001 — cleanup is best-effort
            obs.swallowed_exception("liveness.clear", e)

    def __enter__(self) -> "LivenessSession":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
