"""Per-backend circuit breaker: fail fast when a backend is down.

Classic three-state breaker fed by the retry engine (retry.py): only
COMPLETED failures count (an op whose retries were exhausted, or a
fatal classification) — an op that recovered on retry is a success.

- **closed** — normal operation; consecutive failures are counted.
- **open** — ``threshold`` consecutive failures tripped it: ``check()``
  raises ``CircuitOpenError`` immediately (writes fail fast instead of
  burning a full retry window each; tiered reads route straight to the
  replica/durable fallback) until the cooldown elapses.
- **half-open** — after the cooldown one probe op is allowed through;
  its success closes the breaker, its failure re-opens (fresh cooldown).

Knobs: ``TORCHSNAPSHOT_TPU_BREAKER_THRESHOLD`` (consecutive failures),
``BREAKER_COOLDOWN_S``.  State is exported as the gauge
``resilience.breaker_state.<name>`` (0 closed, 1 half-open, 2 open) and
trips count ``resilience.breaker_trips``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from .. import knobs, obs

logger = logging.getLogger(__name__)

CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"

_STATE_GAUGE_VALUES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitOpenError(OSError):
    """The backend's breaker is open: failing fast instead of issuing
    an op that would burn a full retry window.  An OSError so existing
    per-backend error handling (fallbacks, fatal classification) treats
    it as the I/O failure it stands in for."""

    def __init__(self, name: str, op_name: str, retry_in_s: float) -> None:
        super().__init__(
            f"circuit breaker for {name!r} is open ({op_name}): backend "
            f"failing consecutively; next probe allowed in "
            f"{max(0.0, retry_in_s):.1f}s"
        )
        self.breaker_name = name


class CircuitBreaker:
    def __init__(
        self,
        name: str,
        threshold: Optional[int] = None,
        cooldown_s: Optional[float] = None,
    ) -> None:
        self.name = name
        self._threshold = threshold
        self._cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._state = CLOSED
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._gauge = obs.gauge(f"resilience.breaker_state.{name}")
        self._gauge.set(0)

    # knob-resolved per use so test overrides take effect mid-life
    @property
    def threshold(self) -> int:
        return (
            knobs.get_breaker_threshold() if self._threshold is None
            else self._threshold
        )

    @property
    def cooldown_s(self) -> float:
        return (
            knobs.get_breaker_cooldown_s() if self._cooldown_s is None
            else self._cooldown_s
        )

    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state()

    def _effective_state(self) -> str:
        # lock held.  An open breaker whose cooldown elapsed presents as
        # half-open (the next allow() admits one probe).
        if self._state == OPEN and (
            time.monotonic() - self._opened_at >= self.cooldown_s
        ):
            self._state = HALF_OPEN
            self._probe_in_flight = False
            self._gauge.set(_STATE_GAUGE_VALUES[HALF_OPEN])
        return self._state

    def allow(self) -> bool:
        """True when an op may be issued now.  In half-open, exactly one
        probe is admitted until its outcome is recorded."""
        with self._lock:
            state = self._effective_state()
            if state == CLOSED:
                return True
            if state == OPEN:
                return False
            if self._probe_in_flight:
                return False
            self._probe_in_flight = True
            return True

    def check(self, op_name: str = "") -> None:
        """allow() or raise CircuitOpenError (the retry engine's entry
        gate)."""
        if not self.allow():
            with self._lock:
                retry_in = self.cooldown_s - (
                    time.monotonic() - self._opened_at
                )
            raise CircuitOpenError(self.name, op_name, retry_in)

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            if self._state != CLOSED:
                logger.info(
                    "circuit breaker %r closed (probe succeeded)", self.name
                )
            self._state = CLOSED
            self._gauge.set(_STATE_GAUGE_VALUES[CLOSED])

    def release_probe(self) -> None:
        """The op's outcome said nothing about backend health (e.g. a
        genuine not-found): release the half-open probe slot without
        recording success or failure, so the breaker can't wedge
        half-open waiting for an outcome that never arrives."""
        with self._lock:
            self._probe_in_flight = False

    def record_failure(self) -> None:
        with self._lock:
            self._probe_in_flight = False
            self._consecutive_failures += 1
            failures = self._consecutive_failures
            tripped = (
                self._state == HALF_OPEN
                or (
                    self._state == CLOSED
                    and failures >= self.threshold
                )
            )
            if tripped:
                self._state = OPEN
                self._opened_at = time.monotonic()
                self._gauge.set(_STATE_GAUGE_VALUES[OPEN])
        if tripped:
            obs.counter(obs.RESILIENCE_BREAKER_TRIPS).inc()
            logger.warning(
                "circuit breaker %r tripped open after %d consecutive "
                "failure(s); failing fast for %.1fs",
                self.name, failures, self.cooldown_s,
            )

    def reset(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._state = CLOSED
            self._probe_in_flight = False
            self._gauge.set(0)


_REGISTRY: Dict[str, CircuitBreaker] = {}
_REGISTRY_LOCK = threading.Lock()


def get_breaker(name: str) -> CircuitBreaker:
    """Process-global breaker per backend name, get-or-create."""
    with _REGISTRY_LOCK:
        b = _REGISTRY.get(name)
        if b is None:
            b = _REGISTRY[name] = CircuitBreaker(name)
        return b


def reset_breakers() -> None:
    """Close every registered breaker (tests)."""
    with _REGISTRY_LOCK:
        breakers = list(_REGISTRY.values())
    for b in breakers:
        b.reset()
