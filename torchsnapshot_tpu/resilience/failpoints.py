"""Deterministic, seedable fault injection: the ``failpoint`` registry.

Instrumented sites across the package call ``failpoint("site.name")``;
when armed, a matching spec raises the configured exception there.  The
disarmed path is one module-global ``None`` check — the same
zero-cost-when-off discipline as the obs tracer — so production code
keeps the calls unconditionally.

Spec grammar (the ``TORCHSNAPSHOT_TPU_FAILPOINTS`` knob, or
``knobs.override_failpoints`` in tests)::

    site=error[:probability[:count]][,site=error...]

- **site** — an instrumented site name, or an ``fnmatch`` glob over
  them (``storage.s3.*``).  Sites are listed in docs/resilience.md.
- **error** — one of the registered kinds below (``eintr``, ``enospc``,
  ``conn``, ``slowdown``, ...).
- **probability** — per-evaluation fire chance in (0, 1]; default 1.
- **count** — maximum number of fires before the spec disarms itself;
  default unlimited.

Determinism: every spec draws from its own ``random.Random`` seeded
from ``TORCHSNAPSHOT_TPU_FAILPOINT_SEED`` and the spec text, so a
probabilistic schedule replays identically regardless of how OTHER
sites interleave across threads (per-spec streams never share draws).
Fire counts are lock-guarded — concurrent evaluations can never
over-fire a bounded spec.

Instrumented sites (kept in sync with docs/resilience.md):
``storage.{fs,s3,gcs,memory}.{write,read}``, ``storage.fs.write.sync``,
``scheduler.{stage,write,read}``, ``coord.{kv_set,kv_get,barrier}``,
``tier.promote.{data,commit}``, ``obs.publish``,
``continuous.replicate``.

Beyond the raising kinds, ``delay<ms>`` (e.g. ``delay250``) SLEEPS at
the site instead of raising — deterministic injected slowness for
straggler-attribution tests, where the flight record must name the
delayed rank and phase without any failure in the run.

``hang`` blocks FOREVER at the site (until ``release_hangs()``) — the
in-process stand-in for a SIGKILLed/wedged rank: the hung thread never
raises, never poisons, and its peers only escape via the liveness
layer's stale-heartbeat detection (resilience/liveness.py).  Dead-rank
scenarios become injectable without real process kills.
"""

from __future__ import annotations

import dataclasses
import errno as _errno
import fnmatch
import logging
import random
import re as _re
import threading
import zlib
from typing import List, Optional

from .. import knobs, obs

logger = logging.getLogger(__name__)

_LOCK = threading.Lock()
# None == disarmed (the zero-cost check in failpoint()); a list of
# _Armed specs otherwise.
_ARMED: Optional[List["_Armed"]] = None


class InjectedClientError(Exception):
    """A botocore ClientError-shaped injected failure: carries
    ``response["Error"]["Code"]`` (and an HTTP status) so the storage
    plugins' real classification logic runs against it unchanged."""

    def __init__(self, code: str, status: int, site: str) -> None:
        super().__init__(f"injected {code} at {site}")
        self.response = {
            "Error": {"Code": code},
            "ResponseMetadata": {"HTTPStatusCode": status},
        }


def _oserror(code: int, site: str) -> OSError:
    # OSError(errno, ...) resolves to the right subclass (ENOENT ->
    # FileNotFoundError), matching what real syscalls raise
    return OSError(code, f"injected {_errno.errorcode.get(code, code)}", site)


# error kind -> factory(site) -> BaseException
_ERROR_KINDS = {
    "io": lambda s: _oserror(_errno.EIO, s),
    "enospc": lambda s: _oserror(_errno.ENOSPC, s),
    "eintr": lambda s: _oserror(_errno.EINTR, s),
    "eagain": lambda s: _oserror(_errno.EAGAIN, s),
    "fnf": lambda s: _oserror(_errno.ENOENT, s),
    "conn": lambda s: ConnectionError(f"injected connection error at {s}"),
    "timeout": lambda s: TimeoutError(f"injected timeout at {s}"),
    "slowdown": lambda s: InjectedClientError("SlowDown", 503, s),
    "http500": lambda s: InjectedClientError("InternalError", 500, s),
    "runtime": lambda s: RuntimeError(f"injected failure at {s}"),
}

# delay<ms>: sleep instead of raise (injected slowness, not failure).
# Compiled eagerly: failpoint() fires from every execution domain, and
# a lazy compile-on-first-use is a check-then-act on a module global.
_DELAY_RE = _re.compile(r"delay(\d+)$")


def _delay_ms(kind: str):
    """Milliseconds for a ``delay<ms>`` kind, or None for raising kinds."""
    m = _DELAY_RE.fullmatch(kind)
    return int(m.group(1)) if m else None


# hang: the thread parks on this event at the site — a simulated dead
# rank.  release_hangs() frees every parked thread (test teardown).
_HANG_RELEASE = threading.Event()


def release_hangs() -> None:
    """Release every thread currently parked at a ``hang`` failpoint
    (and any that reach one before the armed set is next refreshed) —
    call from test/bench teardown so simulated-dead threads can be
    joined instead of leaking."""
    global _HANG_RELEASE
    # re-arm FIRST so a thread racing into failpoint() parks on the new
    # event only if it reads it after this swap; then free the parked
    # set.  The swap shares _LOCK with the parking read, so a parker
    # observes either the old event (whose set() below frees it) or the
    # re-armed one — never a torn intermediate.
    with _LOCK:
        old = _HANG_RELEASE
        _HANG_RELEASE = threading.Event()
    old.set()


@dataclasses.dataclass
class _Armed:
    pattern: str
    kind: str
    probability: float
    remaining: Optional[int]  # None == unlimited
    rng: random.Random

    def matches(self, site: str) -> bool:
        return site == self.pattern or fnmatch.fnmatchcase(
            site, self.pattern
        )


def parse_failpoints(spec: str, seed: int = 0) -> List[_Armed]:
    """Parse a spec string into armed failpoints; raises ``ValueError``
    on malformed specs (the override path surfaces typos loudly)."""
    armed: List[_Armed] = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        if "=" not in raw:
            raise ValueError(
                f"failpoint spec {raw!r} is not site=error[:prob[:count]]"
            )
        site, _, rhs = raw.partition("=")
        parts = rhs.split(":")
        kind = parts[0].strip().lower()
        if (
            kind not in _ERROR_KINDS
            and kind != "hang"
            and _delay_ms(kind) is None
        ):
            raise ValueError(
                f"failpoint spec {raw!r}: unknown error kind {kind!r} "
                f"(known: {sorted(_ERROR_KINDS)}, hang, or delay<ms>)"
            )
        probability = 1.0
        if len(parts) > 1 and parts[1].strip():
            probability = float(parts[1])
            if not 0.0 < probability <= 1.0:
                raise ValueError(
                    f"failpoint spec {raw!r}: probability must be in "
                    f"(0, 1], got {probability}"
                )
        remaining: Optional[int] = None
        if len(parts) > 2 and parts[2].strip() not in ("", "*"):
            remaining = int(parts[2])
            if remaining < 0:
                raise ValueError(
                    f"failpoint spec {raw!r}: count must be >= 0"
                )
        if len(parts) > 3:
            raise ValueError(f"failpoint spec {raw!r}: too many fields")
        armed.append(
            _Armed(
                pattern=site.strip(),
                kind=kind,
                probability=probability,
                remaining=remaining,
                # per-spec private stream: deterministic under any
                # cross-site/thread interleaving, and never touches the
                # global random state the take-path RNG invariant guards
                rng=random.Random(seed ^ zlib.crc32(raw.encode())),
            )
        )
    return armed


def refresh_from_knobs(strict: bool = False) -> None:
    """Re-resolve the FAILPOINTS knob into the armed set.  ``strict``
    (the override path) raises on malformed specs; the import-time call
    logs and stays disarmed instead — a typo'd env var must not break
    ``import torchsnapshot_tpu``."""
    global _ARMED
    spec = knobs.get_failpoints()
    if not spec:
        _ARMED = None
        return
    try:
        armed = parse_failpoints(spec, seed=knobs.get_failpoint_seed())
    except ValueError:
        if strict:
            raise
        logger.warning(
            "ignoring malformed TORCHSNAPSHOT_TPU_FAILPOINTS=%r",
            spec, exc_info=True,
        )
        _ARMED = None
        return
    _ARMED = armed or None


def active() -> bool:
    return _ARMED is not None


def failpoint(site: str, **attrs) -> None:
    """Evaluate the armed specs at ``site``; raises the configured
    exception when one fires.  One global ``None`` check when disarmed."""
    armed = _ARMED
    if armed is None:
        return
    for fp in armed:
        if not fp.matches(site):
            continue
        with _LOCK:
            if fp.remaining == 0:
                continue
            if fp.probability < 1.0 and fp.rng.random() >= fp.probability:
                continue
            if fp.remaining is not None:
                fp.remaining -= 1
        obs.counter(obs.RESILIENCE_FAILPOINTS_FIRED).inc()
        if fp.kind == "hang":
            # simulated dead rank: park until release_hangs().  Snapshot
            # the event BEFORE logging (under the lock release_hangs
            # swaps it beneath) so a concurrent swap can't strand us on
            # the re-armed event forever.
            with _LOCK:
                ev = _HANG_RELEASE
            logger.info(
                "failpoint %s hanging at %s (%s) until release_hangs()",
                fp.pattern, site, attrs,
            )
            ev.wait()
            continue
        ms = _delay_ms(fp.kind)
        if ms is not None:
            # injected slowness: sleep and keep evaluating the remaining
            # specs — the site proceeds normally, just late
            logger.info(
                "failpoint %s delayed %s by %dms (%s)",
                fp.pattern, site, ms, attrs,
            )
            import time

            time.sleep(ms / 1000.0)
            continue
        exc = _ERROR_KINDS[fp.kind](site)
        logger.info(
            "failpoint %s fired at %s (%s): %r", fp.pattern, site, attrs, exc
        )
        raise exc


def fired_count() -> int:
    """Total fires since process start (the obs counter's value)."""
    return obs.counter(obs.RESILIENCE_FAILPOINTS_FIRED).value


# arm from the environment at import, mirroring the tracer's ENABLED
# resolution: the knob is read once here and by override_failpoints
refresh_from_knobs(strict=False)
