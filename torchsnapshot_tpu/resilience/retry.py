"""Unified retry/backoff engine for storage and KV transients.

Extracted from the GCS plugin's collective-progress retry (previously
``storage/gcs.py _CollectiveProgressRetry``) and generalized so every
backend shares one policy:

- **SharedProgress** — the shared-deadline window: all concurrent ops
  on a plugin share one clock that is refreshed whenever *any* op
  completes, so an op only gives up when the whole pipeline has made no
  progress for the window.  Transient per-connection stalls can't fail
  a 30-minute snapshot, while a genuinely dead backend still fails
  within one window.
- **retry_call** — the retry loop: run the op, classify failures
  (transient / missing / fatal), back off exponentially with
  deterministic jitter on transients, respect the shared window and
  per-op attempt cap, and feed the per-backend circuit breaker.

Classification verdicts (returned by a backend's ``classify(e)``):

- ``"transient"``  — retry with backoff (throttle, 5xx, connection
  reset, EINTR/EAGAIN).
- ``"missing"``    — raise ``FileNotFoundError`` chaining the original
  (the cross-plugin cold-start contract).
- ``"fatal"``      — re-raise the original; counts as a breaker failure.
- ``"raise"``      — re-raise the original; NOT a breaker failure
  (deterministic non-backend outcomes, e.g. a 416 on a zero-byte read).
- ``"success_none"`` — swallow and return None (e.g. idempotent
  delete of a missing object).

Policy knobs: ``TORCHSNAPSHOT_TPU_RETRY_MAX_ATTEMPTS``,
``RETRY_PROGRESS_WINDOW_S``, ``RETRY_BACKOFF_CAP_S``.  Hand-rolled
sleep-backoff loops around storage/KV ops elsewhere in the package are
rejected by the snaplint ``retry-discipline`` pass — this module is the
one sanctioned home for them.
"""

from __future__ import annotations

import asyncio
import errno as _errno
import logging
import random
import time
import zlib
from typing import Any, Callable, Optional

from .. import knobs, obs

logger = logging.getLogger(__name__)

TRANSIENT = "transient"
MISSING = "missing"
FATAL = "fatal"
RAISE = "raise"
SUCCESS_NONE = "success_none"

_VERDICTS = frozenset((TRANSIENT, MISSING, FATAL, RAISE, SUCCESS_NONE))


class SharedProgress:
    """Shared-deadline retry window (the reference _RetryStrategy,
    gcs.py:221-277, by way of the GCS plugin's _CollectiveProgressRetry):
    any completion anywhere refreshes the clock."""

    def __init__(
        self,
        window_s: Optional[float] = None,
        max_attempts: Optional[int] = None,
        label: str = "",
    ) -> None:
        self.window_s = (
            knobs.get_retry_progress_window_s() if window_s is None
            else window_s
        )
        self.max_attempts = (
            knobs.get_retry_max_attempts() if max_attempts is None
            else max_attempts
        )
        self.last_progress = time.monotonic()
        # private, deterministically seeded stream: backoff jitter
        # (possibly on the async-commit background thread) must never
        # perturb the global random state the take-path RNG invariant
        # protects, and the same label replays the same jitter sequence
        self._rng = random.Random(0x5EED ^ zlib.crc32(label.encode()))

    def record_progress(self) -> None:
        self.last_progress = time.monotonic()

    def should_retry(
        self, attempt: int, started: Optional[float] = None
    ) -> bool:
        """``started``: when the CURRENT op began — the window must
        never count idle time from before the op existed.  A
        SharedProgress can sit idle arbitrarily long between operations
        (a process-global one like the codec's encodes; a plugin that
        last saw traffic minutes ago), and without the floor the first
        transient after such a gap would read as "no progress for the
        whole window" and surface un-retried."""
        if attempt >= self.max_attempts:
            return False
        anchor = self.last_progress
        if started is not None and started > anchor:
            anchor = started
        return (time.monotonic() - anchor) < self.window_s

    def backoff_delay(self, attempt: int) -> float:
        cap = knobs.get_retry_backoff_cap_s()
        return min(2**attempt, cap) * (0.5 + self._rng.random())

    async def backoff(self, attempt: int) -> None:
        delay = self.backoff_delay(attempt)
        obs.histogram(obs.RESILIENCE_BACKOFF_DELAY_S).observe(delay)
        await asyncio.sleep(delay)


def lazy_shared_progress(obj: Any, label: str) -> SharedProgress:
    """Get-or-create ``obj._progress`` (one SharedProgress per plugin
    instance).  Via ``__dict__`` on purpose: contract-test doubles build
    plugins with ``__new__`` + attribute assignment and must work
    without running ``__init__``."""
    p = obj.__dict__.get("_progress")
    if p is None:
        p = obj.__dict__["_progress"] = SharedProgress(label=label)
    return p


async def retry_call(
    fn: Callable[[], Any],
    *,
    op_name: str,
    backend: str,
    classify: Callable[[BaseException], str],
    progress: SharedProgress,
    executor: Any = None,
    breaker: Any = None,
) -> Any:
    """Run ``fn`` under the shared retry policy.  ``fn`` is a plain
    callable executed on ``executor`` when one is given (the storage
    plugins' thread-pool pattern) or awaited directly when it returns a
    coroutine.  ``breaker``: an optional CircuitBreaker consulted before
    the first attempt (open -> fail fast) and fed the op's final
    outcome."""
    if breaker is not None:
        breaker.check(op_name)
    try:
        return await _retry_loop(
            fn, op_name, backend, classify, progress, executor, breaker
        )
    except BaseException:
        # whatever escapes (classified fatals already recorded; but also
        # cancellation/KeyboardInterrupt, which the loop never
        # classifies) must not leave a half-open probe slot claimed —
        # releasing after record_success/record_failure is a no-op
        if breaker is not None:
            breaker.release_probe()
        raise


async def _retry_loop(
    fn, op_name, backend, classify, progress, executor, breaker
) -> Any:
    loop = asyncio.get_running_loop() if executor is not None else None
    attempt = 0
    # floor for the progress window: idle time BEFORE this op began is
    # not this op's stall (see SharedProgress.should_retry)
    started = time.monotonic()
    # the most recent backoff span: the retry sequence's FINAL verdict
    # (success / fatal / exhausted) is stamped onto it when the loop
    # resolves, so a trace shows how each backoff chain ended without
    # correlating spans by hand (the Span object stays referenced by
    # the tracer, so post-close attr stamps reach the export)
    last_backoff_span = None

    def _stamp_final(verdict: str) -> None:
        if last_backoff_span is not None:
            last_backoff_span.attrs["final_verdict"] = verdict

    while True:
        try:
            if executor is not None:
                result = await loop.run_in_executor(executor, fn)
            else:
                result = fn()
                if asyncio.iscoroutine(result):
                    result = await result
            progress.record_progress()
            if breaker is not None:
                breaker.record_success()
            _stamp_final("success")
            return result
        except FileNotFoundError:
            # missing is an answer, not a backend failure (but a
            # half-open probe slot must not stay claimed)
            if breaker is not None:
                breaker.release_probe()
            raise
        # Exception, NOT BaseException: cancellation, KeyboardInterrupt
        # and SystemExit must propagate immediately — classifying them
        # would retry through a cancellation (wedging wait_for past its
        # timeout) or count healthy-backend teardown as breaker failures
        except Exception as e:  # noqa: BLE001 — classified below
            verdict = classify(e)
            if verdict not in _VERDICTS:
                raise AssertionError(
                    f"classifier for {backend} returned {verdict!r}"
                ) from e
            if verdict == MISSING:
                if breaker is not None:
                    breaker.release_probe()
                raise FileNotFoundError(f"{op_name}: {e}") from e
            if verdict == SUCCESS_NONE:
                progress.record_progress()
                if breaker is not None:
                    breaker.record_success()
                return None
            if verdict == RAISE:
                if breaker is not None:
                    breaker.release_probe()
                raise
            if verdict == FATAL:
                if breaker is not None:
                    breaker.record_failure()
                _stamp_final("fatal")
                raise
            attempt += 1
            obs.counter(obs.RESILIENCE_RETRIES).inc()
            obs.counter(f"resilience.{backend}.retries").inc()
            if not progress.should_retry(attempt, started=started):
                if breaker is not None:
                    breaker.record_failure()
                _stamp_final("exhausted")
                raise
            logger.warning(
                "%s %s failed (attempt %d, retrying): %r",
                backend, op_name, attempt, e,
            )
            # attempt + triggering verdict ride the span so a trace can
            # reconstruct each backoff chain without log correlation
            with obs.span(
                "resilience/backoff",
                backend=backend, op=op_name, attempt=attempt,
                verdict=verdict,
            ) as sp:
                if sp is not None:
                    last_backoff_span = sp
                await progress.backoff(attempt)


# ------------------------------------------------------- classifiers


_FS_TRANSIENT_ERRNOS = frozenset((_errno.EINTR, _errno.EAGAIN))


def classify_fs(e: BaseException) -> str:
    """Local filesystem: EINTR/EAGAIN are the retriable transients; a
    missing file already surfaces as FileNotFoundError (passed through
    by the engine) and anything else (ENOSPC, EIO, ...) is fatal."""
    if isinstance(e, OSError) and e.errno in _FS_TRANSIENT_ERRNOS:
        return TRANSIENT
    return FATAL


def _client_error_code(e: BaseException) -> str:
    return str(getattr(e, "response", {}).get("Error", {}).get("Code", ""))


def _http_status(e: BaseException) -> Optional[int]:
    status = (
        getattr(e, "response", {})
        .get("ResponseMetadata", {})
        .get("HTTPStatusCode")
    )
    return status if isinstance(status, int) else None


# NoSuchUpload: the multipart-upload twin of NoSuchKey — an abort/part
# op against an upload id that no longer exists (already aborted or
# completed); maps to MISSING so abort-on-cleanup stays idempotent
_S3_MISSING_CODES = frozenset(("NoSuchKey", "NoSuchUpload", "404"))
_S3_TRANSIENT_CODES = frozenset(
    (
        "SlowDown",
        "Throttling",
        "ThrottlingException",
        "RequestTimeout",
        "RequestLimitExceeded",
        "ServiceUnavailable",
        "InternalError",
        "500",
        "502",
        "503",
        "504",
    )
)


def classify_s3(e: BaseException) -> str:
    """S3: explicit transient vs. missing vs. fatal — a transient 500
    must retry (and, exhausted, surface as ITSELF), never masquerade as
    some other failure with the original context lost."""
    code = _client_error_code(e)
    name = type(e).__name__
    if code in _S3_MISSING_CODES or name == "NoSuchKey":
        return MISSING
    if code in _S3_TRANSIENT_CODES or name == "SlowDown":
        return TRANSIENT
    if isinstance(e, (ConnectionError, TimeoutError)):
        return TRANSIENT
    # botocore's connection-layer errors don't subclass the builtins
    # (EndpointConnectionError, ConnectTimeoutError, ReadTimeoutError,
    # IncompleteReadError ...)
    if "ConnectionError" in name or "Timeout" in name:
        return TRANSIENT
    status = _http_status(e)
    if status is not None and status >= 500:
        return TRANSIENT
    return FATAL


def classify_generic(e: BaseException) -> str:
    """Backends with no richer signal (memory://, third-party plugins):
    connection/timeout shapes and EINTR/EAGAIN retry, the rest is
    fatal."""
    if isinstance(e, (ConnectionError, TimeoutError)):
        return TRANSIENT
    return classify_fs(e)
