"""Resilience layer: failure injection, unified retry, cross-rank
abort, and per-backend circuit breaking.

Four cooperating pieces (docs/resilience.md):

- **failpoints** — a deterministic, seedable fault-injection registry
  threaded through the storage plugins, the scheduler's pipelines, the
  coordinator KV/barrier ops and the tier promoter; armed via the
  ``TORCHSNAPSHOT_TPU_FAILPOINTS`` knob or
  ``knobs.override_failpoints``, zero-cost when off.
- **retry** — one shared retry/backoff policy (shared-progress
  deadline, exponential backoff with deterministic jitter, per-op
  attempt caps) with per-backend transient classifiers; extracted from
  the GCS plugin and now also carrying S3, fs and memory transients.
- **abort** — the KV poison protocol: a rank hitting an unrecoverable
  error broadcasts an abort, abort-aware barriers/kv waits raise a
  typed ``SnapshotAbortedError`` on every rank within seconds, and the
  durable commit point is never written after poison.
- **breaker** — per-backend consecutive-failure circuit breakers:
  tripped writes fail fast (``CircuitOpenError``), tiered reads route
  to the replica/durable fallback, half-open probes re-close.
- **preemption** — the SIGTERM preemption-notice hook: registered
  drains (the continuous checkpoint loop's in-flight replication)
  finish inside a bounded grace window before the signal is
  re-delivered and the process exits as before.
- **liveness** — op-scoped rank heartbeats and dead-rank detection:
  a SIGKILLed/hung peer (which can never reach its ``poison`` call)
  surfaces as a typed ``RankDeadError`` within ``LIVENESS_TIMEOUT_S``
  via death-aware coordinator waits, enabling the take path's write
  takeover and degraded commits instead of abort-the-world.

Everything emits obs metrics (``resilience.retries``,
``resilience.aborts``, ``resilience.failpoints_fired``,
``resilience.breaker_trips``, per-backend breaker-state gauges and a
backoff-delay histogram) and rides the existing span tracer.
"""

from __future__ import annotations

from .abort import (  # noqa: F401
    AbortInfo,
    SnapshotAbortedError,
    decode_poison,
    encode_poison,
    poison_key,
)
from .breaker import (  # noqa: F401
    CircuitBreaker,
    CircuitOpenError,
    get_breaker,
    reset_breakers,
)
from .failpoints import (  # noqa: F401
    InjectedClientError,
    failpoint,
    parse_failpoints,
    refresh_from_knobs as refresh_failpoints,
    release_hangs,
)
from .liveness import (  # noqa: F401
    DegradedSnapshotError,
    LivenessMonitor,
    LivenessSession,
    RankDeadError,
)
from .preemption import (  # noqa: F401
    notify_preemption,
    on_preemption,
    preemption_requested,
    remove_handler as remove_preemption_handler,
)
from .retry import (  # noqa: F401
    FATAL,
    MISSING,
    RAISE,
    SUCCESS_NONE,
    TRANSIENT,
    SharedProgress,
    classify_fs,
    classify_generic,
    classify_s3,
    retry_call,
)

__all__ = [
    "AbortInfo",
    "SnapshotAbortedError",
    "poison_key",
    "encode_poison",
    "decode_poison",
    "CircuitBreaker",
    "CircuitOpenError",
    "get_breaker",
    "reset_breakers",
    "InjectedClientError",
    "on_preemption",
    "notify_preemption",
    "preemption_requested",
    "remove_preemption_handler",
    "failpoint",
    "parse_failpoints",
    "refresh_failpoints",
    "release_hangs",
    "RankDeadError",
    "DegradedSnapshotError",
    "LivenessMonitor",
    "LivenessSession",
    "SharedProgress",
    "retry_call",
    "classify_fs",
    "classify_s3",
    "classify_generic",
    "TRANSIENT",
    "MISSING",
    "FATAL",
    "RAISE",
    "SUCCESS_NONE",
]
