"""Cross-rank abort: the KV "poison" protocol's types and encoding.

When a rank hits an unrecoverable error mid-take/restore/promotion it
*poisons* the operation's scope — one KV key every peer can see.
Abort-aware waits (``Coordinator.kv_get``/``barrier`` inside an
``abort_scope``) poll that key while blocking, so every rank raises a
typed ``SnapshotAbortedError`` naming the origin rank and cause within
seconds instead of hanging to the barrier timeout.  The durable-commit
invariant rides on top: rank 0 re-checks the poison key immediately
before writing ``.snapshot_metadata``, so a poisoned operation can
never commit.

This module is deliberately coordination-free (plain types + JSON
encoding); the protocol itself lives on ``Coordinator``
(coordination.py) so all three backends share it by construction.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

# poison keys live outside every uid namespace callers generate
# (commit/N, bar/N, ...): the prefix cannot collide with _next_uid ops
POISON_PREFIX = "__poison__"


def poison_key(scope: str) -> str:
    return f"{POISON_PREFIX}/{scope}"


@dataclasses.dataclass(frozen=True)
class AbortInfo:
    """What a poison key carries: who aborted, where, and why."""

    origin_rank: int
    cause: str
    site: str = ""


class SnapshotAbortedError(RuntimeError):
    """A distributed snapshot operation was aborted — by this rank (the
    original error is chained as ``__cause__``) or by a peer (the
    origin rank and its cause are named here)."""

    def __init__(self, info: AbortInfo, scope: str = "") -> None:
        self.info = info
        self.scope = scope
        super().__init__(
            f"snapshot operation aborted by rank {info.origin_rank}"
            + (f" at {info.site}" if info.site else "")
            + (f" (scope {scope})" if scope else "")
            + f": {info.cause}"
        )


def encode_poison(info: AbortInfo) -> str:
    return json.dumps(
        {
            "origin_rank": info.origin_rank,
            "cause": info.cause,
            "site": info.site,
        }
    )


def decode_poison(raw: str) -> Optional[AbortInfo]:
    """Best-effort decode: a torn/garbled poison value still aborts
    (with an opaque cause) rather than wedging the waiter."""
    try:
        d = json.loads(raw)
        return AbortInfo(
            origin_rank=int(d.get("origin_rank", -1)),
            cause=str(d.get("cause", "")),
            site=str(d.get("site", "")),
        )
    except (ValueError, TypeError, AttributeError):
        return AbortInfo(origin_rank=-1, cause=f"unparseable poison: {raw!r}")
