"""Content-addressed chunk store: the shared chunk pool under a
snapshot root.

A chunk is an immutable byte range of a staged storage object, named by
its content key — ``<crc32>-<adler32>-<size>`` in hex/decimal, the same
two-independent-checksums-plus-exact-length trust basis the incremental
dedup path already uses (one 32-bit collision can never silently alias
two different chunks).  Chunks live under ``objects/<kk>/<key>`` at the
CAS root (``<manager-root>/cas`` by default) and are shared by every
step that references them; the refcounted index (index.py) tracks who.

Write side: a take digests each staged object in ``chunk_size`` spans
(deterministic boundaries — an unchanged slice of a mutated tensor
produces the same key every step) and skips the write for any chunk the
committed index already holds; only new content moves.  The streamed
variant does the same per part inside the part pipeline, so a large
object's unchanged parts release their admission window the moment
their digest resolves — a skipped part never occupies a storage slot.

Read side: ``chunked_read`` maps a RAW byte range onto the overlapping
chunks and fans out parallel ranged reads, assembling into the
``into`` destination when given (the same contract as striped/framed
reads).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from .. import obs
from ..io_types import ReadIO, StoragePlugin, WriteIO, resolve_read_destination
from ..resilience.failpoints import failpoint
from ..storage.stripe import plan_parts

CHUNK_DIR = "objects"


def chunk_key(digest: Tuple[int, int, int]) -> str:
    """Content key for a chunk digest ``(crc32, adler32, size)``."""
    crc, adler, size = digest
    return f"{crc:08x}-{adler:08x}-{int(size)}"


def key_size(key: str) -> int:
    """The exact byte length a key's content must have (embedded in the
    key itself, so integrity checks need no extra metadata read)."""
    return int(key.rsplit("-", 1)[1])


def chunk_location(key: str) -> str:
    # two-hex-char fan-out keeps any one directory from holding the
    # whole pool (fs roots; object stores don't care)
    return f"{CHUNK_DIR}/{key[:2]}/{key}"


def make_table(chunk_size: int, size: int, keys: List[str]) -> Dict[str, Any]:
    """The manifest chunk-ref entry for one storage object: its raw
    byte stream is the concatenation of ``keys``' chunk payloads, tiled
    at ``chunk_size`` (last chunk short)."""
    return {"chunk_size": int(chunk_size), "size": int(size), "keys": list(keys)}


def validate_table(table: Any) -> bool:
    """Structural check (version-skew guard, same contract as
    codec.validate_table): a table that fails here is treated as absent
    so the read fails loudly at the storage layer instead of silently
    assembling garbage."""
    if not isinstance(table, dict):
        return False
    try:
        chunk_size = int(table["chunk_size"])
        size = int(table["size"])
        keys = table["keys"]
    except (KeyError, TypeError, ValueError):
        return False
    if chunk_size <= 0 or size < 0 or not isinstance(keys, list):
        return False
    if len(keys) != len(plan_parts(size, chunk_size)):
        return False
    spans = plan_parts(size, chunk_size)
    for key, (lo, hi) in zip(keys, spans):
        try:
            if key_size(str(key)) != hi - lo:
                return False
        except (ValueError, IndexError):
            return False
    return True


def diff_tables(
    old: Optional[Dict[str, Any]], new: Dict[str, Any]
) -> Tuple[List[int], List[int]]:
    """Positional chunk delta between two chunk tables of the SAME
    logical object: ``(changed, reused)`` index lists into
    ``new["keys"]``.  A chunk is reused only when the old table holds
    the SAME content key at the SAME byte offset — the conservative
    direction: offset-shifted identical content re-fetches rather than
    risking a mapping the applier can't place.  ``old=None`` (or a
    table tiled at a different chunk size, where offsets can't line
    up) marks every chunk changed.  This is the publication planner's
    primitive (publish/delta.py): a subscriber's per-update wire cost
    is exactly the ``changed`` side."""
    keys = list(new["keys"])
    if (
        old is None
        or int(old.get("chunk_size", -1)) != int(new["chunk_size"])
    ):
        return list(range(len(keys))), []
    old_keys = list(old["keys"])
    changed: List[int] = []
    reused: List[int] = []
    for i, key in enumerate(keys):
        if i < len(old_keys) and old_keys[i] == key:
            reused.append(i)
        else:
            changed.append(i)
    return changed, reused


def record_root(snapshot_path: str, cas_root: str) -> str:
    """How the CAS root is written into a snapshot's metadata: relative
    (``../cas``) when the root is a sibling of the snapshot directory —
    the manager layout — so a rehomed checkpoint tree keeps restoring;
    the configured URL verbatim otherwise."""
    snap = snapshot_path.rstrip("/")
    root = cas_root.rstrip("/")
    parent = snap.rsplit("/", 1)[0] if "/" in snap else ""
    if parent and root.startswith(parent + "/"):
        rest = root[len(parent) + 1 :]
        if rest and "/" not in rest:
            return f"../{rest}"
    return root


def resolve_root(snapshot_path: str, recorded: str) -> str:
    """Inverse of ``record_root`` at restore time."""
    if recorded.startswith("../"):
        snap = snapshot_path.rstrip("/")
        parent = snap.rsplit("/", 1)[0] if "/" in snap else ""
        return f"{parent}/{recorded[3:]}" if parent else recorded[3:]
    return recorded


class ChunkStore:
    """Plugin-backed access to one CAS root's chunk pool.  Thin: all
    policy (what to write, what to skip, when to delete) lives in the
    callers; this owns only paths and idempotent chunk I/O."""

    def __init__(
        self, root: str, storage: Optional[StoragePlugin] = None
    ) -> None:
        self.root = root.rstrip("/")
        self._storage = storage

    @property
    def storage(self) -> StoragePlugin:
        if self._storage is None:
            from ..storage import url_to_storage_plugin

            self._storage = url_to_storage_plugin(self.root)
        return self._storage

    async def has(self, key: str) -> bool:
        try:
            return await self.storage.stat(chunk_location(key)) == key_size(key)
        except FileNotFoundError:
            return False

    async def put(self, key: str, buf: Any) -> bool:
        """Store ``buf`` under ``key`` unless an intact copy is already
        durable (the promoter discipline: only content not already in
        the pool moves).  Returns True when bytes were written.
        Concurrent same-key puts are safe — both write the same content
        and every backend's publish is atomic (fs temp+rename, object
        stores by nature)."""
        failpoint("cas.chunk.put", key=key)
        if await self.has(key):
            return False
        await self.storage.write(WriteIO(path=chunk_location(key), buf=buf))
        return True

    async def read_chunk(
        self,
        key: str,
        byte_range: Optional[Tuple[int, int]] = None,
        into: Any = None,
    ) -> Any:
        rio = ReadIO(
            path=chunk_location(key),
            byte_range=list(byte_range) if byte_range else None,
            into=into,
        )
        await self.storage.read(rio)
        return rio.buf

    async def stat(self, key: str) -> int:
        return await self.storage.stat(chunk_location(key))

    async def delete(self, key: str) -> None:
        await self.storage.delete(chunk_location(key))

    def sync_close(self) -> None:
        if self._storage is not None:
            self._storage.sync_close()
            self._storage = None


@dataclass
class CasWriteContext:
    """Everything one WriteReq needs to route through the chunk store:
    attached by the take (snapshot.py) and consumed by the scheduler's
    skip-write short-circuit.  ``known_keys`` is the committed index's
    LIVE key set at take start (orphaned chunks are deliberately
    excluded — a chunk already marked for sweeping must be re-written,
    not referenced, or GC could race the in-flight take past the grace
    window).  ``sink`` receives the object's chunk table, which rides
    the post-staging checksum gather into ``SnapshotMetadata.cas``."""

    store: ChunkStore
    known_keys: Set[str]
    chunk_size: int
    sink: Callable[[Dict[str, Any]], None]
    # chunks this context newly wrote (shared across the take's write
    # reqs): a slab rewritten by two reqs in one take must not double-
    # write, and intra-take repeats (tied weights) dedup for free
    written_this_take: Set[str] = field(default_factory=set)


def _digest_piece(piece: Any) -> Tuple[int, int, int]:
    from ..utils.checksums import adler32_fast, crc32_fast

    v = memoryview(piece).cast("B")
    return (crc32_fast(v), adler32_fast(v), v.nbytes)


def _chunk_concurrency() -> int:
    from ..storage.stripe import part_concurrency

    return part_concurrency()


async def chunked_write(
    ctx: CasWriteContext,
    path: str,
    buf: Any,
    executor: Any = None,
) -> Tuple[Dict[str, Any], int, int]:
    """Store a whole-staged buffer as content-addressed chunks: digest
    each span (on ``executor``), write only chunks the committed index
    doesn't hold, and hand the chunk table to ``ctx.sink``.  Returns
    ``(table, bytes_written, bytes_shared)``."""
    view = memoryview(buf).cast("B")
    total = view.nbytes
    spans = plan_parts(total, ctx.chunk_size)
    keys: List[Optional[str]] = [None] * len(spans)
    loop = asyncio.get_running_loop()
    sem = asyncio.Semaphore(_chunk_concurrency())
    written = 0
    shared = 0
    m_written_b = obs.counter(obs.CAS_BYTES_WRITTEN)
    m_shared_b = obs.counter(obs.CAS_BYTES_SHARED)
    m_written_c = obs.counter(obs.CAS_CHUNKS_WRITTEN)
    m_shared_c = obs.counter(obs.CAS_CHUNKS_SHARED)

    with obs.span("cas/chunked_write", path=path, bytes=total, chunks=len(spans)):

        async def one(idx: int, lo: int, hi: int) -> None:
            nonlocal written, shared
            piece = view[lo:hi]
            if executor is not None:
                digest = await loop.run_in_executor(
                    executor, _digest_piece, piece
                )
            else:
                digest = _digest_piece(piece)
            key = chunk_key(digest)
            keys[idx] = key
            if key in ctx.known_keys or key in ctx.written_this_take:
                shared += hi - lo
                m_shared_b.inc(hi - lo)
                m_shared_c.inc()
                return
            ctx.written_this_take.add(key)
            async with sem:
                with obs.span("cas/put_chunk", key=key, bytes=hi - lo):
                    did_write = await ctx.store.put(key, piece)
            if did_write:
                written += hi - lo
                m_written_b.inc(hi - lo)
                m_written_c.inc()
            else:
                # durable already (an uncommitted earlier take, or a
                # sibling rank racing this one): shared for accounting
                shared += hi - lo
                m_shared_b.inc(hi - lo)
                m_shared_c.inc()

        results = await asyncio.gather(
            *(one(i, lo, hi) for i, (lo, hi) in enumerate(spans)),
            return_exceptions=True,
        )
        errs = [r for r in results if isinstance(r, BaseException)]
        if errs:
            raise errs[0]
    table = make_table(ctx.chunk_size, total, [k for k in keys])
    ctx.sink(table)
    return table, written, shared


async def cas_streamed_write(
    ctx: CasWriteContext,
    path: str,
    stager: Any,
    spans: List[Tuple[int, int]],
    executor: Any,
    *,
    window_parts: int,
    on_part_staged: Optional[Callable[[int], None]] = None,
    on_part_done: Optional[Callable[[int], None]] = None,
    on_part_shared: Optional[Callable[[int], None]] = None,
) -> List[Tuple[int, int, int]]:
    """Per-part stage→digest→store streaming through the chunk pool:
    the CAS twin of ``stripe.streamed_part_write``.  Part N stages,
    digests (digest strictly BEFORE any write — the key IS the dedup
    lookup), and either skips (content already committed: the part's
    admission window releases immediately and no storage op runs) or
    stores its chunk, while parts N+1… are still staging.  Spans must
    tile the object at ``ctx.chunk_size`` so keys line up with the
    chunk plan.  Returns ordered per-part raw digests for the caller to
    fold into the whole-object digest."""
    total = spans[-1][1]
    digests: List[Optional[Tuple[int, int, int]]] = [None] * len(spans)
    keys: List[Optional[str]] = [None] * len(spans)
    loop = asyncio.get_running_loop()
    window = asyncio.Semaphore(window_parts)
    m_phase_stage = obs.histogram(obs.PHASE_STAGE_S)
    m_phase_write = obs.histogram(obs.PHASE_WRITE_S)
    m_written_b = obs.counter(obs.CAS_BYTES_WRITTEN)
    m_shared_b = obs.counter(obs.CAS_BYTES_SHARED)
    m_written_c = obs.counter(obs.CAS_CHUNKS_WRITTEN)
    m_shared_c = obs.counter(obs.CAS_CHUNKS_SHARED)

    with obs.span(
        "cas/stream_write", path=path, bytes=total, chunks=len(spans)
    ):

        async def one(idx: int, span: Tuple[int, int]) -> None:
            lo, hi = span
            await window.acquire()
            try:
                t_stage = time.perf_counter()
                failpoint("scheduler.stage.part", path=path, part=idx)
                with obs.span(
                    "cas/stage_part", path=path, part=idx, bytes=hi - lo
                ):
                    piece = await stager.stage_part(span, executor)
                m_phase_stage.observe(time.perf_counter() - t_stage)
                if on_part_staged is not None:
                    on_part_staged(hi - lo)
                if executor is not None:
                    digest = await loop.run_in_executor(
                        executor, _digest_piece, piece
                    )
                else:
                    digest = _digest_piece(piece)
                digests[idx] = digest
                key = chunk_key(digest)
                keys[idx] = key
                if key in ctx.known_keys or key in ctx.written_this_take:
                    # skip-write short-circuit: the content is already in
                    # the pool — drop the staged part NOW (the finally
                    # below releases the admission window) and never
                    # enter the storage path
                    m_shared_b.inc(hi - lo)
                    m_shared_c.inc()
                    if on_part_shared is not None:
                        on_part_shared(hi - lo)
                    if on_part_done is not None:
                        on_part_done(0)
                    return
                ctx.written_this_take.add(key)
                t0 = time.perf_counter()
                with obs.span(
                    "cas/put_chunk", key=key, part=idx, bytes=hi - lo
                ):
                    did_write = await ctx.store.put(key, piece)
                m_phase_write.observe(time.perf_counter() - t0)
                if did_write:
                    m_written_b.inc(hi - lo)
                    m_written_c.inc()
                    if on_part_done is not None:
                        on_part_done(hi - lo)
                else:
                    m_shared_b.inc(hi - lo)
                    m_shared_c.inc()
                    if on_part_shared is not None:
                        on_part_shared(hi - lo)
                    if on_part_done is not None:
                        on_part_done(0)
            finally:
                window.release()

        try:
            results = await asyncio.gather(
                *(one(i, s) for i, s in enumerate(spans)),
                return_exceptions=True,
            )
        finally:
            stager.release_source()
        errs = [r for r in results if isinstance(r, BaseException)]
        if errs:
            raise errs[0]
        # a failed take leaves already-written chunks in the pool with
        # no index refs — harmless orphans the two-phase GC reclaims
    ctx.sink(make_table(ctx.chunk_size, total, [k for k in keys]))
    return [d for d in digests if d is not None]


async def chunked_read(
    store: ChunkStore,
    path: str,
    table: Dict[str, Any],
    byte_range: Optional[List[int]] = None,
    into: Any = None,
) -> Any:
    """Materialize ``[start, end)`` of a chunk-ref'd object's RAW byte
    stream: parallel ranged reads of the overlapping chunks assembled
    into one buffer (honoring the ``into`` destination hint by
    identity, same contract as striped/framed reads)."""
    chunk_size = int(table["chunk_size"])
    size = int(table["size"])
    keys = table["keys"]
    if byte_range is None:
        start, end = 0, size
    else:
        start, end = int(byte_range[0]), int(byte_range[1])
    if not 0 <= start <= end <= size:
        raise ValueError(
            f"byte range [{start}, {end}) outside chunked object "
            f"{path!r} of size {size}"
        )
    length = end - start
    out = resolve_read_destination(into, length)
    out_view = memoryview(out).cast("B")
    sem = asyncio.Semaphore(_chunk_concurrency())

    with obs.span("cas/chunked_read", path=path, bytes=length):

        async def one(idx: int) -> None:
            clo = idx * chunk_size
            chi = min(clo + chunk_size, size)
            lo, hi = max(start, clo), min(end, chi)
            if lo >= hi:
                return
            dst = out_view[lo - start : hi - start]
            async with sem:
                rng = (
                    None
                    if (lo == clo and hi == chi)
                    else (lo - clo, hi - clo)
                )
                buf = await store.read_chunk(keys[idx], rng, into=dst)
            if buf is not dst:
                got = memoryview(buf).cast("B")
                if got.nbytes != hi - lo:
                    raise IOError(
                        f"chunk {keys[idx]} of {path!r} returned "
                        f"{got.nbytes} bytes, wanted {hi - lo}"
                    )
                dst[:] = got

        if length:
            first = start // chunk_size
            last = (end - 1) // chunk_size
            await asyncio.gather(*(one(i) for i in range(first, last + 1)))
    return out
