"""The refcounted chunk index: which steps reference which chunks.

One JSON document (``index.json`` at the CAS root, self-CRC'd with the
shared trailer discipline from utils/selfcrc.py) mapping chunk key →
``{size, refs, added_by, orphaned_at?}``:

- ``refs`` — snapshot paths (normalized) whose committed manifests
  reference the chunk.  A take adds its refs strictly BEFORE its
  ``.snapshot_metadata`` marker, so an in-flight take's chunks are
  protected from GC the moment they could matter; refs belonging to a
  take that died pre-commit are cleaned up by the mark phase below.
- ``added_by`` — the step that first introduced the chunk (feeds the
  per-step new-vs-shared rollup in the ``stats``/``cas`` CLIs).
- ``orphaned_at`` — set by the MARK phase when no ref looks committed;
  the SWEEP phase deletes the chunk only after the grace window has
  passed AND a re-verification still finds every ref dead.  A chunk
  re-referenced while orphaned is resurrected (``orphaned_at``
  cleared), which is what makes "GC racing a concurrent take" safe.

Mutators are rank-0-only by convention (the same discipline as
``manager_index.json``); the document is written atomically by every
backend (fs temp+rename, object stores by nature).

``fsck`` rebuilds the whole index from committed manifests — the
recovery path after index corruption or a crash that left the index
behind reality.  On listable roots (local fs) it also discovers
on-disk chunks no manifest references and marks them orphaned so the
sweep can reclaim them.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from .. import obs
from ..io_types import ReadIO, WriteIO
from ..utils.selfcrc import append_crc_trailer, strip_crc_trailer
from .store import CHUNK_DIR, ChunkStore, key_size

logger = logging.getLogger(__name__)

CHUNK_INDEX_FNAME = "index.json"
_INDEX_CRC_MARKER = "\n#tsnp-cas-crc32:"
INDEX_VERSION = 1

# One lock per pool root: every load-modify-save of index.json in this
# process serializes through it.  The async-commit thread's commit_refs
# legitimately races the training thread's retention/GC on rank 0 —
# without this, interleaved read-modify-writes would clobber refs a
# committed step depends on.  Cross-PROCESS mutators remain excluded by
# the rank-0-single-writer convention (same as manager_index.json); the
# grace window is the safety margin for out-of-band `cas --gc` runs.
_LOCKS_GUARD = threading.Lock()
_INDEX_LOCKS: Dict[str, Any] = {}


def index_lock(root: str):
    with _LOCKS_GUARD:
        lock = _INDEX_LOCKS.get(root)
        if lock is None:
            lock = _INDEX_LOCKS[root] = threading.RLock()
        return lock


class ChunkIndexCorruptError(RuntimeError):
    """The index document failed its self-checksum or its parse — run
    ``fsck`` (or let the next take auto-fsck) to rebuild it from the
    committed manifests."""


def norm_ref(path: str) -> str:
    """Canonical ref id for a snapshot path (trailing slashes and the
    implicit-fs scheme spelling must not split one step into two ids)."""
    p = path.rstrip("/")
    if p.startswith("fs://"):
        p = p[len("fs://"):]
    return p


class ChunkIndex:
    def __init__(self, chunks: Optional[Dict[str, Dict[str, Any]]] = None):
        # key -> {"size": int, "refs": [id...], "added_by": id,
        #         "orphaned_at": float (absent when live)}
        self.chunks: Dict[str, Dict[str, Any]] = chunks or {}

    # ------------------------------------------------------ persistence

    def to_json(self) -> str:
        return json.dumps(
            {"version": INDEX_VERSION, "chunks": self.chunks},
            sort_keys=True,
        )

    @classmethod
    def from_serialized(cls, s: str) -> "ChunkIndex":
        try:
            body, _ = strip_crc_trailer(
                s, _INDEX_CRC_MARKER, "chunk index", CHUNK_INDEX_FNAME
            )
            d = json.loads(body)
            chunks = {
                str(k): dict(v) for k, v in (d.get("chunks") or {}).items()
            }
            for key, entry in chunks.items():
                entry["size"] = int(entry.get("size", key_size(key)))
                entry["refs"] = [str(r) for r in entry.get("refs", [])]
        except Exception as e:
            raise ChunkIndexCorruptError(
                f"unusable {CHUNK_INDEX_FNAME}: {e!r}"
            ) from e
        return cls(chunks)

    @classmethod
    def load(cls, store: ChunkStore) -> "ChunkIndex":
        """The committed index, or an empty one when none exists yet.
        Raises ``ChunkIndexCorruptError`` (never silently degrades) on
        a corrupt document."""
        rio = ReadIO(path=CHUNK_INDEX_FNAME)
        try:
            store.storage.sync_read(rio)
        except FileNotFoundError:
            return cls()
        return cls.from_serialized(bytes(rio.buf).decode())

    def save(self, store: ChunkStore) -> None:
        store.storage.sync_write(
            WriteIO(
                path=CHUNK_INDEX_FNAME,
                buf=append_crc_trailer(
                    self.to_json(), _INDEX_CRC_MARKER
                ).encode(),
                durable=True,
            )
        )

    # ------------------------------------------------------- accounting

    def live_keys(self) -> Set[str]:
        """Keys a take may dedup against: present, NOT marked orphaned
        (an orphaned chunk could be swept while the take is in flight,
        so new takes re-write that content instead), and NOT flagged
        missing by fsck (dedup against a chunk whose bytes are gone
        would commit an unrestorable step; re-writing the content is
        also what heals the pool)."""
        return {
            k
            for k, e in self.chunks.items()
            if "orphaned_at" not in e and not e.get("missing")
        }

    def add_refs(
        self, ref_id: str, tables: Dict[str, Dict[str, Any]]
    ) -> None:
        """Register every chunk the given step's tables reference;
        resurrects orphan-marked chunks (the step proved them live)."""
        ref_id = norm_ref(ref_id)
        for table in tables.values():
            for key in table.get("keys", ()):
                entry = self.chunks.get(key)
                if entry is None:
                    entry = self.chunks[key] = {
                        "size": key_size(key),
                        "refs": [],
                        "added_by": ref_id,
                    }
                if ref_id not in entry["refs"]:
                    entry["refs"].append(ref_id)
                entry.pop("orphaned_at", None)

    def release(
        self, ref_id: str, now: Optional[float] = None
    ) -> List[Tuple[str, int]]:
        """Drop one step's refs; chunks left with zero refs are marked
        orphaned at ``now`` (phase one of the two-phase GC — physical
        deletion waits for the grace window).  Returns the
        ``(key, size)`` pairs whose refcount dropped to zero — the
        bytes this deletion actually un-shares."""
        ref_id = norm_ref(ref_id)
        now = time.time() if now is None else now
        zeroed: List[Tuple[str, int]] = []
        for key, entry in self.chunks.items():
            if ref_id in entry["refs"]:
                entry["refs"].remove(ref_id)
                if not entry["refs"] and "orphaned_at" not in entry:
                    entry["orphaned_at"] = now
                    zeroed.append((key, entry["size"]))
        return zeroed

    def mark(
        self,
        is_committed: Callable[[str], bool],
        now: Optional[float] = None,
    ) -> int:
        """Phase one over the WHOLE index: chunks with no committed ref
        get orphan-marked; chunks with at least one committed ref are
        resurrected.  Returns how many chunks were newly marked.

        Refs that merely LOOK dead are never pruned here: an in-flight
        take (index update before marker) and a write-back step whose
        durable marker trails its promotion both hold not-yet-committed
        refs that will become committed — dropping them from a chunk
        that stays live (shared with a committed step) would leave the
        later-committed step ref-less, and deleting its peers would
        then sweep chunks it depends on.  Dead refs on live chunks cost
        only rollup noise and are reconciled by ``release``/``fsck``;
        all-dead chunks go through the orphan mark + grace + re-verify
        sweep, which is where actual cleanup belongs."""
        now = time.time() if now is None else now
        verdicts: Dict[str, bool] = {}

        def committed(ref: str) -> bool:
            if ref not in verdicts:
                verdicts[ref] = bool(is_committed(ref))
            return verdicts[ref]

        marked = 0
        for key, entry in self.chunks.items():
            if any(committed(r) for r in entry["refs"]):
                entry.pop("orphaned_at", None)
            elif "orphaned_at" not in entry:
                entry["orphaned_at"] = now
                marked += 1
        return marked

    def sweep_due(
        self, grace_s: float, now: Optional[float] = None
    ) -> List[str]:
        """Keys whose orphan mark has outlived the grace window —
        sweep candidates; the caller re-verifies refs before deleting."""
        now = time.time() if now is None else now
        return sorted(
            k
            for k, e in self.chunks.items()
            if "orphaned_at" in e and now - e["orphaned_at"] >= grace_s
        )

    def remove(self, key: str) -> None:
        self.chunks.pop(key, None)

    # ---------------------------------------------------------- rollups

    def rollup(self) -> Dict[str, Any]:
        """Operator view for the ``stats``/``cas`` CLIs: live/orphaned
        counts and bytes, the refcount histogram, and per-step
        shared-vs-new byte attribution."""
        live = orphaned = live_bytes = orphaned_bytes = 0
        missing = 0
        ref_hist: Dict[str, int] = {}
        per_step: Dict[str, Dict[str, int]] = {}
        for key, entry in self.chunks.items():
            size = entry["size"]
            if entry.get("missing"):
                missing += 1
            if "orphaned_at" in entry:
                orphaned += 1
                orphaned_bytes += size
            else:
                live += 1
                live_bytes += size
            n = len(entry["refs"])
            ref_hist[str(n)] = ref_hist.get(str(n), 0) + 1
            for ref in entry["refs"]:
                st = per_step.setdefault(
                    ref, {"chunks": 0, "new_bytes": 0, "shared_bytes": 0}
                )
                st["chunks"] += 1
                if entry.get("added_by") == ref:
                    st["new_bytes"] += size
                else:
                    st["shared_bytes"] += size
        return {
            "chunks": len(self.chunks),
            "live_chunks": live,
            "orphaned_chunks": orphaned,
            "missing_chunks": missing,
            "live_bytes": live_bytes,
            "orphaned_bytes": orphaned_bytes,
            "refcount_histogram": dict(sorted(ref_hist.items())),
            "per_step": {k: per_step[k] for k in sorted(per_step)},
        }


# ---------------------------------------------------------------- fsck


def _snapshot_is_committed(path: str) -> bool:
    """A readable, intact ``.snapshot_metadata`` is the definition of
    committed — the same contract the restore path enforces."""
    from ..snapshot import Snapshot

    try:
        Snapshot(path).metadata  # noqa: B018 — parse == verification
        return True
    except Exception:  # noqa: BLE001 — absent or corrupt: not committed
        return False


def _local_base(root: str) -> Optional[str]:
    """The listable local path behind ``root``: bare paths and the
    ``fs://`` scheme (what url_to_storage_plugin maps to the fs
    plugin; ``file://`` accepted as an alias) resolve; cloud/opaque
    schemes return None."""
    if "://" not in root:
        return root
    scheme, path = root.split("://", 1)
    return path if scheme in ("", "fs", "file") else None


def _scan_sibling_snapshots(cas_root: str) -> List[str]:
    """Candidate snapshot dirs next to the CAS root (the manager
    layout), local fs only — cloud roots must pass explicit paths."""
    import os

    base = _local_base(cas_root.rstrip("/"))
    if base is None:
        return []
    parent = os.path.dirname(base)
    cas_name = os.path.basename(base)
    try:
        names = os.listdir(parent)
    except FileNotFoundError:
        return []
    return sorted(
        os.path.join(parent, n)
        for n in names
        if n != cas_name and os.path.isdir(os.path.join(parent, n))
    )


def _list_pool_keys(cas_root: str) -> Optional[Set[str]]:
    """Every chunk key physically present in the pool, or None when the
    backend can't list (cloud roots: fsck then rebuilds refs only, and
    unreferenced chunks are reclaimed when their writers re-run GC)."""
    import os

    local = _local_base(cas_root.rstrip("/"))
    if local is None:
        return None
    base = os.path.join(local, CHUNK_DIR)
    keys: Set[str] = set()
    try:
        fanout = os.listdir(base)
    except FileNotFoundError:
        return set()
    for d in fanout:
        sub = os.path.join(base, d)
        try:
            keys.update(os.listdir(sub))
        except NotADirectoryError:
            continue
    return keys


def fsck(
    cas_root: str,
    snapshot_paths: Optional[List[str]] = None,
    now: Optional[float] = None,
) -> Dict[str, Any]:
    """Rebuild the chunk index from committed manifests.

    The recovery path after index corruption, or after a crash between
    a take's index update and its commit marker: refs are recomputed
    from what is ACTUALLY committed, and (on listable roots) chunks in
    the pool that no committed manifest references are orphan-marked so
    the next sweep reclaims them — never deleted here, because an
    in-flight take may be about to commit refs to them (the grace
    window still applies).

    ``snapshot_paths``: the candidate steps; defaults to scanning the
    CAS root's parent directory (local fs manager layout).  A default
    scan that finds ZERO committed snapshots while the pool holds
    chunks is refused: it is indistinguishable from a custom
    (non-sibling) pool layout, and rebuilding would orphan-mark every
    chunk of every committed step — pass explicit ``snapshot_paths``
    (``SnapshotManager.fsck()`` does) to assert the empty set is
    real."""
    from ..snapshot import Snapshot

    now = time.time() if now is None else now
    scanned = snapshot_paths is None
    store = ChunkStore(cas_root)
    with obs.span("cas/fsck", root=cas_root), index_lock(cas_root):
        try:
            if snapshot_paths is None:
                snapshot_paths = _scan_sibling_snapshots(cas_root)
            index = ChunkIndex()
            committed = 0
            for path in snapshot_paths:
                try:
                    md = Snapshot(path).metadata
                except Exception:  # noqa: BLE001 — aborted/corrupt step
                    continue
                committed += 1
                tables = chunk_tables_from_metadata(md)
                if tables:
                    index.add_refs(norm_ref(path), tables)
            pool_keys = _list_pool_keys(cas_root)
            if scanned and committed == 0 and (
                pool_keys is None or pool_keys
            ):
                # pool_keys None = un-listable (cloud) root, where the
                # sibling scan also can't see snapshots — an empty
                # rebuild would silently wipe every committed step's
                # refs, so refuse BOTH the populated-pool and the
                # can't-tell case
                raise RuntimeError(
                    f"cas fsck: sibling scan of {cas_root!r} found no "
                    f"committed snapshots while the pool "
                    f"{'cannot be listed' if pool_keys is None else f'holds {len(pool_keys)} chunk(s)'}"
                    f" — a custom or cloud pool layout?  Rebuilding "
                    f"would orphan (or silently un-ref) every chunk; "
                    f"pass the snapshot paths explicitly "
                    f"(SnapshotManager.fsck())."
                )
            orphans = 0
            if pool_keys is not None:
                for key in pool_keys - set(index.chunks):
                    try:
                        size = key_size(key)
                    except (ValueError, IndexError):
                        continue  # foreign file in the pool: leave it
                    index.chunks[key] = {
                        "size": size,
                        "refs": [],
                        "added_by": None,
                        "orphaned_at": now,
                    }
                    orphans += 1
            missing = sorted(
                set(index.chunks) - pool_keys
            ) if pool_keys is not None else []
            if missing:
                logger.warning(
                    "cas fsck: %d referenced chunk(s) MISSING from the "
                    "pool under %r (first: %s) — the referencing steps "
                    "will fail deep verification",
                    len(missing), cas_root, missing[:3],
                )
                for key in missing:
                    # keep the refs (the damage report) but flag the
                    # entry: takes must not dedup against bytes that
                    # are gone — re-writing the content is what heals
                    # the pool (commit_refs clears the flag once the
                    # bytes verifiably exist again)
                    index.chunks[key]["missing"] = True
            index.save(store)
            obs.counter(obs.CAS_FSCKS).inc()
            return {
                "root": cas_root,
                "snapshots_committed": committed,
                "chunks": len(index.chunks),
                "orphans_marked": orphans,
                "missing_chunks": missing,
            }
        finally:
            store.sync_close()


def chunk_tables_from_metadata(metadata: Any) -> Dict[str, Dict[str, Any]]:
    """location → VALIDATED chunk table for a snapshot's chunk-ref'd
    objects (structurally invalid tables are dropped with a warning so
    the read path fails loudly at the storage layer instead of
    assembling garbage)."""
    from .store import validate_table

    cas = getattr(metadata, "cas", None) or {}
    out: Dict[str, Dict[str, Any]] = {}
    for loc, table in (cas.get("chunks") or {}).items():
        if validate_table(table):
            out[loc] = table
        else:
            logger.warning(
                "manifest chunk table for %r is structurally invalid "
                "(version skew?); treating the object as plain storage",
                loc,
            )
    return out
