"""Two-phase refcounted GC over the chunk pool, plus the commit-side
index update.

Lifecycle of a chunk:

1. **written** by a take (or skipped — content already pooled);
2. **referenced**: the take's rank 0 calls ``commit_refs`` strictly
   BEFORE writing the ``.snapshot_metadata`` marker — from that moment
   the chunk is protected even though the step isn't committed yet;
3. **orphan-marked** (phase one): ``release_step`` (a deliberate
   delete) or ``run_gc``'s mark pass finds it with zero live refs and
   stamps ``orphaned_at`` — nothing is deleted yet;
4. **swept** (phase two): after the grace window
   (``TORCHSNAPSHOT_TPU_CAS_GC_GRACE_S``) the sweep RE-VERIFIES every
   remaining ref against the commit markers and only then deletes the
   chunk bytes and the index entry.  A chunk re-referenced at any
   point before deletion is resurrected.

The grace window is the concurrency story: a take that looked a chunk
up as live can always commit its ref before a racing GC's sweep may
touch it, as long as the window exceeds the take's duration.  Takes
additionally never dedup against already-orphaned chunks
(``ChunkIndex.live_keys``), so sweeps only ever race AGAINST
resurrection, never against a fresh reference to a marked chunk.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

from .. import knobs, obs
from ..resilience.failpoints import failpoint
from .index import (
    ChunkIndex,
    ChunkIndexCorruptError,
    _snapshot_is_committed,
    fsck,
    index_lock,
    norm_ref,
)
from .store import ChunkStore, chunk_location, key_size

logger = logging.getLogger(__name__)


def commit_refs(
    store: ChunkStore,
    ref_id: str,
    tables: Dict[str, Dict[str, Any]],
) -> None:
    """Register one take's chunk references in the index — called by
    rank 0 strictly BEFORE the ``.snapshot_metadata`` marker (sync and
    async commit paths both).  A crash after this but before the marker
    leaves refs for an uncommitted step; the mark phase treats them as
    dead and the grace window reclaims the chunks, so nothing leaks and
    nothing committed is ever endangered.

    Raises when a referenced chunk the index did not already track is
    MISSING from the pool — the skip-write safety net: a sweep that
    raced this take past the grace window (or an operator deleting pool
    files by hand) must fail the take's commit here, never produce a
    committed step whose restore hits missing chunks."""
    with obs.span(
        "cas/commit_refs", ref=ref_id, objects=len(tables)
    ), index_lock(store.root):
        try:
            index = ChunkIndex.load(store)
        except ChunkIndexCorruptError:
            logger.warning(
                "corrupt chunk index under %r at commit time; rebuilding "
                "via fsck before registering refs", store.root,
            )
            fsck(store.root)
            index = ChunkIndex.load(store)
        # verify pool presence for keys the index has no entry for
        # (newly written this take, or re-written content whose prior
        # entry was swept mid-take) and for entries fsck flagged
        # missing (this take re-wrote the content, healing the pool).
        # Other index-tracked entries — live OR orphaned — are
        # guaranteed on storage: the sweep removes the entry and the
        # bytes together, under this same lock.
        ref_keys = {
            str(k) for t in tables.values() for k in t.get("keys", ())
        }
        check = sorted(
            k
            for k in ref_keys
            if k not in index.chunks or index.chunks[k].get("missing")
        )
        missing = _stat_missing(store, check)
        if missing:
            raise RuntimeError(
                f"cas commit for {ref_id!r}: {len(missing)} referenced "
                f"chunk(s) missing from the pool (first: {missing[:3]}) "
                f"— a GC sweep raced this take?  The commit is aborted; "
                f"re-take the step."
            )
        for key in check:
            entry = index.chunks.get(key)
            if entry is not None:
                entry.pop("missing", None)  # verifiably healed
        index.add_refs(ref_id, tables)
        index.save(store)
        # deterministic crash window for the chaos suite: index updated,
        # marker not yet written
        failpoint("cas.index.commit", ref=ref_id)


def _stat_missing(store: ChunkStore, keys: list) -> list:
    """Keys (of ``keys``) absent from the pool or present with the
    wrong size — concurrent stats, one event loop."""
    if not keys:
        return []
    import asyncio

    from ..utils.asyncio_utils import run_in_fresh_loop

    async def gather():
        sem = asyncio.Semaphore(16)

        async def one(key: str):
            async with sem:
                try:
                    ok = await store.stat(key) == key_size(key)
                except FileNotFoundError:
                    ok = False
                return key, ok

        return await asyncio.gather(*(one(k) for k in keys))

    return [k for k, ok in run_in_fresh_loop(gather()) if not ok]


def release_step(
    cas_root: str,
    path: str,
    grace_s: Optional[float] = None,
    now: Optional[float] = None,
) -> int:
    """Drop one deleted step's chunk refs and run a sweep for anything
    already past the grace window.  Returns the byte count of chunks
    whose refcount dropped to zero — the bytes this deletion actually
    un-shared (chunks other steps still reference are NOT counted;
    that is the ``snapshot.gc.bytes_reclaimed`` contract under CAS)."""
    now = time.time() if now is None else now
    store = ChunkStore(cas_root)
    with obs.span(
        "cas/release_step", root=cas_root, ref=path
    ), index_lock(cas_root):
        try:
            try:
                index = ChunkIndex.load(store)
            except ChunkIndexCorruptError:
                logger.warning(
                    "corrupt chunk index under %r during delete; refs for "
                    "%r will be reclaimed by the next fsck/gc",
                    cas_root, path,
                )
                return 0
            zeroed = index.release(norm_ref(path), now=now)
            _sweep(store, index, grace_s, now)
            index.save(store)
            return sum(size for _key, size in zeroed)
        finally:
            store.sync_close()


def run_gc(
    cas_root: str,
    snapshot_paths: Optional[List[str]] = None,
    grace_s: Optional[float] = None,
    now: Optional[float] = None,
) -> Dict[str, Any]:
    """Full mark + sweep: refs are verified against the commit markers
    (refs of never-committed or since-deleted steps go dead, committed
    refs resurrect their chunks), then everything orphaned longer than
    the grace window is re-verified and deleted.  The committed-ness
    probes go straight to each ref's own commit marker (memoized per
    ref) — ``snapshot_paths`` is used ONLY by the corrupt-index fsck
    fallback, which needs the candidate list to rebuild from."""
    now = time.time() if now is None else now
    store = ChunkStore(cas_root)
    with obs.span("cas/gc", root=cas_root), index_lock(cas_root):
        try:
            try:
                index = ChunkIndex.load(store)
            except ChunkIndexCorruptError:
                logger.warning(
                    "corrupt chunk index under %r; rebuilding via fsck "
                    "before GC", cas_root,
                )
                fsck(cas_root, snapshot_paths, now=now)
                index = ChunkIndex.load(store)
            marked = index.mark(_snapshot_is_committed, now=now)
            swept_keys, swept_bytes = _sweep(store, index, grace_s, now)
            index.save(store)
            return {
                "root": cas_root,
                "marked": marked,
                "swept_chunks": swept_keys,
                "swept_bytes": swept_bytes,
                "chunks": len(index.chunks),
            }
        finally:
            store.sync_close()


def _sweep(
    store: ChunkStore,
    index: ChunkIndex,
    grace_s: Optional[float],
    now: float,
) -> tuple:
    """Phase two, in place on ``index``: delete chunks orphaned past
    the grace window whose refs STILL all point at uncommitted steps
    (the re-verification that makes a sweep racing a resurrecting
    commit lose safely)."""
    grace = knobs.get_cas_gc_grace_s() if grace_s is None else grace_s
    swept = 0
    swept_bytes = 0
    verdicts: Dict[str, bool] = {}

    def committed(ref: str) -> bool:
        if ref not in verdicts:
            verdicts[ref] = _snapshot_is_committed(ref)
        return verdicts[ref]

    for key in index.sweep_due(grace, now=now):
        entry = index.chunks[key]
        if any(committed(r) for r in entry["refs"]):
            # resurrected since the mark; refs are kept as-is (an
            # uncommitted-LOOKING ref may be an in-flight take's — see
            # ChunkIndex.mark)
            entry.pop("orphaned_at", None)
            continue
        try:
            store.storage.sync_delete(chunk_location(key))
        except FileNotFoundError:
            pass  # idempotent: a previous partial sweep got the bytes
        index.remove(key)
        swept += 1
        swept_bytes += entry["size"]
    if swept:
        obs.counter(obs.CAS_CHUNKS_SWEPT).inc(swept)
        obs.counter(obs.CAS_BYTES_SWEPT).inc(swept_bytes)
    return swept, swept_bytes
