"""Content-addressed chunk store: chunk-level incremental snapshots
with delta chains and refcounted GC.

Layered UNDER the snapshot format: payload bytes are stored as
content-keyed chunks in a shared per-root pool (``<root>/cas``), a
take skips staging-pipeline writes for chunks an earlier committed
step already stored, the manifest records chunk references (raw
digests preserved — dedup and deep-verify stay bitwise-identical),
and retention becomes refcounted two-phase GC so ANY step can be
deleted without breaking the others.  See docs/incremental.md.

Modules:

- ``store``  — chunk keys/paths, the ``ChunkStore``, the
  chunked/streamed write engines and the assembling read.
- ``index``  — the refcounted self-CRC'd ``index.json`` plus ``fsck``
  (rebuild from committed manifests).
- ``gc``     — commit-side ref registration, release-on-delete, and
  the mark/grace/sweep collector.
"""

from .gc import commit_refs, release_step, run_gc  # noqa: F401
from .index import (  # noqa: F401
    CHUNK_INDEX_FNAME,
    ChunkIndex,
    ChunkIndexCorruptError,
    chunk_tables_from_metadata,
    fsck,
    norm_ref,
)
from .store import (  # noqa: F401
    CasWriteContext,
    ChunkStore,
    cas_streamed_write,
    chunk_key,
    chunk_location,
    chunked_read,
    chunked_write,
    key_size,
    make_table,
    record_root,
    resolve_root,
    validate_table,
)
