"""Snapshot integrity audit: ``verify_snapshot`` / ``Snapshot.verify``.

Beyond-parity subsystem.  The reference's only integrity signal is
restore crashing; operators want to audit checkpoints *before* they
matter (post-save, pre-migration, after storage incidents).  Two levels:

- **shallow** (default): one ``stat`` per physical object — every
  location the manifest references must exist and be at least as large
  as the byte extent the entries claim (batched slabs: the max
  ``byte_range`` end across sharing entries; plain arrays: the exact
  serialized size).  O(#objects) metadata calls, no data movement.
- **deep**: additionally dry-run-restores every array/object entry
  through the real read machinery (no templates, results discarded) —
  proves the bytes deserialize, not just that they exist.  O(payload)
  reads; run it when you'd rather find out now than at restore time.

Primitive entries are inlined in the metadata and verified by parsing.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import codec
from .manifest import (
    Entry,
    PrimitiveEntry,
    ShardedArrayEntry,
    is_container_entry,
)
from .manifest_ops import get_manifest_for_rank
from .preparers import prepare_read
from .scheduler import (
    get_process_memory_budget_bytes,
    sync_execute_read_reqs,
)
from .serialization import serialized_size_bytes, string_to_dtype

logger = logging.getLogger(__name__)


@dataclass
class VerifyResult:
    """Audit outcome.  ``ok`` iff every check passed.

    A snapshot committed degraded (a writer died mid-take and its
    sharded/unreplicated state could not be taken over —
    docs/resilience.md) lists the lost logical paths in ``degraded``:
    those entries are *known-absent by contract*, so they are excluded
    from the missing/truncated audit instead of drowning it in
    expected failures.  ``ok`` therefore means "everything the
    snapshot claims to hold is intact"; ``complete`` additionally
    requires that nothing was lost at commit time."""

    objects_checked: int = 0
    entries_checked: int = 0
    missing: List[str] = field(default_factory=list)
    truncated: List[Tuple[str, int, int]] = field(
        default_factory=list
    )  # (location, expected_min_bytes, actual_bytes)
    unreadable: List[Tuple[str, str]] = field(
        default_factory=list
    )  # (logical_path, error)
    corrupt: List[Tuple[str, int, int]] = field(
        default_factory=list
    )  # (location, recorded_crc32, actual_crc32) — deep mode only
    degraded: List[str] = field(
        default_factory=list
    )  # logical paths the commit recorded as lost to rank death

    @property
    def ok(self) -> bool:
        return not (
            self.missing or self.truncated or self.unreadable or self.corrupt
        )

    @property
    def complete(self) -> bool:
        """``ok`` and the commit lost nothing to rank death."""
        return self.ok and not self.degraded

    def raise_if_failed(self) -> None:
        if not self.ok:
            raise RuntimeError(f"snapshot verification failed: {self}")

    def __str__(self) -> str:
        deg = (
            f", {len(self.degraded)} degraded path(s)"
            if self.degraded
            else ""
        )
        if self.ok:
            return (
                f"OK ({self.objects_checked} objects, "
                f"{self.entries_checked} entries{deg})"
            )
        parts = []
        if self.missing:
            parts.append(f"missing={self.missing[:5]}")
        if self.truncated:
            parts.append(f"truncated={self.truncated[:5]}")
        if self.unreadable:
            parts.append(f"unreadable={self.unreadable[:5]}")
        if self.corrupt:
            parts.append(f"corrupt={self.corrupt[:5]}")
        return "FAILED " + ", ".join(parts) + deg


def _expected_extents(manifest: Dict[str, Entry]) -> Dict[str, int]:
    """location → minimum byte size the manifest implies for it."""
    extents: Dict[str, int] = {}

    def claim(location: str, nbytes: Optional[int]) -> None:
        if nbytes is None:
            # size not derivable (e.g. object codec payloads): existence
            # check only
            extents.setdefault(location, 0)
        else:
            extents[location] = max(extents.get(location, 0), nbytes)

    for entry in manifest.values():
        loc = getattr(entry, "location", None)
        if isinstance(loc, str):
            br = getattr(entry, "byte_range", None)
            if br:
                claim(loc, int(br[1]))
            else:
                shape, dtype = (
                    getattr(entry, "shape", None),
                    getattr(entry, "dtype", None),
                )
                if shape is not None and dtype is not None:
                    claim(
                        loc,
                        serialized_size_bytes(
                            shape, string_to_dtype(dtype)
                        ),
                    )
                else:
                    claim(loc, None)
        for attr in ("shards", "chunks"):
            for shard in getattr(entry, attr, None) or ():
                sdtype = getattr(entry, "dtype", None)
                if shard.byte_range:
                    claim(shard.location, int(shard.byte_range[1]))
                elif sdtype is not None:
                    claim(
                        shard.location,
                        serialized_size_bytes(
                            shard.sizes, string_to_dtype(sdtype)
                        ),
                    )
                else:
                    claim(shard.location, None)
    return extents


_STAT_CONCURRENCY = 16


def _stat_all(storage: Any, locations: List[str]):
    """[(location, size | exception)] — all stats gathered concurrently
    in ONE event loop (a cloud audit over thousands of objects would
    otherwise pay one serial round-trip per object)."""
    import asyncio

    from .utils.asyncio_utils import run_in_fresh_loop

    async def gather():
        sem = asyncio.Semaphore(_STAT_CONCURRENCY)

        async def one(loc: str):
            async with sem:
                try:
                    return loc, await storage.stat(loc)
                except asyncio.CancelledError:
                    raise  # Ctrl-C/cancellation aborts the audit
                except Exception as e:  # noqa: BLE001
                    return loc, e

        return await asyncio.gather(*(one(loc) for loc in locations))

    return run_in_fresh_loop(gather())


def _crc_targets(
    manifest: Dict[str, Entry]
) -> List[Tuple[str, Optional[List[int]], int]]:
    """(location, byte_range, recorded_crc32) for every payload the
    manifest carries a content checksum for (knobs WRITE_CHECKSUMS)."""
    targets = []
    seen = set()
    for entry in manifest.values():
        loc = getattr(entry, "location", None)
        crc = getattr(entry, "crc32", None)
        if isinstance(loc, str) and crc is not None:
            key = (loc, tuple(getattr(entry, "byte_range", None) or ()))
            if key not in seen:
                seen.add(key)
                targets.append(
                    (loc, getattr(entry, "byte_range", None), crc)
                )
        for attr in ("shards", "chunks"):
            for s in getattr(entry, attr, None) or ():
                if s.crc32 is None:
                    continue
                key = (s.location, tuple(s.byte_range or ()))
                if key not in seen:
                    seen.add(key)
                    targets.append((s.location, s.byte_range, s.crc32))
    return targets


def _check_crcs(
    storage: Any,
    manifest: Dict[str, Entry],
    result: VerifyResult,
    extents: Dict[str, int],
    codec_tables: Optional[Dict[str, Any]] = None,
    cas_reads: Optional[Any] = None,
) -> set:
    """Deep mode: re-read every checksummed payload and compare crc32
    (catches bit rot / torn or overwritten content that sizes and parse
    checks can miss).  Returns the set of ``(location, byte_range)``
    keys that VERIFIED — entries fully covered by verified checksums
    skip the parse pass (their bytes are exactly what the serializer
    wrote, so re-reading them to parse would double the audit's I/O).

    Reads are admitted under the process staging budget (each task
    buffers its whole payload; 16 concurrent 128MB slabs would otherwise
    spike multi-GB on a small audit VM)."""
    import asyncio
    import os
    from concurrent.futures import ThreadPoolExecutor

    from .io_types import ReadIO
    from .utils.asyncio_utils import run_in_fresh_loop
    from .utils.checksums import crc32_fast

    targets = _crc_targets(manifest)
    if not targets:
        return set()
    budget_cap = get_process_memory_budget_bytes()
    # codec frames decode on this pool so a 64MB decompress never blocks
    # the loop thread that all the other reads are overlapping on
    decode_pool = ThreadPoolExecutor(
        max_workers=max(1, os.cpu_count() or 1),
        thread_name_prefix="verify-decode",
    )

    def size_of(loc, byte_range):
        if byte_range:
            return int(byte_range[1]) - int(byte_range[0])
        return extents.get(loc, 0)

    async def gather():
        sem = asyncio.Semaphore(_STAT_CONCURRENCY)
        in_use = 0
        budget_free = asyncio.Condition()

        async def one(loc, byte_range, crc):
            nonlocal in_use
            nbytes = size_of(loc, byte_range)
            async with budget_free:
                # admit under budget; an oversized payload is admitted
                # alone (same progress rule as the write scheduler)
                await budget_free.wait_for(
                    lambda: in_use == 0 or in_use + nbytes <= budget_cap
                )
                in_use += nbytes
            try:
                async with sem:
                    cas_table = (
                        cas_reads[1].get(loc) if cas_reads else None
                    )
                    table = (
                        codec_tables.get(loc) if codec_tables else None
                    )
                    if cas_table is not None:
                        # chunk-ref'd object (cas/): recorded crcs are
                        # RAW-byte crcs of the assembled stream, so
                        # reassemble through the chunk pool (which also
                        # proves every referenced chunk is readable)
                        from . import cas as cas_mod

                        buf = await cas_mod.chunked_read(
                            cas_reads[0],
                            loc,
                            cas_table,
                            byte_range=(
                                list(byte_range) if byte_range else None
                            ),
                        )
                    elif table is not None:
                        # encoded object: recorded crcs are RAW-byte
                        # crcs, so decode through the frame layer (which
                        # also proves the frames themselves are intact)
                        buf = await codec.framed_read(
                            storage,
                            loc,
                            table,
                            byte_range=(
                                list(byte_range) if byte_range else None
                            ),
                            executor=decode_pool,
                        )
                    else:
                        read_io = ReadIO(
                            path=loc,
                            byte_range=(
                                list(byte_range) if byte_range else None
                            ),
                        )
                        await storage.read(read_io)
                        buf = read_io.buf
                    actual = crc32_fast(memoryview(buf).cast("B"))
                    return loc, byte_range, crc, actual, None
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                return loc, byte_range, crc, None, e
            finally:
                async with budget_free:
                    in_use -= nbytes
                    budget_free.notify_all()

        return await asyncio.gather(
            *(one(*target) for target in targets)
        )

    verified = set()
    try:
        results = run_in_fresh_loop(gather())
    finally:
        decode_pool.shutdown(wait=False)
    for loc, byte_range, crc, actual, err in results:
        if err is not None:
            # existence/size problems are already reported by the stat
            # pass; don't double-report missing objects here
            if not isinstance(err, FileNotFoundError):
                result.unreadable.append((loc, f"crc read: {err!r}"))
        elif actual != crc:
            result.corrupt.append((loc, crc, actual))
        else:
            verified.add((loc, tuple(byte_range or ())))
    return verified


def _fully_crc_verified(entry: Entry, verified: set) -> bool:
    """True iff the entry has ≥1 payload and EVERY payload's
    (location, byte_range) verified against a recorded checksum."""
    n = 0
    loc = getattr(entry, "location", None)
    if isinstance(loc, str):
        n += 1
        key = (loc, tuple(getattr(entry, "byte_range", None) or ()))
        if key not in verified:
            return False
    for attr in ("shards", "chunks"):
        for s in getattr(entry, attr, None) or ():
            n += 1
            if (s.location, tuple(s.byte_range or ())) not in verified:
                return False
    return n > 0


def verify_snapshot(
    snapshot: Any, deep: bool = False, rank: Optional[int] = None
) -> VerifyResult:
    """Audit one rank's view of a snapshot (default: this process's
    rank).  ``snapshot``: a ``Snapshot`` or a path/URL.  See module
    docstring for the shallow/deep contract."""
    from .event import Event
    from .event_handlers import log_event

    if isinstance(snapshot, str):
        from .snapshot import Snapshot

        snapshot = Snapshot(snapshot)
    if rank is None:
        rank = snapshot._coordinator.rank
    with log_event(
        Event("verify", {"path": snapshot.path, "deep": deep, "rank": rank})
    ):
        return _verify_impl(snapshot, deep, rank)


def _verify_impl(snapshot: Any, deep: bool, rank: int) -> VerifyResult:
    from .snapshot import _storage_for

    result = VerifyResult()
    manifest = dict(get_manifest_for_rank(snapshot.metadata, rank))
    # degraded paths (lost to rank death at commit — manifest.degraded)
    # are known-absent by contract: report them as degraded and drop
    # them from the audit manifest so their never-written payloads don't
    # flood ``missing``.  Same view rule as restore: this rank's audit
    # is affected iff its view would source the dead rank's bytes.
    degraded_meta = getattr(snapshot.metadata, "degraded", None) or {}
    if degraded_meta:
        result.degraded = sorted(
            p
            for p, e in manifest.items()
            if p in degraded_meta
            and not is_container_entry(e)
            and (
                rank == degraded_meta[p].get("origin_rank")
                or isinstance(e, ShardedArrayEntry)
                or bool(getattr(e, "replicated", False))
            )
        )
        for p in result.degraded:
            del manifest[p]
    storage = _storage_for(
        snapshot.path, getattr(snapshot, "_storage_options", None)
    )
    # chunk-ref'd locations (cas/) have no per-step storage object:
    # their residency check stats the referenced CHUNKS in the shared
    # pool instead, and deep reads reassemble through it
    cas_reads = (
        snapshot._cas_reads() if hasattr(snapshot, "_cas_reads") else None
    )
    cas_tables = cas_reads[1] if cas_reads is not None else {}
    try:
        extents = _expected_extents(manifest)
        # the objects table (WRITE_CHECKSUMS takes) records exact sizes —
        # a stricter bound than the entry-derived minimum extents
        exact_sizes = {
            loc: rec[2]
            for loc, rec in (snapshot.metadata.objects or {}).items()
            if isinstance(rec, (list, tuple)) and len(rec) == 3
        }
        # codec-encoded objects (codec.py): what's on storage is the
        # FRAME stream, so expected sizes come from the codec table's
        # stored lengths — the raw sizes above would flag every encoded
        # object as truncated
        codec_tables = snapshot._codec_tables() or {}
        for loc, tbl in codec_tables.items():
            stored = codec.table_stored_size(tbl)
            exact_sizes[loc] = stored
            if loc in extents:
                extents[loc] = stored
        for location, outcome in _stat_all(
            storage, sorted(set(extents) - set(cas_tables))
        ):
            expected = extents[location]
            if isinstance(outcome, FileNotFoundError):
                result.missing.append(location)
            elif isinstance(outcome, BaseException):
                result.unreadable.append((location, f"stat: {outcome!r}"))
            else:
                result.objects_checked += 1
                exact = exact_sizes.get(location)
                if exact is not None and outcome != exact:
                    result.truncated.append((location, exact, outcome))
                elif outcome < expected:
                    result.truncated.append((location, expected, outcome))
        if cas_tables:
            from . import cas as cas_mod

            chunk_sizes = {
                cas_mod.chunk_location(k): cas_mod.key_size(k)
                for loc in cas_tables
                if loc in extents  # this rank's view only
                for k in cas_tables[loc]["keys"]
            }
            for location, outcome in _stat_all(
                cas_reads[0].storage, sorted(chunk_sizes)
            ):
                if isinstance(outcome, FileNotFoundError):
                    result.missing.append(location)
                elif isinstance(outcome, BaseException):
                    result.unreadable.append(
                        (location, f"stat: {outcome!r}")
                    )
                else:
                    result.objects_checked += 1
                    # the key embeds the exact length — any other size
                    # is corruption, not a benign over-allocation
                    if outcome != chunk_sizes[location]:
                        result.truncated.append(
                            (location, chunk_sizes[location], outcome)
                        )

        crc_verified: set = set()
        if deep:
            crc_verified = _check_crcs(
                storage, manifest, result, extents, codec_tables,
                cas_reads,
            )

        for lpath, entry in sorted(manifest.items()):
            if is_container_entry(entry):
                continue
            result.entries_checked += 1
            if isinstance(entry, PrimitiveEntry):
                try:
                    entry.get_value()
                except Exception as e:  # noqa: BLE001
                    result.unreadable.append((lpath, repr(e)))
                continue
            if not deep:
                continue
            if _fully_crc_verified(entry, crc_verified):
                # every payload byte matched the checksum recorded when
                # the serializer produced it — a parse re-read would
                # double the I/O to re-learn the same thing
                continue
            try:
                read_reqs, fut = prepare_read(entry, obj_out=None)
                sync_execute_read_reqs(
                    list(read_reqs),
                    storage,
                    get_process_memory_budget_bytes(),
                    rank,
                    codec_tables=codec_tables or None,
                    cas_reads=cas_reads,
                )
                if fut.obj is None:
                    raise RuntimeError("read produced no value")
            except Exception as e:  # noqa: BLE001
                result.unreadable.append((lpath, repr(e)))
    finally:
        storage.sync_close()
        if cas_reads is not None:
            cas_reads[0].sync_close()
    if not result.ok:
        logger.warning("snapshot %r verification: %s", snapshot.path, result)
    return result
