"""Core I/O contracts: cost-annotated deferred work items + storage plugin ABC.

This is the load-bearing abstraction of the whole design, mirrored from the
reference (torchsnapshot/io_types.py:24-120):

- ``BufferStager``: deferred "produce the bytes" (device→host transfer +
  serialize), annotated with its peak host-memory cost so the scheduler can
  admit work under a budget.
- ``BufferConsumer``: the read-side dual — "consume these bytes" (deserialize
  + place into the target array/object).
- ``WriteReq``/``ReadReq`` bind a storage path to a stager/consumer;
  ``ReadReq`` carries an optional byte range for ranged reads.
- ``StoragePlugin``: async write/read/delete/close against a storage backend.

On TPU the stager's device→host copy is ``jax.Array.copy_to_host_async()``
per addressable shard followed by ``np.asarray`` in a worker thread — XLA
transfers complete on their own stream, so cost accounting hooks transfer
completion, not task creation (see scheduler.py).
"""

from __future__ import annotations

import abc
import asyncio
from concurrent.futures import Executor
from dataclasses import dataclass, field
from typing import Any, Callable, Generic, List, Optional, Tuple, TypeVar

T = TypeVar("T")


class Future(Generic[T]):
    """A placeholder for a value produced after read execution completes
    (reference io_types.py Future)."""

    __slots__ = ("obj", "_done")

    def __init__(self, obj: Optional[T] = None) -> None:
        self.obj = obj
        self._done = False

    def set(self, obj: T) -> None:
        self.obj = obj
        self._done = True

    @property
    def done(self) -> bool:
        return self._done


class BufferStager(abc.ABC):
    """Deferred producer of a write buffer (reference io_types.py:24-38)."""

    # Codec preconditioning hint (codec.py): the element stride the
    # byte-shuffle filter should use for this stager's bytes (0 = no
    # filter).  Preparers set it from the manifest dtype (float formats
    # shuffle; ints/bytes/objects don't) — a pure hint, never
    # correctness-bearing: the chosen stride is recorded in each frame's
    # header, so restore needs nothing from the stager.
    codec_filter_stride: int = 0

    @abc.abstractmethod
    async def stage_buffer(self, executor: Optional[Executor] = None) -> Any:
        """Produce the bytes to write (bytes / memoryview). May launch
        device→host transfers; heavy host work should run on ``executor``."""

    @abc.abstractmethod
    def get_staging_cost_bytes(self) -> int:
        """Peak host memory consumed while the staged buffer is alive."""

    # ------------------------------------------------- part streaming
    # Optional capability consumed by the scheduler's stripe path: a
    # stager that can produce its bytes one part at a time lets a large
    # object's staging and storage I/O overlap WITHIN the object — a
    # part stages, its write dispatches immediately, later parts are
    # still staging — and the memory-budget reservation shrinks from
    # the whole object to a window of parts.  Stagers that can only
    # materialize whole (device packs, slabs with interior checksum
    # ranges) keep the defaults and stage as before.

    def part_plan(self, part_size_bytes: int) -> Optional[List[Tuple[int, int]]]:
        """``[start, end)`` byte spans that exactly tile the staged
        object (last span may be short), or None when this stager can
        only stage whole.  Spans must be returnable BEFORE staging (the
        exact-size property the buffer-protocol stagers already have)."""
        return None

    async def stage_part(
        self, span: Tuple[int, int], executor: Optional[Executor] = None
    ) -> Any:
        """Produce exactly the bytes of ``span`` (a span from
        ``part_plan``).  Each part buffer must be independent of the
        others so it can be released as soon as its write completes."""
        raise NotImplementedError

    def release_source(self) -> None:
        """Drop references to the staging source after the last
        ``stage_part`` call (success or failure) — the part-streaming
        twin of ``stage_buffer``'s drop-refs-early discipline."""


class BufferConsumer(abc.ABC):
    """Read-side dual of BufferStager (reference io_types.py:41-56)."""

    @abc.abstractmethod
    async def consume_buffer(
        self, buf: Any, executor: Optional[Executor] = None
    ) -> None:
        """Deserialize ``buf`` and place the result into its target."""

    @abc.abstractmethod
    def get_consuming_cost_bytes(self) -> int:
        """Peak host memory consumed while the read buffer is alive."""


@dataclass
class WriteReq:
    path: str
    buffer_stager: BufferStager
    # (sink, byte_range | None): after staging, each sink receives the
    # crc32 of its slice of the staged buffer (None = whole buffer) —
    # preparers point these at manifest entry/shard ``crc32`` fields so
    # committed metadata carries end-to-end content checksums.  The
    # batcher re-ranges sinks when it folds requests into a slab.
    checksum_sinks: Optional[
        List[Tuple[Callable[[int], None], Optional[Tuple[int, int]]]]
    ] = None
    # incremental takes: (base snapshot url, that base's object digest
    # for this same location).  When the staged object's digest matches,
    # the write is replaced by StoragePlugin.link_from (hardlink /
    # server-side copy) — content-addressed dedup against the previous
    # checkpoint.  The digest is (crc32, adler32, size): two independent
    # checksums + exact length, so one 32-bit collision can't silently
    # link stale content.
    dedup: Optional[Tuple[str, Tuple[int, int, int]]] = None
    # receives the staged object's (crc32, adler32, size) at staging
    # time when WRITE_CHECKSUMS is on
    digest_sink: Optional[Callable[[List[int]], None]] = None
    # filled via digest_sink; consumed by the dedup check
    object_digest: Optional[Tuple[int, int, int]] = None
    # codec layer (codec.py): receives the object's frame table when the
    # write was stored compressed — the snapshot take points this at its
    # per-rank codec map, which rides the crc gather into
    # SnapshotMetadata.codecs.  Writes WITHOUT a sink are never encoded
    # (nothing could record how to decode them).
    codec_sink: Optional[Callable[[dict], None]] = None
    # incremental takes: the BASE snapshot's codec-table entry for this
    # location (None = base stored it raw).  A successful dedup link
    # copies the base's stored bytes, so its frame table must carry over
    # verbatim.
    dedup_codec: Optional[dict] = None
    # content-addressed chunk store (cas/): a CasWriteContext routing
    # this write through the shared chunk pool instead of a per-step
    # object — the scheduler digests the staged bytes in chunk-size
    # spans, skips the write for every chunk an earlier committed step
    # already stored, and the context's sink records the chunk table
    # into the manifest.  Mutually exclusive with ``dedup`` (chunk-level
    # addressing subsumes whole-object base links) and with the codec
    # layer (chunks store raw bytes — their keys ARE raw digests).
    cas: Optional[Any] = None


def check_read_crc(read_req: "ReadReq", buf: Any) -> None:
    """VERIFY_ON_RESTORE: fail loudly when a whole-payload read doesn't
    match its manifest-recorded checksum (shared by the scheduler's
    request-level check and the batcher's per-member slice check)."""
    from .utils.checksums import crc32_fast

    expected = read_req.expected_crc32
    actual = crc32_fast(memoryview(buf).cast("B"))
    if actual != expected:
        raise RuntimeError(
            f"checksum mismatch reading {read_req.path!r} "
            f"(range {read_req.byte_range}): recorded crc32={expected}, "
            f"read crc32={actual} — the payload changed after commit"
        )


@dataclass
class ReadReq:
    path: str
    buffer_consumer: BufferConsumer
    byte_range: Optional[List[int]] = None  # [start, end)
    # manifest-recorded crc32 when this read covers a payload exactly
    # (whole entry/shard/chunk — never a tile); checked before consume
    # when knobs VERIFY_ON_RESTORE is on
    expected_crc32: Optional[int] = None
    # OPTIONAL destination hint: a writable buffer of exactly this
    # read's byte length (e.g. a numpy restore template's memory).  A
    # plugin MAY read straight into it and set ``buf = into`` (the fs
    # plugin's native path does), making host restore a single read
    # pass with no intermediate buffer — the reference's read-into-
    # preallocated-tensor property.  Plugins are free to ignore it;
    # consumers detect honor by identity (``buf is into``) and fall
    # back to the normal copy otherwise, so ignoring is always safe.
    into: Any = None
    # Restore prioritization (serving): lower values execute first.
    # The read scheduler orders its admission queue by this key (stable
    # within a priority class), so a server restoring a snapshot can
    # ask for its first-requested layers first and begin serving before
    # the full snapshot lands.  Purely an ordering hint — correctness
    # never depends on it.
    priority: int = 0


def resolve_read_destination(into: Any, length: int) -> Any:
    """The assembly buffer for a ``length``-byte read honoring the
    ``into`` hint (see ReadReq.into): ``into`` itself when it is a
    writable buffer of exactly ``length`` bytes (callers detect honor
    by identity), else a fresh uint8 array.  Shared by every ranged
    parallel assembler (striped_read, codec.framed_read) so the
    into-honoring contract can't diverge between them."""
    if into is not None:
        try:
            v = memoryview(into).cast("B")
            if not v.readonly and v.nbytes == length:
                return into
        except (TypeError, ValueError):
            pass  # exotic/non-contiguous hint: assemble normally
    import numpy as np

    return np.empty(length, dtype=np.uint8)


@dataclass
class WriteIO:
    path: str
    buf: Any  # bytes | memoryview
    # Durable writes are fdatasync'd, with every directory up the chain
    # fsync'd too.  Set for the COMMIT-point write (.snapshot_metadata)
    # only; bulk data defaults to page-cache mode, so by default the
    # guarantee is "a crash never leaves a HALF-written metadata file" —
    # NOT "a committed local-fs snapshot survives any crash" (data files
    # behind the marker may still be in page cache; a crash window of
    # seconds remains).  For full local-fs crash durability set
    # TORCHSNAPSHOT_TPU_FS_SYNC_DATA=1, which fdatasyncs every data
    # write (costs write throughput).  Object stores (the production
    # target) are durable-on-success by nature and ignore all of this.
    durable: bool = False
    # Digest request: the caller wants the zlib (crc32, adler32) of
    # ``buf``.  A plugin MAY compute it fused with its write (the fs
    # native path digests each block cache-hot in the same pass that
    # hands it to write(2)) and set ``digests``; plugins that don't are
    # fine — the scheduler computes post-write when ``digests`` is
    # still None.  Saves one full read pass over every checksummed
    # direct write on honoring plugins.
    want_digest: bool = False
    digests: Optional[Tuple[int, int]] = None  # set by honoring plugins


@dataclass
class ReadIO:
    path: str
    byte_range: Optional[List[int]] = None
    buf: Any = field(default=None)  # filled by the plugin
    # destination hint (see ReadReq.into); honoring plugins read into
    # it and set ``buf = into``
    into: Any = None
    # Zero-copy request: a plugin that declares ``supports_mmap_read``
    # MAY serve this read as a READ-ONLY mmap-backed buffer (a numpy
    # view over file-backed pages) instead of copying into the heap.
    # Callers detect honor with ``is_mmap_backed(buf)``; plugins are
    # free to ignore the flag (e.g. when the knob is off), so setting
    # it is always safe.  Mutually exclusive with ``into`` in practice
    # — a caller that wants bytes placed into its own buffer has no
    # use for a foreign mapping.
    want_mmap: bool = False


def is_mmap_backed(buf: Any) -> bool:
    """True when ``buf`` is (a view over) an ``mmap.mmap`` — the
    detection contract for ReadIO.want_mmap honor.  Walks the
    numpy ``.base`` / memoryview ``.obj`` ownership chain, so sliced
    and dtype-viewed arrays over a mapping still report True."""
    import mmap as _mmap

    o = buf
    for _ in range(8):  # ownership chains are shallow; bound the walk
        if o is None:
            return False
        if isinstance(o, _mmap.mmap):
            return True
        o = o.obj if isinstance(o, memoryview) else getattr(o, "base", None)
    return False


class StripedWriteHandle(abc.ABC):
    """One in-flight striped (multipart) write of a single object.

    Obtained from ``StoragePlugin.begin_striped_write``; parts may be
    written concurrently and in any order, then EXACTLY ONE of
    ``complete``/``abort`` finishes the handle.  The object must never
    be observable half-written: ``complete`` is the atomic publish (S3
    CompleteMultipartUpload, GCS compose, fs temp→rename) and ``abort``
    must leave zero orphaned parts/temp files behind — a poisoned or
    failed take cleans up after itself (the chaos suite asserts this).

    Retry/failpoint/breaker discipline lives INSIDE ``write_part`` (the
    per-backend classifiers know what a transient looks like), so a
    transient mid-object re-sends one part, not the object."""

    # the part-level twin of StoragePlugin.supports_fused_digest: True
    # when write_part honors ``want_digest`` by computing the part's
    # (crc32, adler32) fused with its copy/upload — the stripe engine
    # then skips its separate per-part digest pass
    supports_fused_digest: bool = False

    # smallest part the backend accepts in any position but the last
    # (S3 rejects CompleteMultipartUpload with EntityTooSmall when a
    # non-final part is under 5MiB).  0 = no floor.  The codec stream
    # consults this: an encoded frame that lands under the floor stores
    # that part raw instead (raw parts are sized by the stripe knob,
    # which backends size above their floor)
    min_part_bytes: int = 0

    @abc.abstractmethod
    async def write_part(
        self, index: int, offset: int, buf: Any, want_digest: bool = False
    ) -> Optional[Tuple[int, int]]:
        """Write ``buf`` at byte ``offset`` as part ``index`` (0-based,
        contiguous, exactly tiling the object).  Returns the part's
        (crc32, adler32) when ``want_digest`` and the handle fuses
        digests, else None."""

    @abc.abstractmethod
    async def complete(self) -> None:
        """Atomically publish the assembled object."""

    @abc.abstractmethod
    async def abort(self) -> None:
        """Tear down without publishing; idempotent and best-effort
        (never raises over the original failure)."""


class StoragePlugin(abc.ABC):
    """Async storage backend (reference io_types.py:80-120)."""

    # True when this plugin honors WriteIO.want_digest by computing the
    # (crc32, adler32) fused with its write (one pass over the staged
    # bytes).  The scheduler only DEFERS checksum work to the write for
    # such plugins — on anything else the pre-write digest path keeps
    # its staging-phase overlap.
    supports_fused_digest: bool = False

    # True when begin_striped_write is implemented; the stripe engine
    # (storage/stripe.py) checks this before splitting a write.  Ranged
    # READS need no capability flag — every plugin already honors
    # ReadIO.byte_range, so striped restore works against any backend.
    supports_striped_write: bool = False

    # True when this plugin's striped-write HANDLES honor write_part's
    # ``want_digest`` (StripedWriteHandle.supports_fused_digest) — the
    # scheduler then defers checksum work for stripe-eligible writes
    # too: the folded per-part digests replace the separate staging-
    # phase pass over the whole object.  Plugin-level so the defer
    # decision can be made BEFORE a handle exists.
    supports_fused_part_digest: bool = False

    # True when this plugin can honor ReadIO.want_mmap by serving raw
    # object bytes as a read-only mmap-backed buffer (fs, the shared-
    # host cache, tiered fast reads).
    supports_mmap_read: bool = False

    # STRICTER than supports_mmap_read: True only when every read this
    # plugin serves stays off the Python heap (a local file map, or a
    # cache whose fills stream in bounded spans) — it can never decline
    # into buffering a whole object.  This is the flag the read
    # scheduler keys budget-exempt admission (and the striped-read
    # bypass) on: a composite that can fall back to a raw cloud GET
    # (tier over uncached s3) must keep budgeted, striped reads on that
    # degraded path, even though its fast leg serves mappings.
    mmap_budget_exempt: bool = False

    async def begin_striped_write(
        self, path: str, total_size: int
    ) -> StripedWriteHandle:
        """Open a striped write of ``total_size`` bytes to ``path``.
        Only called when ``supports_striped_write`` is True."""
        raise NotImplementedError

    @abc.abstractmethod
    async def write(self, write_io: WriteIO) -> None: ...

    @abc.abstractmethod
    async def read(self, read_io: ReadIO) -> None: ...

    @abc.abstractmethod
    async def delete(self, path: str) -> None: ...

    async def stat(self, path: str) -> int:
        """Object size in bytes; FileNotFoundError if absent.  The
        default reads the whole object (correct on any plugin);
        subclasses override with a cheap metadata call."""
        read_io = ReadIO(path=path)
        await self.read(read_io)
        return len(read_io.buf)

    async def link_from(self, base_url: str, path: str) -> None:
        """Make ``path`` under this plugin's root hold the same content
        as ``path`` under ``base_url``, WITHOUT moving the bytes through
        this host when the backend can avoid it (fs: hardlink; object
        stores: server-side copy).  Each snapshot must own the resulting
        object — deleting the base must not affect it.  Raising
        NotImplementedError makes the caller fall back to a normal
        write."""
        raise NotImplementedError

    async def close(self) -> None:
        pass

    # Sync convenience wrappers (reference io_types.py:107-120)
    def sync_write(self, write_io: WriteIO) -> None:
        from .utils.asyncio_utils import run_in_fresh_loop

        run_in_fresh_loop(self.write(write_io))

    def sync_read(self, read_io: ReadIO) -> None:
        from .utils.asyncio_utils import run_in_fresh_loop

        run_in_fresh_loop(self.read(read_io))

    def sync_delete(self, path: str) -> None:
        from .utils.asyncio_utils import run_in_fresh_loop

        run_in_fresh_loop(self.delete(path))

    def sync_stat(self, path: str) -> int:
        from .utils.asyncio_utils import run_in_fresh_loop

        return run_in_fresh_loop(self.stat(path))

    def sync_close(self) -> None:
        from .utils.asyncio_utils import run_in_fresh_loop

        run_in_fresh_loop(self.close())
