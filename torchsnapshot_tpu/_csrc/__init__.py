"""Native extension loader: builds fastio.so on first use (g++, cached),
falls back to pure Python silently when no toolchain is available.

Bindings are ctypes (no pybind11 in the image); all entry points release
the GIL for the duration of the syscall chain, so the scheduler's worker
threads overlap I/O properly.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fastio.cpp")
_SO = os.path.join(_HERE, "fastio.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build() -> bool:
    # Compile to a process-unique temp file and os.rename into place:
    # atomic on posix, so concurrent first-use across processes (the
    # multi-process tests spawn several) can never observe a half-written
    # .so — worst case they each build once and the last rename wins.
    tmp = f"{_SO}.tmp.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _SO)
        return True
    except Exception as e:  # noqa: BLE001
        logger.debug("fastio build failed (falling back to Python): %r", e)
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _try_load() -> Optional[ctypes.CDLL]:
    try:
        return ctypes.CDLL(_SO)
    except OSError as e:
        logger.debug("fastio load failed: %r", e)
        return None


def load() -> Optional[ctypes.CDLL]:
    """The fastio library, or None when unavailable."""
    global _lib, _load_attempted
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        lib = None
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(
            _SRC
        ):
            lib = _try_load()
        if lib is None:
            # stale, absent, or unloadable (e.g. foreign-platform binary):
            # rebuild once and retry
            if not _build():
                return None
            lib = _try_load()
            if lib is None:
                return None
        lib.tsnp_write_file.restype = ctypes.c_int
        lib.tsnp_write_file.argtypes = [
            ctypes.c_char_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int,
        ]
        lib.tsnp_read_file.restype = ctypes.c_int64
        lib.tsnp_read_file.argtypes = [
            ctypes.c_char_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.tsnp_file_size.restype = ctypes.c_int64
        lib.tsnp_file_size.argtypes = [ctypes.c_char_p]
        lib.tsnp_crc32c.restype = ctypes.c_uint32
        lib.tsnp_crc32c.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_uint32,
        ]
        _lib = lib
        return _lib


def _buffer_address(view: memoryview) -> int:
    # zero-copy pointer even for read-only buffers
    import numpy as np

    return np.frombuffer(view, dtype=np.uint8).ctypes.data


def crc32c(data, seed: int = 0) -> Optional[int]:
    """crc32c via the native lib; None when unavailable."""
    lib = load()
    if lib is None:
        return None
    view = memoryview(data).cast("B")
    if view.nbytes == 0:
        return int(lib.tsnp_crc32c(None, 0, seed))
    return int(lib.tsnp_crc32c(_buffer_address(view), view.nbytes, seed))
