"""Native extension loader: builds fastio.so on first use (g++, cached),
falls back to pure Python silently when no toolchain is available.

Bindings are ctypes (no pybind11 in the image); all entry points release
the GIL for the duration of the syscall chain, so the scheduler's worker
threads overlap I/O properly.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fastio.cpp")
_SO = os.path.join(_HERE, "fastio.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build() -> bool:
    # Compile to a process-unique temp file and os.rename into place:
    # atomic on posix, so concurrent first-use across processes (the
    # multi-process tests spawn several) can never observe a half-written
    # .so — worst case they each build once and the last rename wins.
    tmp = f"{_SO}.tmp.{os.getpid()}"
    # -march=native is a ~25% win for the fused digest loops (the adler
    # closed-form reductions vectorize), but an ISA-specific binary must
    # never outlive its host CPU: the build records the CPU fingerprint
    # next to the .so, and load() discards a cached binary whose
    # fingerprint no longer matches (a copied venv / NFS tree / docker
    # image moved to an older CPU would otherwise SIGILL mid-checkpoint).
    # Hosts where the fingerprint cannot be read get portable flags only.
    fp = _cpu_fingerprint()
    # zlib linkage first (its SIMD crc32 beats our slice-by-8 ~2x);
    # then without, for hosts missing zlib.h/libz
    zflags = (["-DTSNP_USE_ZLIB"], ["-lz"])
    native = (
        [
            (["-march=native", *zflags[0]], zflags[1], fp),
            (["-march=native"], [], fp),
        ]
        if fp
        else []
    )
    portable = [(zflags[0], zflags[1], ""), ([], [], "")]
    # ISA-specific variants exist ONLY when a CPU fingerprint can be
    # recorded; order prefers zlib linkage (its SIMD crc32), then no-zlib
    variants = native[:1] + portable[:1] + native[1:] + portable[1:]
    for extra, libs, build_fp in variants:
        try:
            subprocess.run(
                [
                    "g++",
                    "-O3",
                    *extra,
                    "-shared",
                    "-fPIC",
                    "-o",
                    tmp,
                    _SRC,
                    *libs,
                ],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, _SO)
            try:
                with open(_SO + ".cpu", "w") as f:
                    f.write(build_fp)
            except OSError:
                if build_fp:
                    # an ISA-specific binary without its fingerprint
                    # record would later read as "portable" and SIGILL
                    # on a different CPU — drop it and try the next
                    # (portable) variant instead
                    try:
                        os.remove(_SO)
                    except OSError:
                        pass
                    continue
            return True
        except Exception as e:  # noqa: BLE001
            logger.debug(
                "fastio build failed with %s (%r)", extra or "base flags", e
            )
            try:
                os.remove(tmp)
            except OSError:
                pass
    return False


def _cpu_fingerprint() -> str:
    """Hash of this host's CPU feature flags ('' when undeterminable —
    callers then avoid ISA-specific codegen entirely)."""
    try:
        import hashlib

        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    return hashlib.sha256(
                        " ".join(sorted(line.split(":", 1)[1].split())).encode()
                    ).hexdigest()[:16]
    except OSError:
        pass
    return ""


def _cached_so_usable() -> bool:
    """The on-disk .so is current AND was built for this CPU (or with
    portable flags, recorded as an empty fingerprint)."""
    if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(
        _SRC
    ):
        return False
    try:
        with open(_SO + ".cpu") as f:
            built_for = f.read().strip()
    except OSError:
        # no record: legacy portable build — loadable anywhere
        return True
    return built_for == "" or built_for == _cpu_fingerprint()


def _try_load() -> Optional[ctypes.CDLL]:
    try:
        return ctypes.CDLL(_SO)
    except OSError as e:
        logger.debug("fastio load failed: %r", e)
        return None


def load() -> Optional[ctypes.CDLL]:
    """The fastio library, or None when unavailable."""
    global _lib, _load_attempted
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        lib = None
        if _cached_so_usable():
            lib = _try_load()
        if lib is None:
            # stale, absent, or unloadable (e.g. foreign-platform binary):
            # rebuild once and retry
            if not _build():
                return None
            lib = _try_load()
            if lib is None:
                return None
        lib.tsnp_write_file.restype = ctypes.c_int
        lib.tsnp_write_file.argtypes = [
            ctypes.c_char_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int,
        ]
        lib.tsnp_read_file.restype = ctypes.c_int64
        lib.tsnp_read_file.argtypes = [
            ctypes.c_char_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.tsnp_file_size.restype = ctypes.c_int64
        lib.tsnp_file_size.argtypes = [ctypes.c_char_p]
        lib.tsnp_crc32c.restype = ctypes.c_uint32
        lib.tsnp_crc32c.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_uint32,
        ]
        lib.tsnp_copy_digest.restype = None
        lib.tsnp_copy_digest.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        _lib = lib
        return _lib


def _buffer_address(view: memoryview) -> int:
    # zero-copy pointer even for read-only buffers
    import numpy as np

    return np.frombuffer(view, dtype=np.uint8).ctypes.data


def crc32c(data, seed: int = 0) -> Optional[int]:
    """crc32c via the native lib; None when unavailable."""
    lib = load()
    if lib is None:
        return None
    view = memoryview(data).cast("B")
    if view.nbytes == 0:
        return int(lib.tsnp_crc32c(None, 0, seed))
    return int(lib.tsnp_crc32c(_buffer_address(view), view.nbytes, seed))


def copy_digest(dst, src) -> Optional[tuple]:
    """memcpy ``src`` into ``dst`` (equal-size buffers) while computing
    the zlib (crc32, adler32) of the bytes in the same cache-blocked
    native pass; None when the lib is unavailable (caller falls back to
    a python copy + separate hashing)."""
    lib = load()
    if lib is None:
        return None
    sview = memoryview(src).cast("B")
    dview = memoryview(dst).cast("B")
    if dview.nbytes != sview.nbytes or dview.readonly:
        return None
    if sview.nbytes == 0:
        return (0, 1)
    out = (ctypes.c_uint32 * 2)()
    lib.tsnp_copy_digest(
        _buffer_address(dview), _buffer_address(sview), sview.nbytes, out
    )
    return (int(out[0]), int(out[1]))
