"""Native extension loader: builds fastio.so on first use (g++, cached),
falls back to pure Python silently when no toolchain is available.

Bindings are ctypes (no pybind11 in the image); all entry points release
the GIL for the duration of the syscall chain, so the scheduler's worker
threads overlap I/O properly.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fastio.cpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _so_candidates() -> list:
    """Loadable cache paths, most-preferred first.

    The CPU fingerprint is embedded in the FILENAME, so a native .so and
    its provenance are published by ONE atomic rename — there is no
    companion record that a crash or concurrent builder could leave
    missing/stale (which would let a -march=native binary masquerade as
    portable and SIGILL on an older CPU)."""
    fp = _cpu_fingerprint()
    cands = []
    if fp:
        cands.append(os.path.join(_HERE, f"fastio.{fp}.so"))
    cands.append(os.path.join(_HERE, "fastio.portable.so"))
    return cands


def _build() -> Optional[str]:
    # Compile to a process-unique temp file and os.replace into the
    # fingerprint-named destination: atomic on posix, so concurrent
    # first-use across processes (the multi-process tests spawn several)
    # can never observe a half-written .so or a native .so under the
    # portable name — worst case they each build once, last rename wins.
    tmp = os.path.join(_HERE, f"fastio.so.tmp.{os.getpid()}")
    # -march=native is a ~25% win for the fused digest loops (the adler
    # closed-form reductions vectorize), but an ISA-specific binary must
    # never outlive its host CPU: it is cached under fastio.<fp>.so and
    # only ever loaded by a host with the same CPU-feature fingerprint
    # (a copied venv / NFS tree / docker image moved to an older CPU
    # resolves to a different name and rebuilds).  Hosts where the
    # fingerprint cannot be read get portable flags only.
    fp = _cpu_fingerprint()
    # zlib linkage first (its SIMD crc32 beats our slice-by-8 ~2x);
    # then without, for hosts missing zlib.h/libz
    zflags = (["-DTSNP_USE_ZLIB"], ["-lz"])
    native = (
        [
            (["-march=native", *zflags[0]], zflags[1], fp),
            (["-march=native"], [], fp),
        ]
        if fp
        else []
    )
    portable = [(zflags[0], zflags[1], ""), ([], [], "")]
    # ISA-specific variants exist ONLY when a CPU fingerprint can be
    # recorded; order prefers zlib linkage (its SIMD crc32), then no-zlib
    variants = native[:1] + portable[:1] + native[1:] + portable[1:]
    for extra, libs, build_fp in variants:
        try:
            subprocess.run(
                [
                    "g++",
                    "-O3",
                    *extra,
                    "-shared",
                    "-fPIC",
                    "-o",
                    tmp,
                    _SRC,
                    *libs,
                ],
                check=True,
                capture_output=True,
                timeout=120,
            )
            dest = os.path.join(
                _HERE,
                f"fastio.{build_fp}.so" if build_fp else "fastio.portable.so",
            )
            os.replace(tmp, dest)
            if fp and not build_fp:
                # every native variant failed on a fingerprintable host
                # (e.g. a g++ that rejects -march=native): record that,
                # so later processes accept the cached portable build
                # instead of re-paying the failed native compiles on
                # every startup
                _publish_marker(_no_native_marker(fp))
            return dest
        except Exception as e:  # noqa: BLE001
            logger.debug(
                "fastio build failed with %s (%r)", extra or "base flags", e
            )
            try:
                os.remove(tmp)
            except OSError:
                pass
    return None


def _no_native_marker(fp: str) -> str:
    return os.path.join(_HERE, f"fastio.{fp}.nonative")


def _publish_marker(path: str) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w"):
            pass
        os.replace(tmp, path)
    except OSError:
        pass


def _cpu_fingerprint() -> str:
    """Hash of this host's CPU feature flags ('' when undeterminable —
    callers then avoid ISA-specific codegen entirely)."""
    try:
        import hashlib

        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    return hashlib.sha256(
                        " ".join(sorted(line.split(":", 1)[1].split())).encode()
                    ).hexdigest()[:16]
    except OSError:
        pass
    return ""


def _try_load(path: str) -> Optional[ctypes.CDLL]:
    try:
        return ctypes.CDLL(path)
    except OSError as e:
        logger.debug("fastio load failed for %s: %r", path, e)
        return None


def load() -> Optional[ctypes.CDLL]:
    """The fastio library, or None when unavailable."""
    global _lib, _load_attempted
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True

        def _fresh(path: str) -> bool:
            try:
                return os.path.getmtime(path) >= os.path.getmtime(_SRC)
            except OSError:
                return False

        cands = _so_candidates()
        # Only the PREFERRED (native, when fingerprintable) candidate is
        # accepted from cache: settling for a fresh portable .so while
        # the native one is stale/absent would silently forfeit the
        # -march=native win forever (a successful load skips _build) —
        # UNLESS a fresh .nonative marker records that native compilation
        # already failed for this CPU, in which case the cached portable
        # build is the best achievable and rebuilding every process would
        # just re-pay the failed native compiles.
        lib = _try_load(cands[0]) if _fresh(cands[0]) else None
        if (
            lib is None
            and len(cands) > 1
            and _fresh(_no_native_marker(_cpu_fingerprint()))
            and _fresh(cands[-1])
        ):
            lib = _try_load(cands[-1])
        if lib is None:
            dest = _build()
            lib = _try_load(dest) if dest else None
        if lib is None:
            # no toolchain: any fresh lesser candidate beats the pure-
            # python fallback
            for cand in cands[1:]:
                if _fresh(cand):
                    lib = _try_load(cand)
                    if lib is not None:
                        break
        if lib is None:
            return None
        lib.tsnp_write_file.restype = ctypes.c_int
        lib.tsnp_write_file.argtypes = [
            ctypes.c_char_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int,
        ]
        try:
            # newer symbol: a cached .so from older source that slips
            # past the mtime freshness check (e.g. artifact restores
            # stamping fresh mtimes) must degrade to the unfused path,
            # not crash every native-ext consumer out of load()
            lib.tsnp_write_file_digest.restype = ctypes.c_int
            lib.tsnp_write_file_digest.argtypes = [
                ctypes.c_char_p,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint32),
            ]
        except AttributeError:
            logger.debug("loaded fastio lacks tsnp_write_file_digest")
        lib.tsnp_read_file.restype = ctypes.c_int64
        lib.tsnp_read_file.argtypes = [
            ctypes.c_char_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.tsnp_file_size.restype = ctypes.c_int64
        lib.tsnp_file_size.argtypes = [ctypes.c_char_p]
        lib.tsnp_crc32c.restype = ctypes.c_uint32
        lib.tsnp_crc32c.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_uint32,
        ]
        lib.tsnp_copy_digest.restype = None
        lib.tsnp_copy_digest.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.tsnp_crc32z.restype = ctypes.c_uint32
        lib.tsnp_crc32z.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_uint32,
        ]
        lib.tsnp_adler32.restype = ctypes.c_uint32
        lib.tsnp_adler32.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_uint32,
        ]
        lib.tsnp_digest.restype = None
        lib.tsnp_digest.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        try:
            # newer symbols (the fast-I/O engine, storage/fastio.py):
            # tolerate a cached .so from older source — the engine then
            # reports itself unavailable and the fs plugin keeps the
            # pre-engine native path
            lib.tsnp_part_pwrite.restype = ctypes.c_int
            lib.tsnp_part_pwrite.argtypes = [
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint32),
            ]
            lib.tsnp_part_pread.restype = ctypes.c_int64
            lib.tsnp_part_pread.argtypes = [
                ctypes.c_int,
                ctypes.c_int,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_int64,
            ]
        except AttributeError:
            logger.debug("loaded fastio lacks the part pwrite/pread symbols")
        try:
            # newer symbols (the "huff" block codec): tolerate a cached
            # .so from older source — codec.py then reports huff
            # unavailable instead of crashing every native-ext consumer
            for sym in ("tsnp_huff_compress", "tsnp_huff_decompress"):
                fn = getattr(lib, sym)
                fn.restype = ctypes.c_int64
                fn.argtypes = [
                    ctypes.c_void_p,
                    ctypes.c_int64,
                    ctypes.c_void_p,
                    ctypes.c_int64,
                ]
            for sym in ("tsnp_byte_shuffle", "tsnp_byte_unshuffle"):
                fn = getattr(lib, sym)
                fn.restype = None
                fn.argtypes = [
                    ctypes.c_void_p,
                    ctypes.c_int64,
                    ctypes.c_int64,
                    ctypes.c_void_p,
                ]
        except AttributeError:
            logger.debug("loaded fastio lacks the huff codec symbols")
        _lib = lib
        return _lib


def _buffer_address(view: memoryview) -> int:
    # zero-copy pointer even for read-only buffers
    import numpy as np

    return np.frombuffer(view, dtype=np.uint8).ctypes.data


def crc32c(data, seed: int = 0) -> Optional[int]:
    """crc32c via the native lib; None when unavailable."""
    lib = load()
    if lib is None:
        return None
    view = memoryview(data).cast("B")
    if view.nbytes == 0:
        return int(lib.tsnp_crc32c(None, 0, seed))
    return int(lib.tsnp_crc32c(_buffer_address(view), view.nbytes, seed))


def crc32z(data, seed: int = 0) -> Optional[int]:
    """zlib-polynomial crc32 (bit-compatible with zlib.crc32) via the
    native PCLMUL path; None when the lib is unavailable."""
    lib = load()
    if lib is None:
        return None
    view = memoryview(data).cast("B")
    if view.nbytes == 0:
        return seed
    return int(lib.tsnp_crc32z(_buffer_address(view), view.nbytes, seed))


def adler32(data, seed: int = 1) -> Optional[int]:
    """adler32 (bit-compatible with zlib.adler32) via the native AVX2
    path; None when the lib is unavailable."""
    lib = load()
    if lib is None:
        return None
    view = memoryview(data).cast("B")
    if view.nbytes == 0:
        return seed
    return int(lib.tsnp_adler32(_buffer_address(view), view.nbytes, seed))


def digest(data) -> Optional[tuple]:
    """(crc32, adler32) of ``data`` in one native call (no copy); None
    when the lib is unavailable."""
    lib = load()
    if lib is None:
        return None
    view = memoryview(data).cast("B")
    if view.nbytes == 0:
        return (0, 1)
    out = (ctypes.c_uint32 * 2)()
    lib.tsnp_digest(_buffer_address(view), view.nbytes, out)
    return (int(out[0]), int(out[1]))


def byte_shuffle(data, stride: int, inverse: bool = False):
    """Byte-shuffle (or unshuffle) ``data`` with the native cache-blocked
    transpose — GIL-free, one pass, no intermediate copy; None when the
    native lib (or its shuffle symbols) is unavailable."""
    import numpy as np

    lib = load()
    if lib is None or not hasattr(lib, "tsnp_byte_shuffle"):
        return None
    view = memoryview(data).cast("B")
    out = np.empty(view.nbytes, dtype=np.uint8)
    fn = lib.tsnp_byte_unshuffle if inverse else lib.tsnp_byte_shuffle
    fn(_buffer_address(view), view.nbytes, stride, out.ctypes.data)
    return out


def huff_available() -> bool:
    """True when the loaded native lib carries the huff codec symbols."""
    lib = load()
    return lib is not None and hasattr(lib, "tsnp_huff_compress")


def huff_compress(data, headroom: int = 0):
    """Compress ``data`` with the native block-Huffman coder; None when
    the native lib (or its huff symbols) is unavailable.  The returned
    stream may exceed the input by ~5 bytes per 128KB block on
    incompressible data (raw-mode blocks) — codec.py's min-ratio check
    handles store-raw fallback above this layer.

    ``headroom``: reserve that many writable bytes BEFORE the stream
    and return a uint8 array of headroom+stream (codec.py packs the
    frame header into the reservation) — the stream is produced exactly
    once, in place; with headroom=0 plain bytes are returned."""
    import numpy as np

    lib = load()
    if lib is None or not hasattr(lib, "tsnp_huff_compress"):
        return None
    view = memoryview(data).cast("B")
    if view.nbytes == 0:
        return np.empty(headroom, dtype=np.uint8) if headroom else b""
    cap = view.nbytes + view.nbytes // 64 + 4096
    out = np.empty(headroom + cap, dtype=np.uint8)
    rc = lib.tsnp_huff_compress(
        _buffer_address(view), view.nbytes,
        out.ctypes.data + headroom, cap,
    )
    if rc < 0:  # cap is sized so this cannot happen; guard anyway
        return None
    if headroom:
        ret = out[: headroom + rc]
        # a slice view pins the whole raw-sized capacity allocation for
        # as long as the frame lives (through the write queue) — the
        # stripe engine's byte-gate credits the saved bytes as freed, so
        # they must actually free: shrink-copy when compression saved
        # enough to matter
        if out.nbytes - ret.nbytes > (1 << 20):
            ret = ret.copy()
        return ret
    return out[:rc].tobytes()


def huff_decompress(data, raw_len: int):
    """Decompress a huff stream to exactly ``raw_len`` bytes (bytes-like
    uint8 array — no trailing tobytes copy on the restore hot path);
    None when the native lib is unavailable; ValueError on malformed
    input."""
    import numpy as np

    lib = load()
    if lib is None or not hasattr(lib, "tsnp_huff_decompress"):
        return None
    view = memoryview(data).cast("B")
    if raw_len == 0 and view.nbytes == 0:
        return b""
    out = np.empty(raw_len, dtype=np.uint8)
    rc = lib.tsnp_huff_decompress(
        _buffer_address(view), view.nbytes, out.ctypes.data, raw_len
    )
    if rc != raw_len:
        raise ValueError(
            f"corrupt huff stream: decoded {rc} of {raw_len} expected bytes"
        )
    return out


def copy_digest(dst, src) -> Optional[tuple]:
    """memcpy ``src`` into ``dst`` (equal-size buffers) while computing
    the zlib (crc32, adler32) of the bytes in the same cache-blocked
    native pass; None when the lib is unavailable (caller falls back to
    a python copy + separate hashing)."""
    lib = load()
    if lib is None:
        return None
    sview = memoryview(src).cast("B")
    dview = memoryview(dst).cast("B")
    if dview.nbytes != sview.nbytes or dview.readonly:
        return None
    if sview.nbytes == 0:
        return (0, 1)
    out = (ctypes.c_uint32 * 2)()
    lib.tsnp_copy_digest(
        _buffer_address(dview), _buffer_address(sview), sview.nbytes, out
    )
    return (int(out[0]), int(out[1]))
