// Native file-I/O engine for the fs storage plugin.
//
// The reference delegates its native needs to PyTorch's C++ (TCPStore, CUDA
// copies — SURVEY §2.9); this repo's runtime equivalent is this small
// library: single-syscall-chain file writes/reads that run entirely outside
// the GIL (called via ctypes from scheduler worker threads), plus a
// slice-by-8 crc32c for blob integrity.
//
// Build: g++ -O3 -shared -fPIC -o fastio.so fastio.cpp  (see build_ext.py)

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <unistd.h>

// When the build links libz (-DTSNP_USE_ZLIB -lz), the fused digest
// defers to its crc32/adler32 — system zlib ships SIMD (PCLMUL) crc on
// most distros, ~2x this file's slice-by-8.  The table implementations
// below remain the no-zlib fallback.
#if defined(TSNP_USE_ZLIB)
#include <zlib.h>
#endif

// ISA fast paths: compile-time guards are safe here because the build
// uses -march=native and caches the .so under a CPU-feature fingerprint
// (_csrc/__init__.py) — a binary can never run on a host older than the
// one that compiled it.
#if defined(__PCLMUL__) && defined(__SSE4_1__)
#define TSNP_HAVE_CLMUL 1
#endif
#if defined(__AVX2__)
#define TSNP_HAVE_AVX2 1
#endif
#if defined(TSNP_HAVE_CLMUL) || defined(TSNP_HAVE_AVX2)
#include <immintrin.h>
#endif

extern "C" {

// Write buf[0:size] to path (create/truncate). Returns 0 on success,
// -errno on failure. fsync_mode: 0 = none (page-cache, benchmark mode),
// 1 = fdatasync before close (durability).
int tsnp_write_file(const char *path, const void *buf, int64_t size,
                    int fsync_mode) {
  int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC, 0644);
  if (fd < 0)
    return -errno;
  const char *p = static_cast<const char *>(buf);
  int64_t remaining = size;
  while (remaining > 0) {
    ssize_t n = write(fd, p, static_cast<size_t>(remaining));
    if (n < 0) {
      if (errno == EINTR)
        continue;
      int err = errno;
      close(fd);
      return -err;
    }
    p += n;
    remaining -= n;
  }
  int rc = 0;
  if (fsync_mode == 1 && fdatasync(fd) != 0)
    rc = -errno;
  if (close(fd) != 0 && rc == 0)
    rc = -errno;
  return rc;
}

// tsnp_write_file, fused with the zlib (crc32, adler32) digest of the
// written bytes: each 256KB block is digested while cache-hot from the
// same pass that hands it to write(), so a checksummed direct write
// touches the staged buffer ONCE instead of digest-pass + write-pass.
// out[0] = crc32, out[1] = adler32.  Declared after the digest helpers;
// defined at the bottom of this file.
int tsnp_write_file_digest(const char *path, const void *buf, int64_t size,
                           int fsync_mode, uint32_t *out);

// Read length bytes at offset from path into buf. offset<0 means 0;
// length<0 means "to EOF" (caller must size buf via tsnp_file_size).
// Returns bytes read, or -errno.
int64_t tsnp_read_file(const char *path, void *buf, int64_t offset,
                       int64_t length) {
  int fd = open(path, O_RDONLY | O_CLOEXEC);
  if (fd < 0)
    return -errno;
  if (offset > 0 && lseek(fd, static_cast<off_t>(offset), SEEK_SET) < 0) {
    int err = errno;
    close(fd);
    return -err;
  }
  char *p = static_cast<char *>(buf);
  int64_t total = 0;
  while (length < 0 || total < length) {
    size_t want = length < 0 ? (1u << 20) : static_cast<size_t>(length - total);
    if (want > (1u << 20))
      want = 1u << 20;
    ssize_t n = read(fd, p + total, want);
    if (n < 0) {
      if (errno == EINTR)
        continue;
      int err = errno;
      close(fd);
      return -err;
    }
    if (n == 0)
      break;
    total += n;
  }
  close(fd);
  return total;
}

int64_t tsnp_file_size(const char *path) {
  struct stat st;
  if (stat(path, &st) != 0)
    return -errno;
  return static_cast<int64_t>(st.st_size);
}

// slice-by-8 table construction, shared by the crc32c (Castagnoli) and
// zlib-crc32 variants below.
static void init_slice8_tables(uint32_t poly, uint32_t table[8][256]) {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++)
      crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    table[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = table[0][i];
    for (int s = 1; s < 8; s++) {
      crc = table[0][crc & 0xff] ^ (crc >> 8);
      table[s][i] = crc;
    }
  }
}

// The word-at-a-time slice-by-8 folds `crc ^= (uint32_t)chunk` on a
// memcpy'd 8-byte word, which is only correct when the low word holds
// the FIRST four bytes — i.e. on little-endian hosts.  Big-endian hosts
// take the (correct, slower) bytewise loops instead of silently
// recording wrong checksums into manifests.
#if defined(__BYTE_ORDER__) && (__BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__)
#define TSNP_LITTLE_ENDIAN 1
#else
#define TSNP_LITTLE_ENDIAN 0
#endif

// crc32c (Castagnoli), slice-by-8.
static uint32_t crc32c_table[8][256];
// zlib-polynomial crc32 (0xEDB88320), slice-by-8 — bit-compatible with
// python's zlib.crc32 (manifest checksums use that polynomial; crc32c
// above is only for fs write verification).
static uint32_t crc32z_table[8][256];

// Eager init at library load: tsnp_crc32c / tsnp_copy_digest are called
// concurrently from executor threads with the GIL released, so a lazy
// check-then-init would be a data race (a thread could read a
// partially-built higher slice).
__attribute__((constructor)) static void tsnp_init_crc_tables() {
  init_slice8_tables(0x82f63b78u, crc32c_table);
  init_slice8_tables(0xEDB88320u, crc32z_table);
}

// ---------------------------------------------------------------- zlib crc32
// Internal state convention: "state" is the inverted running register
// (zlib value v == ~state); callers convert at the boundary.

static uint32_t crc32z_slice8(uint32_t state, const uint8_t *s, int64_t n) {
  uint32_t crc = state;
#if TSNP_LITTLE_ENDIAN
  while (n >= 8) {
    uint64_t chunk;
    memcpy(&chunk, s, 8);
    crc ^= static_cast<uint32_t>(chunk);
    uint32_t hi = static_cast<uint32_t>(chunk >> 32);
    crc = crc32z_table[7][crc & 0xff] ^ crc32z_table[6][(crc >> 8) & 0xff] ^
          crc32z_table[5][(crc >> 16) & 0xff] ^ crc32z_table[4][crc >> 24] ^
          crc32z_table[3][hi & 0xff] ^ crc32z_table[2][(hi >> 8) & 0xff] ^
          crc32z_table[1][(hi >> 16) & 0xff] ^ crc32z_table[0][hi >> 24];
    s += 8;
    n -= 8;
  }
#endif
  while (n > 0) {
    crc = crc32z_table[0][(crc ^ *s) & 0xff] ^ (crc >> 8);
    s++;
    n--;
  }
  return crc;
}

#if defined(TSNP_HAVE_CLMUL)
// PCLMUL fold-by-4 for the reflected 0xEDB88320 polynomial (the classic
// Gopal/Intel construction; constants are the standard IEEE-crc32 fold
// multipliers).  Processes len bytes (len >= 64, len % 16 == 0) against
// the inverted running state; returns the new inverted state.
static uint32_t crc32z_clmul(uint32_t state, const uint8_t *buf,
                             int64_t len) {
  // _mm_set_epi64x takes (high, low): low qword folds pair with imm
  // 0x00, high with 0x11 — k1/k3 are the low-qword multipliers
  const __m128i k1k2 = _mm_set_epi64x(0x01c6e41596, 0x0154442bd4);
  const __m128i k3k4 = _mm_set_epi64x(0x00ccaa009e, 0x01751997d0);
  const __m128i k5k0 = _mm_set_epi64x(0x0000000000, 0x0163cd6124);
  const __m128i poly = _mm_set_epi64x(0x01f7011641, 0x01db710641);
  __m128i x1 = _mm_loadu_si128(reinterpret_cast<const __m128i *>(buf));
  __m128i x2 = _mm_loadu_si128(reinterpret_cast<const __m128i *>(buf + 16));
  __m128i x3 = _mm_loadu_si128(reinterpret_cast<const __m128i *>(buf + 32));
  __m128i x4 = _mm_loadu_si128(reinterpret_cast<const __m128i *>(buf + 48));
  x1 = _mm_xor_si128(x1, _mm_cvtsi32_si128(static_cast<int>(state)));
  buf += 64;
  len -= 64;
  while (len >= 64) {
    __m128i x5 = _mm_clmulepi64_si128(x1, k1k2, 0x00);
    __m128i x6 = _mm_clmulepi64_si128(x2, k1k2, 0x00);
    __m128i x7 = _mm_clmulepi64_si128(x3, k1k2, 0x00);
    __m128i x8 = _mm_clmulepi64_si128(x4, k1k2, 0x00);
    x1 = _mm_clmulepi64_si128(x1, k1k2, 0x11);
    x2 = _mm_clmulepi64_si128(x2, k1k2, 0x11);
    x3 = _mm_clmulepi64_si128(x3, k1k2, 0x11);
    x4 = _mm_clmulepi64_si128(x4, k1k2, 0x11);
    x1 = _mm_xor_si128(_mm_xor_si128(x1, x5),
                       _mm_loadu_si128(reinterpret_cast<const __m128i *>(buf)));
    x2 = _mm_xor_si128(_mm_xor_si128(x2, x6),
                       _mm_loadu_si128(reinterpret_cast<const __m128i *>(buf + 16)));
    x3 = _mm_xor_si128(_mm_xor_si128(x3, x7),
                       _mm_loadu_si128(reinterpret_cast<const __m128i *>(buf + 32)));
    x4 = _mm_xor_si128(_mm_xor_si128(x4, x8),
                       _mm_loadu_si128(reinterpret_cast<const __m128i *>(buf + 48)));
    buf += 64;
    len -= 64;
  }
  // fold the four accumulators into one
  __m128i x5 = _mm_clmulepi64_si128(x1, k3k4, 0x00);
  x1 = _mm_clmulepi64_si128(x1, k3k4, 0x11);
  x1 = _mm_xor_si128(_mm_xor_si128(x1, x5), x2);
  x5 = _mm_clmulepi64_si128(x1, k3k4, 0x00);
  x1 = _mm_clmulepi64_si128(x1, k3k4, 0x11);
  x1 = _mm_xor_si128(_mm_xor_si128(x1, x5), x3);
  x5 = _mm_clmulepi64_si128(x1, k3k4, 0x00);
  x1 = _mm_clmulepi64_si128(x1, k3k4, 0x11);
  x1 = _mm_xor_si128(_mm_xor_si128(x1, x5), x4);
  // remaining whole 16-byte blocks
  while (len >= 16) {
    x5 = _mm_clmulepi64_si128(x1, k3k4, 0x00);
    x1 = _mm_clmulepi64_si128(x1, k3k4, 0x11);
    x1 = _mm_xor_si128(_mm_xor_si128(x1, x5),
                       _mm_loadu_si128(reinterpret_cast<const __m128i *>(buf)));
    buf += 16;
    len -= 16;
  }
  // fold 128 -> 64 bits
  const __m128i mask32 = _mm_setr_epi32(~0, 0, ~0, 0);
  __m128i x0 = _mm_clmulepi64_si128(x1, k3k4, 0x10);
  x1 = _mm_srli_si128(x1, 8);
  x1 = _mm_xor_si128(x1, x0);
  x0 = _mm_srli_si128(x1, 4);
  x1 = _mm_and_si128(x1, mask32);
  x1 = _mm_clmulepi64_si128(x1, k5k0, 0x00);
  x1 = _mm_xor_si128(x1, x0);
  // Barrett reduction 64 -> 32 bits
  x0 = _mm_and_si128(x1, mask32);
  x0 = _mm_clmulepi64_si128(x0, poly, 0x10);
  x0 = _mm_and_si128(x0, mask32);
  x0 = _mm_clmulepi64_si128(x0, poly, 0x00);
  x1 = _mm_xor_si128(x1, x0);
  return static_cast<uint32_t>(_mm_extract_epi32(x1, 1));
}
#endif  // TSNP_HAVE_CLMUL

// zlib-value-convention running update: v' = update(v, bytes); matches
// python zlib.crc32(bytes, v).
static uint32_t crc32z_update(uint32_t v, const uint8_t *s, int64_t n) {
  if (n <= 0)
    return v;
  uint32_t state = ~v;
#if defined(TSNP_HAVE_CLMUL)
  if (n >= 64) {
    int64_t simd = n & ~static_cast<int64_t>(15);
    state = crc32z_clmul(state, s, simd);
    s += simd;
    n -= simd;
  }
#elif defined(TSNP_USE_ZLIB)
  // system zlib's crc32 is SIMD on most distros — use it when our own
  // PCLMUL path wasn't compiled in.  Chunked: zlib takes uInt lengths,
  // and an unchunked cast would silently truncate >=4GiB buffers.
  while (n > 0) {
    int64_t blk = n > (1 << 30) ? (1 << 30) : n;
    v = static_cast<uint32_t>(
        crc32(static_cast<uLong>(v), s, static_cast<uInt>(blk)));
    s += blk;
    n -= blk;
  }
  return v;
#endif
  state = crc32z_slice8(state, s, n);
  return ~state;
}

// ---------------------------------------------------------------- adler32

#if defined(TSNP_HAVE_AVX2)
// AVX2 adler32: per 32-byte chunk c (local byte offset 32*c) keep three
// exact vector accumulators —
//   acc_cs  += chunk byte sums            (for S1)
//   acc_ccs += c * chunk byte sums        (for the 32*sum(c*cs) term)
//   acc_w   += sum_j j*s_j within chunk   (maddubs against 0..31)
// — then close each <=4096-byte window with the same closed form the
// scalar path uses: S2 = 32*sum(c*cs) + W, b' = b + m*a + m*S1 - S2.
// All lanes stay far from overflow (cs<=2040/lane, c<128, W-lane <=
// 31110 per chunk * 128 chunks).
static void adler32_avx2_window(const uint8_t *s, int64_t m, uint32_t *pa,
                                uint32_t *pb) {
  const __m256i zero = _mm256_setzero_si256();
  const __m256i jw = _mm256_setr_epi8(0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11,
                                      12, 13, 14, 15, 16, 17, 18, 19, 20, 21,
                                      22, 23, 24, 25, 26, 27, 28, 29, 30, 31);
  const __m256i ones16 = _mm256_set1_epi16(1);
  const uint32_t MOD = 65521u;
  __m256i acc_cs = zero, acc_ccs = zero, acc_w = zero;
  int64_t chunks = m / 32;
  for (int64_t c = 0; c < chunks; c++) {
    __m256i bytes =
        _mm256_loadu_si256(reinterpret_cast<const __m256i *>(s + c * 32));
    __m256i cs = _mm256_sad_epu8(bytes, zero);  // 4 x u64 partial sums
    acc_cs = _mm256_add_epi64(acc_cs, cs);
    acc_ccs = _mm256_add_epi64(
        acc_ccs, _mm256_mul_epu32(cs, _mm256_set1_epi32(static_cast<int>(c))));
    __m256i w16 = _mm256_maddubs_epi16(bytes, jw);  // u8 * s8 pairs -> s16
    acc_w = _mm256_add_epi32(acc_w, _mm256_madd_epi16(w16, ones16));
  }
  // horizontal sums
  uint64_t cs_l[4], ccs_l[4];
  uint32_t w_l[8];
  _mm256_storeu_si256(reinterpret_cast<__m256i *>(cs_l), acc_cs);
  _mm256_storeu_si256(reinterpret_cast<__m256i *>(ccs_l), acc_ccs);
  _mm256_storeu_si256(reinterpret_cast<__m256i *>(w_l), acc_w);
  uint64_t S1v = cs_l[0] + cs_l[1] + cs_l[2] + cs_l[3];
  uint64_t CCS = ccs_l[0] + ccs_l[1] + ccs_l[2] + ccs_l[3];
  uint64_t W = 0;
  for (int i = 0; i < 8; i++)
    W += w_l[i];
  int64_t done = chunks * 32;
  uint64_t S1 = S1v, S2 = 32u * CCS + W;
  // scalar tail of the window
  for (int64_t k = done; k < m; k++) {
    S1 += s[k];
    S2 += static_cast<uint64_t>(k) * s[k];
  }
  uint64_t a = *pa, b = *pb;
  uint64_t mm = static_cast<uint64_t>(m);
  uint64_t bb = b + mm * a + mm * S1 - S2;
  *pa = static_cast<uint32_t>((a + S1) % MOD);
  *pb = static_cast<uint32_t>(bb % MOD);
}
#endif  // TSNP_HAVE_AVX2

static uint32_t adler32_update(uint32_t adler, const uint8_t *s, int64_t n) {
  if (n <= 0)
    return adler;
#if defined(TSNP_HAVE_AVX2)
  uint32_t a = adler & 0xffff, b = (adler >> 16) & 0xffff;
  while (n > 0) {
    int64_t m = n > 4096 ? 4096 : n;
    adler32_avx2_window(s, m, &a, &b);
    s += m;
    n -= m;
  }
  return (b << 16) | a;
#elif defined(TSNP_USE_ZLIB)
  // chunked for the same uInt-truncation reason as crc32z_update
  while (n > 0) {
    int64_t blk = n > (1 << 30) ? (1 << 30) : n;
    adler = static_cast<uint32_t>(
        adler32(static_cast<uLong>(adler), s, static_cast<uInt>(blk)));
    s += blk;
    n -= blk;
  }
  return adler;
#else
  const uint32_t MOD = 65521u;
  uint32_t a = adler & 0xffff, b = (adler >> 16) & 0xffff;
  while (n > 0) {
    int64_t m = n > 5552 ? 5552 : n;
    uint64_t s1 = 0, s2 = 0;
    for (int64_t k = 0; k < m; k++) {
      s1 += s[k];
      s2 += static_cast<uint64_t>(k) * s[k];
    }
    uint64_t mm = static_cast<uint64_t>(m);
    uint64_t bb = b + mm * a + mm * s1 - s2;
    a = static_cast<uint32_t>((a + s1) % MOD);
    b = static_cast<uint32_t>(bb % MOD);
    s += m;
    n -= m;
  }
  return (b << 16) | a;
#endif
}

uint32_t tsnp_crc32c(const void *buf, int64_t size, uint32_t seed) {
  uint32_t crc = ~seed;
  const uint8_t *p = static_cast<const uint8_t *>(buf);
#if TSNP_LITTLE_ENDIAN
  while (size >= 8) {
    uint64_t chunk;
    memcpy(&chunk, p, 8);
    crc ^= static_cast<uint32_t>(chunk);
    uint32_t hi = static_cast<uint32_t>(chunk >> 32);
    crc = crc32c_table[7][crc & 0xff] ^ crc32c_table[6][(crc >> 8) & 0xff] ^
          crc32c_table[5][(crc >> 16) & 0xff] ^ crc32c_table[4][crc >> 24] ^
          crc32c_table[3][hi & 0xff] ^ crc32c_table[2][(hi >> 8) & 0xff] ^
          crc32c_table[1][(hi >> 16) & 0xff] ^ crc32c_table[0][hi >> 24];
    p += 8;
    size -= 8;
  }
#endif
  while (size > 0) {
    crc = crc32c_table[0][(crc ^ *p) & 0xff] ^ (crc >> 8);
    p++;
    size--;
  }
  return ~crc;
}

// Running zlib-polynomial crc32, bit-compatible with python's
// zlib.crc32(data, seed).  PCLMUL fold-by-4 when compiled in, else
// system zlib (SIMD on most distros), else slice-by-8.
uint32_t tsnp_crc32z(const void *buf, int64_t size, uint32_t seed) {
  return crc32z_update(seed, static_cast<const uint8_t *>(buf), size);
}

// Running adler32, bit-compatible with python's zlib.adler32(data, seed).
uint32_t tsnp_adler32(const void *buf, int64_t size, uint32_t seed) {
  return adler32_update(seed, static_cast<const uint8_t *>(buf), size);
}

// (crc32, adler32) of a buffer WITHOUT copying — the direct
// (non-slabbed) write path digests the staged bytes in place.
// Interleaved per 256KB block so the adler pass hits cache instead of
// re-reading DRAM (same structure as tsnp_copy_digest).  Runs entirely
// outside the GIL (ctypes).
void tsnp_digest(const void *src, int64_t size, uint32_t *out) {
  const uint8_t *p = static_cast<const uint8_t *>(src);
  uint32_t crc = 0, adl = 1;
  int64_t off = 0;
  while (off < size) {
    int64_t blk = size - off;
    if (blk > 262144)
      blk = 262144;
    crc = crc32z_update(crc, p + off, blk);
    adl = adler32_update(adl, p + off, blk);
    off += blk;
  }
  out[0] = crc;
  out[1] = adl;
}

int tsnp_write_file_digest(const char *path, const void *buf, int64_t size,
                           int fsync_mode, uint32_t *out) {
  int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC, 0644);
  if (fd < 0)
    return -errno;
  const uint8_t *p = static_cast<const uint8_t *>(buf);
  uint32_t crc = 0, adl = 1;
  int64_t remaining = size;
  while (remaining > 0) {
    int64_t blk = remaining > 262144 ? 262144 : remaining;
    // digest first (pulls the block into cache), then write() (the
    // kernel's copy reads it back out of cache)
    crc = crc32z_update(crc, p, blk);
    adl = adler32_update(adl, p, blk);
    int64_t off = 0;
    while (off < blk) {
      ssize_t n = write(fd, p + off, static_cast<size_t>(blk - off));
      if (n < 0) {
        if (errno == EINTR)
          continue;
        int err = errno;
        close(fd);
        return -err;
      }
      off += n;
    }
    p += blk;
    remaining -= blk;
  }
  out[0] = crc;
  out[1] = adl;
  int rc = 0;
  if (fsync_mode == 1 && fdatasync(fd) != 0)
    rc = -errno;
  if (close(fd) != 0 && rc == 0)
    rc = -errno;
  return rc;
}

// ------------------------------------------------------- fast-I/O engine
// Part-granular pwrite/pread entry points for storage/fastio.py: one
// ctypes call per part, entirely outside the GIL, with the (crc32,
// adler32) digest fused into the same pass that moves the bytes and
// O_DIRECT alignment owned HERE (the Python layer never does sector
// math).  See docs/fastio.md for the fallback ladder.

static int pwrite_full(int fd, const void *p, int64_t n, int64_t off) {
  const char *s = static_cast<const char *>(p);
  while (n > 0) {
    ssize_t w = pwrite(fd, s, static_cast<size_t>(n), static_cast<off_t>(off));
    if (w < 0) {
      if (errno == EINTR)
        continue;
      return -errno;
    }
    s += w;
    off += w;
    n -= w;
  }
  return 0;
}

static int64_t pread_full(int fd, void *p, int64_t n, int64_t off) {
  char *d = static_cast<char *>(p);
  int64_t got = 0;
  while (got < n) {
    ssize_t r = pread(fd, d + got, static_cast<size_t>(n - got),
                      static_cast<off_t>(off + got));
    if (r < 0) {
      if (errno == EINTR)
        continue;
      return -static_cast<int64_t>(errno);
    }
    if (r == 0)
      break;  // EOF: short read, caller surfaces it
    got += r;
  }
  return got;
}

// Buffered digesting positional write: each 256KB block is digested
// while cache-hot, but the write syscalls batch 64 blocks into ONE
// pwritev (16MB per syscall) — the per-block write(2) chain of
// tsnp_write_file_digest costs a syscall per 256KB, which at local-NVMe
// rates is measurable pure overhead.
static int pwrite_digest_stream(int fd, const uint8_t *p, int64_t n,
                                int64_t off, int want, uint32_t *crc,
                                uint32_t *adl) {
  enum { BLK = 262144, NIOV = 64 };
  struct iovec iov[NIOV];
  while (n > 0) {
    int cnt = 0;
    int64_t batch = 0;
    while (n > 0 && cnt < NIOV) {
      int64_t blk = n > BLK ? BLK : n;
      if (want) {
        *crc = crc32z_update(*crc, p, blk);
        *adl = adler32_update(*adl, p, blk);
      }
      iov[cnt].iov_base = const_cast<uint8_t *>(p);
      iov[cnt].iov_len = static_cast<size_t>(blk);
      cnt++;
      batch += blk;
      p += blk;
      n -= blk;
    }
    int64_t done = 0;
    int idx = 0;
    while (done < batch) {
      ssize_t w = pwritev(fd, iov + idx, cnt - idx,
                          static_cast<off_t>(off + done));
      if (w < 0) {
        if (errno == EINTR)
          continue;
        return -errno;
      }
      done += w;
      // advance the iovec cursor past the consumed bytes (a partial
      // pwritev may stop mid-iovec)
      while (idx < cnt && w >= static_cast<ssize_t>(iov[idx].iov_len)) {
        w -= static_cast<ssize_t>(iov[idx].iov_len);
        idx++;
      }
      if (idx < cnt && w > 0) {
        iov[idx].iov_base = static_cast<char *>(iov[idx].iov_base) + w;
        iov[idx].iov_len -= static_cast<size_t>(w);
      }
    }
    off += batch;
  }
  return 0;
}

// Write src[0:size] at byte `offset` of an already-open file, fusing
// the zlib (crc32, adler32) of src into the same pass when
// want_digest (out[0]=crc32, out[1]=adler32).
//
// fd_direct >= 0 selects the O_DIRECT split: the sub-sector head
// ([offset, align_up(offset))) and tail ([align_down(end), end)) go
// buffered through fd, while the aligned body is copied through the
// caller's `bounce` buffer (alignment-satisfying, bounce_cap an align
// multiple) in one fused copy+digest pass and pwritten via fd_direct —
// sector-aligned offset, length, and memory, as O_DIRECT requires.
// The head/tail/body file ranges are disjoint, so mixing the two fds
// on one file is coherent.  fd_direct < 0 writes everything buffered
// via the pwritev-batched digesting stream.  Returns 0 or -errno.
int tsnp_part_pwrite(int fd, int fd_direct, const void *src, int64_t size,
                     int64_t offset, int64_t align, void *bounce,
                     int64_t bounce_cap, int want_digest, uint32_t *out) {
  const uint8_t *p = static_cast<const uint8_t *>(src);
  uint32_t crc = 0, adl = 1;
  int rc;
  if (size > 0 && fd_direct >= 0 && align > 0 && bounce != nullptr &&
      bounce_cap >= align) {
    int64_t end = offset + size;
    int64_t head_end = (offset + align - 1) / align * align;
    if (head_end > end)
      head_end = end;
    int64_t body_end = end / align * align;
    if (body_end < head_end)
      body_end = head_end;  // span too small to hold an aligned body
    int64_t head = head_end - offset;
    if (head > 0) {
      if (want_digest) {
        crc = crc32z_update(crc, p, head);
        adl = adler32_update(adl, p, head);
      }
      if ((rc = pwrite_full(fd, p, head, offset)) != 0)
        return rc;
    }
    const uint8_t *q = p + head;
    int64_t body = body_end - head_end;
    int64_t cur = head_end;
    while (body > 0) {
      int64_t blk = body > bounce_cap ? bounce_cap : body;
      // fused copy+digest into the aligned bounce, 256KB sub-blocks so
      // the digest runs on cache-hot bytes (same structure as
      // tsnp_copy_digest)
      int64_t o = 0;
      while (o < blk) {
        int64_t sb = blk - o > 262144 ? 262144 : blk - o;
        memcpy(static_cast<uint8_t *>(bounce) + o, q + o,
               static_cast<size_t>(sb));
        if (want_digest) {
          crc = crc32z_update(crc, q + o, sb);
          adl = adler32_update(adl, q + o, sb);
        }
        o += sb;
      }
      if ((rc = pwrite_full(fd_direct, bounce, blk, cur)) != 0)
        return rc;
      q += blk;
      cur += blk;
      body -= blk;
    }
    int64_t tail = end - body_end;
    if (tail > 0) {
      if (want_digest) {
        crc = crc32z_update(crc, q, tail);
        adl = adler32_update(adl, q, tail);
      }
      if ((rc = pwrite_full(fd, q, tail, body_end)) != 0)
        return rc;
    }
  } else if (size > 0) {
    if ((rc = pwrite_digest_stream(fd, p, size, offset, want_digest, &crc,
                                   &adl)) != 0)
      return rc;
  }
  if (want_digest) {
    out[0] = crc;
    out[1] = adl;
  }
  return 0;
}

// Read `size` bytes at `offset` into dst.  fd_direct >= 0 reads the
// aligned body via O_DIRECT into the caller's bounce buffer (then one
// memcpy to dst — the copy is the price of page-cache bypass; dst is
// arbitrary caller memory) with the sub-sector head/tail read buffered
// through fd; fd_direct < 0 reads everything buffered straight into
// dst.  Returns bytes read (short only at EOF), or -errno.
int64_t tsnp_part_pread(int fd, int fd_direct, void *dst, int64_t size,
                        int64_t offset, int64_t align, void *bounce,
                        int64_t bounce_cap) {
  uint8_t *d = static_cast<uint8_t *>(dst);
  if (size <= 0)
    return 0;
  if (fd_direct < 0 || align <= 0 || bounce == nullptr ||
      bounce_cap < align)
    return pread_full(fd, d, size, offset);
  int64_t end = offset + size;
  int64_t head_end = (offset + align - 1) / align * align;
  if (head_end > end)
    head_end = end;
  int64_t body_end = end / align * align;
  if (body_end < head_end)
    body_end = head_end;
  int64_t total = 0;
  int64_t head = head_end - offset;
  if (head > 0) {
    int64_t n = pread_full(fd, d, head, offset);
    if (n < 0)
      return n;
    total += n;
    if (n < head)
      return total;  // EOF inside the head
  }
  int64_t body = body_end - head_end;
  int64_t cur = head_end;
  while (body > 0) {
    int64_t blk = body > bounce_cap ? bounce_cap : body;
    int64_t n = pread_full(fd_direct, bounce, blk, cur);
    if (n < 0)
      return n;
    if (n > 0)
      memcpy(d + (cur - offset), bounce, static_cast<size_t>(n));
    total += n;
    if (n < blk)
      return total;  // EOF inside the body
    cur += blk;
    body -= blk;
  }
  int64_t tail = end - body_end;
  if (tail > 0) {
    int64_t n = pread_full(fd, d + (body_end - offset), tail, body_end);
    if (n < 0)
      return n;
    total += n;
  }
  return total;
}

// memcpy src -> dst while computing zlib crc32 AND adler32 of the bytes,
// processed in 256KB blocks so each block is digested while still hot in
// cache: memory traffic is one read + one write instead of the three
// read passes of copy-then-crc-then-adler.  out[0] = crc32 (zlib
// finalized), out[1] = adler32.  Runs entirely outside the GIL (ctypes).
void tsnp_copy_digest(void *dst, const void *src, int64_t size,
                      uint32_t *out) {
  const uint8_t *p = static_cast<const uint8_t *>(src);
  uint8_t *q = static_cast<uint8_t *>(dst);
  uint32_t crc = 0, adl = 1;
  int64_t off = 0;
  while (off < size) {
    int64_t blk = size - off;
    if (blk > 262144)
      blk = 262144;
    memcpy(q + off, p + off, static_cast<size_t>(blk));
    crc = crc32z_update(crc, p + off, blk);
    adl = adler32_update(adl, p + off, blk);
    off += blk;
  }
  out[0] = crc;
  out[1] = adl;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// "huff" block codec: static canonical-Huffman entropy coder (codec.py's
// native backend).  Checkpoint float payloads after byte-shuffle
// preconditioning are entropy-bound, not match-bound — the exponent byte
// planes hold a handful of symbol values in near-random order, which an
// LZ matcher can't exploit but an order-0 entropy coder compresses well
// (~1.5x on noisy bf16).  Deflate's Huffman-only mode proves the ratio
// but tops out ~65MB/s here; this flat table-driven coder runs several
// times faster and, like everything in this file, entirely outside the
// GIL so the staging executor's encode stage overlaps storage I/O.
//
// Stream layout: independent 128KB blocks, each
//   [mode u8][raw_len i32le][payload]
//   mode 0 raw:      payload = raw bytes (incompressible block)
//   mode 1 huffman:  payload = [code lens 256 x 4bit][nbits u32le][bitstream]
//   mode 2 constant: payload = the single byte value
// Code lengths are capped at 12 bits (frequency flattening on overflow)
// so decode is one 4K-entry table lookup per symbol.  The compressor
// emits bit-REVERSED canonical codes into an LSB-first accumulator, so
// the decoder's peeked low bits are exactly the table index (deflate's
// trick).

namespace {

const int64_t kHuffBlock = 128 * 1024;
const int kHuffMaxLen = 12;

// Canonical code values (MSB-first semantics) from code lengths.
void huff_canonical_codes(const uint8_t *lens, uint16_t *codes) {
  int count[kHuffMaxLen + 1] = {0};
  for (int i = 0; i < 256; i++)
    count[lens[i]]++;
  count[0] = 0;
  uint32_t next[kHuffMaxLen + 1];
  uint32_t code = 0;
  for (int l = 1; l <= kHuffMaxLen; l++) {
    code = (code + count[l - 1]) << 1;
    next[l] = code;
  }
  for (int i = 0; i < 256; i++)
    codes[i] = lens[i] ? static_cast<uint16_t>(next[lens[i]]++) : 0;
}

// Length-limited Huffman code lengths from symbol frequencies: two-queue
// Huffman build, retried with flattened frequencies until the deepest
// leaf fits kHuffMaxLen (the standard cheap substitute for package-merge;
// the ratio loss on real blocks is <0.1%).
void huff_build_lens(const uint32_t *freq_in, uint8_t *lens) {
  uint32_t freq[256];
  memcpy(freq, freq_in, sizeof(freq));
  for (int attempt = 0;; attempt++) {
    struct Node {
      uint64_t f;
      int l, r, sym;
    };
    Node nodes[512];
    int order[256], n = 0;
    for (int i = 0; i < 256; i++)
      if (freq[i])
        order[n++] = i;
    memset(lens, 0, 256);
    if (n == 0)
      return;
    if (n == 1) {
      lens[order[0]] = 1;
      return;
    }
    // insertion sort by frequency (256 symbols max; avoids <algorithm>)
    for (int i = 1; i < n; i++) {
      int v = order[i], j = i - 1;
      while (j >= 0 && freq[order[j]] > freq[v]) {
        order[j + 1] = order[j];
        j--;
      }
      order[j + 1] = v;
    }
    for (int i = 0; i < n; i++) {
      nodes[i].f = freq[order[i]];
      nodes[i].l = nodes[i].r = -1;
      nodes[i].sym = order[i];
    }
    int q1 = 0, q2 = n, q2e = n;
    int root = -1;
    for (int k = 0; k < n - 1; k++) {
      int a, b;
      a = (q1 < n && (q2 >= q2e || nodes[q1].f <= nodes[q2].f)) ? q1++ : q2++;
      b = (q1 < n && (q2 >= q2e || nodes[q1].f <= nodes[q2].f)) ? q1++ : q2++;
      nodes[q2e].f = nodes[a].f + nodes[b].f;
      nodes[q2e].l = a;
      nodes[q2e].r = b;
      nodes[q2e].sym = -1;
      root = q2e++;
    }
    uint8_t depth[512];
    depth[root] = 0;
    // children always precede their parent in creation order, so one
    // top-down sweep from the root resolves every depth
    for (int i = root; i >= n; i--) {
      depth[nodes[i].l] = depth[i] + 1;
      depth[nodes[i].r] = depth[i] + 1;
    }
    int maxd = 0;
    for (int i = 0; i < n; i++)
      if (depth[i] > maxd)
        maxd = depth[i];
    if (maxd <= kHuffMaxLen) {
      for (int i = 0; i < n; i++)
        lens[nodes[i].sym] = depth[i];
      return;
    }
    for (int i = 0; i < 256; i++)
      if (freq[i])
        freq[i] = (freq[i] >> (2 * (attempt + 1))) + 1;
  }
}

}  // namespace

extern "C" {

// Byte-shuffle preconditioning (codec.py's filter): group byte plane i
// of every `stride`-sized element together — dst[p*rows + r] =
// src[r*stride + p].  Cache-blocked transpose, entirely outside the
// GIL (the numpy reshape().T path holds it and costs an extra copy).
// The sub-element tail (n % stride) is copied through unshuffled, so
// the transform stays self-inverse for any length.
void tsnp_byte_shuffle(const uint8_t *src, int64_t n, int64_t stride,
                       uint8_t *dst) {
  int64_t rows = n / stride;
  const int64_t kBlock = 4096;
  for (int64_t r0 = 0; r0 < rows; r0 += kBlock) {
    int64_t r1 = r0 + kBlock < rows ? r0 + kBlock : rows;
    for (int64_t p = 0; p < stride; p++) {
      uint8_t *d = dst + p * rows + r0;
      const uint8_t *s = src + r0 * stride + p;
      for (int64_t r = r0; r < r1; r++) {
        *d++ = *s;
        s += stride;
      }
    }
  }
  memcpy(dst + rows * stride, src + rows * stride, n - rows * stride);
}

void tsnp_byte_unshuffle(const uint8_t *src, int64_t n, int64_t stride,
                         uint8_t *dst) {
  int64_t rows = n / stride;
  const int64_t kBlock = 4096;
  for (int64_t r0 = 0; r0 < rows; r0 += kBlock) {
    int64_t r1 = r0 + kBlock < rows ? r0 + kBlock : rows;
    for (int64_t p = 0; p < stride; p++) {
      const uint8_t *s = src + p * rows + r0;
      uint8_t *d = dst + r0 * stride + p;
      for (int64_t r = r0; r < r1; r++) {
        *d = *s++;
        d += stride;
      }
    }
  }
  memcpy(dst + rows * stride, src + rows * stride, n - rows * stride);
}

// Compress src[0:n] into dst (capacity cap).  Returns the compressed
// size, or -1 when dst is too small (callers size cap >= n + n/64 + 4096
// so a real payload never hits it; a pathological all-raw stream grows
// 5 bytes per 128KB block).
int64_t tsnp_huff_compress(const uint8_t *src, int64_t n, uint8_t *dst,
                           int64_t cap) {
  uint8_t *op = dst;
  const uint8_t *oend = dst + cap;
  for (int64_t pos = 0; pos < n; pos += kHuffBlock) {
    int bn = static_cast<int>(n - pos < kHuffBlock ? n - pos : kHuffBlock);
    const uint8_t *bp = src + pos;
    if (op + bn + 256 > oend)
      return -1;
    uint32_t freq[256] = {0};
    for (int i = 0; i < bn; i++)
      freq[bp[i]]++;
    int nsym = 0, sym0 = 0;
    for (int i = 0; i < 256; i++)
      if (freq[i]) {
        nsym++;
        sym0 = i;
      }
    if (nsym == 1) {
      *op++ = 2;
      memcpy(op, &bn, 4);
      op += 4;
      *op++ = static_cast<uint8_t>(sym0);
      continue;
    }
    uint8_t lens[256];
    uint16_t codes[256], rcodes[256];
    huff_build_lens(freq, lens);
    huff_canonical_codes(lens, codes);
    for (int s = 0; s < 256; s++) {
      uint32_t c = codes[s], r = 0;
      for (int b = 0; b < lens[s]; b++)
        r = (r << 1) | ((c >> b) & 1);
      rcodes[s] = static_cast<uint16_t>(r);
    }
    uint64_t bits = 0;
    for (int i = 0; i < 256; i++)
      bits += static_cast<uint64_t>(freq[i]) * lens[i];
    int64_t est = 1 + 4 + 128 + 4 + static_cast<int64_t>((bits + 7) / 8);
    if (est >= bn) {  // entropy coding wouldn't shrink this block
      *op++ = 0;
      memcpy(op, &bn, 4);
      op += 4;
      memcpy(op, bp, bn);
      op += bn;
      continue;
    }
    *op++ = 1;
    memcpy(op, &bn, 4);
    op += 4;
    for (int i = 0; i < 256; i += 2)
      *op++ = static_cast<uint8_t>(lens[i] | (lens[i + 1] << 4));
    uint32_t nbits32 = static_cast<uint32_t>(bits);
    memcpy(op, &nbits32, 4);
    op += 4;
    uint64_t acc = 0;
    int nb = 0;
    for (int i = 0; i < bn; i++) {
      acc |= static_cast<uint64_t>(rcodes[bp[i]]) << nb;
      nb += lens[bp[i]];
      if (nb >= 32) {
        memcpy(op, &acc, 4);
        op += 4;
        acc >>= 32;
        nb -= 32;
      }
    }
    while (nb > 0) {
      *op++ = static_cast<uint8_t>(acc);
      acc >>= 8;
      nb -= 8;
    }
  }
  return op - dst;
}

// Decompress src[0:n] into dst (capacity rawcap).  Returns the raw size,
// or -1 on any malformed input (truncated block, bad mode byte, bit
// stream shorter than its symbol count claims) — the Python layer maps
// -1 to a typed corrupt-frame error.
int64_t tsnp_huff_decompress(const uint8_t *src, int64_t n, uint8_t *dst,
                             int64_t rawcap) {
  const uint8_t *ip = src;
  const uint8_t *iend = src + n;
  uint8_t *op = dst;
  uint8_t *oend = dst + rawcap;
  while (ip < iend) {
    if (ip + 5 > iend)
      return -1;
    uint8_t mode = *ip++;
    int32_t bn;
    memcpy(&bn, ip, 4);
    ip += 4;
    if (bn < 0 || op + bn > oend)
      return -1;
    if (mode == 0) {
      if (ip + bn > iend)
        return -1;
      memcpy(op, ip, bn);
      op += bn;
      ip += bn;
    } else if (mode == 2) {
      if (ip >= iend)
        return -1;
      memset(op, *ip++, bn);
      op += bn;
    } else if (mode == 1) {
      if (ip + 132 > iend)
        return -1;
      uint8_t lens[256];
      for (int i = 0; i < 128; i++) {
        lens[2 * i] = ip[i] & 15;
        lens[2 * i + 1] = ip[i] >> 4;
      }
      ip += 128;
      uint32_t nbits;
      memcpy(&nbits, ip, 4);
      ip += 4;
      // Wire lengths are 4-bit nibbles (0..15) but the coder never
      // emits above kHuffMaxLen=12 — larger values are corruption, and
      // would index past count[]/next[] in huff_canonical_codes.
      // Kraft check: an overfull length table (sum 2^-len > 1) is not a
      // prefix code — canonical construction would assign code values
      // wider than their lengths.  Undersubscribed tables are fine:
      // their unused table slots stay 0xffff and decode fails cleanly
      // on first hit.
      uint64_t kraft = 0;
      for (int s = 0; s < 256; s++) {
        if (lens[s] > kHuffMaxLen)
          return -1;
        if (lens[s])
          kraft += 1u << (kHuffMaxLen - lens[s]);
      }
      if (kraft > (1u << kHuffMaxLen))
        return -1;
      uint16_t codes[256];
      huff_canonical_codes(lens, codes);
      uint16_t table[1 << kHuffMaxLen];
      memset(table, 0xff, sizeof(table));
      for (int s = 0; s < 256; s++) {
        int l = lens[s];
        if (!l)
          continue;
        uint32_t c = codes[s], r = 0;
        for (int b = 0; b < l; b++)
          r = (r << 1) | ((c >> b) & 1);
        for (uint32_t f = 0; f < (1u << (kHuffMaxLen - l)); f++)
          table[r | (f << l)] = static_cast<uint16_t>(s | (l << 8));
      }
      const uint8_t *bs = ip;
      int64_t nbytes = (static_cast<int64_t>(nbits) + 7) / 8;
      if (bs + nbytes > iend)
        return -1;
      uint64_t acc = 0;
      int nb = 0;
      int64_t bpos = 0;
      for (int i = 0; i < bn; i++) {
        if (nb < kHuffMaxLen) {
          if (bpos + 4 <= nbytes) {
            uint32_t w;
            memcpy(&w, bs + bpos, 4);
            acc |= static_cast<uint64_t>(w) << nb;
            bpos += 4;
            nb += 32;
          } else {
            while (nb < kHuffMaxLen && bpos < nbytes) {
              acc |= static_cast<uint64_t>(bs[bpos++]) << nb;
              nb += 8;
            }
          }
        }
        uint16_t e = table[acc & ((1 << kHuffMaxLen) - 1)];
        int l = e >> 8;
        if (l == 0xff || l == 0 || l > nb)
          return -1;  // invalid code or bit stream exhausted mid-symbol
        *op++ = static_cast<uint8_t>(e);
        acc >>= l;
        nb -= l;
      }
      ip = bs + nbytes;
    } else {
      return -1;
    }
  }
  return op - dst;
}

}  // extern "C"
