// Native file-I/O engine for the fs storage plugin.
//
// The reference delegates its native needs to PyTorch's C++ (TCPStore, CUDA
// copies — SURVEY §2.9); this repo's runtime equivalent is this small
// library: single-syscall-chain file writes/reads that run entirely outside
// the GIL (called via ctypes from scheduler worker threads), plus a
// slice-by-8 crc32c for blob integrity.
//
// Build: g++ -O3 -shared -fPIC -o fastio.so fastio.cpp  (see build_ext.py)

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

// When the build links libz (-DTSNP_USE_ZLIB -lz), the fused digest
// defers to its crc32/adler32 — system zlib ships SIMD (PCLMUL) crc on
// most distros, ~2x this file's slice-by-8.  The table implementations
// below remain the no-zlib fallback.
#if defined(TSNP_USE_ZLIB)
#include <zlib.h>
#endif

extern "C" {

// Write buf[0:size] to path (create/truncate). Returns 0 on success,
// -errno on failure. fsync_mode: 0 = none (page-cache, benchmark mode),
// 1 = fdatasync before close (durability).
int tsnp_write_file(const char *path, const void *buf, int64_t size,
                    int fsync_mode) {
  int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC | O_CLOEXEC, 0644);
  if (fd < 0)
    return -errno;
  const char *p = static_cast<const char *>(buf);
  int64_t remaining = size;
  while (remaining > 0) {
    ssize_t n = write(fd, p, static_cast<size_t>(remaining));
    if (n < 0) {
      if (errno == EINTR)
        continue;
      int err = errno;
      close(fd);
      return -err;
    }
    p += n;
    remaining -= n;
  }
  int rc = 0;
  if (fsync_mode == 1 && fdatasync(fd) != 0)
    rc = -errno;
  if (close(fd) != 0 && rc == 0)
    rc = -errno;
  return rc;
}

// Read length bytes at offset from path into buf. offset<0 means 0;
// length<0 means "to EOF" (caller must size buf via tsnp_file_size).
// Returns bytes read, or -errno.
int64_t tsnp_read_file(const char *path, void *buf, int64_t offset,
                       int64_t length) {
  int fd = open(path, O_RDONLY | O_CLOEXEC);
  if (fd < 0)
    return -errno;
  if (offset > 0 && lseek(fd, static_cast<off_t>(offset), SEEK_SET) < 0) {
    int err = errno;
    close(fd);
    return -err;
  }
  char *p = static_cast<char *>(buf);
  int64_t total = 0;
  while (length < 0 || total < length) {
    size_t want = length < 0 ? (1u << 20) : static_cast<size_t>(length - total);
    if (want > (1u << 20))
      want = 1u << 20;
    ssize_t n = read(fd, p + total, want);
    if (n < 0) {
      if (errno == EINTR)
        continue;
      int err = errno;
      close(fd);
      return -err;
    }
    if (n == 0)
      break;
    total += n;
  }
  close(fd);
  return total;
}

int64_t tsnp_file_size(const char *path) {
  struct stat st;
  if (stat(path, &st) != 0)
    return -errno;
  return static_cast<int64_t>(st.st_size);
}

// slice-by-8 table construction, shared by the crc32c (Castagnoli) and
// zlib-crc32 variants below.
static void init_slice8_tables(uint32_t poly, uint32_t table[8][256]) {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++)
      crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    table[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = table[0][i];
    for (int s = 1; s < 8; s++) {
      crc = table[0][crc & 0xff] ^ (crc >> 8);
      table[s][i] = crc;
    }
  }
}

// The word-at-a-time slice-by-8 folds `crc ^= (uint32_t)chunk` on a
// memcpy'd 8-byte word, which is only correct when the low word holds
// the FIRST four bytes — i.e. on little-endian hosts.  Big-endian hosts
// take the (correct, slower) bytewise loops instead of silently
// recording wrong checksums into manifests.
#if defined(__BYTE_ORDER__) && (__BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__)
#define TSNP_LITTLE_ENDIAN 1
#else
#define TSNP_LITTLE_ENDIAN 0
#endif

// crc32c (Castagnoli), slice-by-8.
static uint32_t crc32c_table[8][256];
// zlib-polynomial crc32 (0xEDB88320), slice-by-8 — bit-compatible with
// python's zlib.crc32 (manifest checksums use that polynomial; crc32c
// above is only for fs write verification).
static uint32_t crc32z_table[8][256];

// Eager init at library load: tsnp_crc32c / tsnp_copy_digest are called
// concurrently from executor threads with the GIL released, so a lazy
// check-then-init would be a data race (a thread could read a
// partially-built higher slice).
__attribute__((constructor)) static void tsnp_init_crc_tables() {
  init_slice8_tables(0x82f63b78u, crc32c_table);
  init_slice8_tables(0xEDB88320u, crc32z_table);
}

uint32_t tsnp_crc32c(const void *buf, int64_t size, uint32_t seed) {
  uint32_t crc = ~seed;
  const uint8_t *p = static_cast<const uint8_t *>(buf);
#if TSNP_LITTLE_ENDIAN
  while (size >= 8) {
    uint64_t chunk;
    memcpy(&chunk, p, 8);
    crc ^= static_cast<uint32_t>(chunk);
    uint32_t hi = static_cast<uint32_t>(chunk >> 32);
    crc = crc32c_table[7][crc & 0xff] ^ crc32c_table[6][(crc >> 8) & 0xff] ^
          crc32c_table[5][(crc >> 16) & 0xff] ^ crc32c_table[4][crc >> 24] ^
          crc32c_table[3][hi & 0xff] ^ crc32c_table[2][(hi >> 8) & 0xff] ^
          crc32c_table[1][(hi >> 16) & 0xff] ^ crc32c_table[0][hi >> 24];
    p += 8;
    size -= 8;
  }
#endif
  while (size > 0) {
    crc = crc32c_table[0][(crc ^ *p) & 0xff] ^ (crc >> 8);
    p++;
    size--;
  }
  return ~crc;
}

// memcpy src -> dst while computing zlib crc32 AND adler32 of the bytes,
// processed in 64KB blocks so each block is digested while still hot in
// cache: memory traffic is one read + one write instead of the three
// read passes of copy-then-crc-then-adler.  out[0] = crc32 (zlib
// finalized), out[1] = adler32.  Runs entirely outside the GIL (ctypes).
void tsnp_copy_digest(void *dst, const void *src, int64_t size,
                      uint32_t *out) {
  const uint8_t *p = static_cast<const uint8_t *>(src);
  uint8_t *q = static_cast<uint8_t *>(dst);
#if defined(TSNP_USE_ZLIB)
  uLong zcrc = crc32(0L, Z_NULL, 0);
  uLong zadl = adler32(0L, Z_NULL, 0);
  int64_t zoff = 0;
  while (zoff < size) {
    int64_t blk = size - zoff;
    if (blk > 65536)
      blk = 65536;
    memcpy(q + zoff, p + zoff, static_cast<size_t>(blk));
    zcrc = crc32(zcrc, p + zoff, static_cast<uInt>(blk));
    zadl = adler32(zadl, p + zoff, static_cast<uInt>(blk));
    zoff += blk;
  }
  out[0] = static_cast<uint32_t>(zcrc);
  out[1] = static_cast<uint32_t>(zadl);
  return;
#else
  uint32_t crc = 0xFFFFFFFFu;
  const uint32_t MOD = 65521u;
  uint32_t a = 1, b = 0;
  int64_t off = 0;
  while (off < size) {
    int64_t blk = size - off;
    if (blk > 65536)
      blk = 65536;
    memcpy(q + off, p + off, static_cast<size_t>(blk));
    const uint8_t *s = p + off;
    int64_t n = blk;
#if TSNP_LITTLE_ENDIAN
    while (n >= 8) {
      uint64_t chunk;
      memcpy(&chunk, s, 8);
      crc ^= static_cast<uint32_t>(chunk);
      uint32_t hi = static_cast<uint32_t>(chunk >> 32);
      crc = crc32z_table[7][crc & 0xff] ^ crc32z_table[6][(crc >> 8) & 0xff] ^
            crc32z_table[5][(crc >> 16) & 0xff] ^ crc32z_table[4][crc >> 24] ^
            crc32z_table[3][hi & 0xff] ^ crc32z_table[2][(hi >> 8) & 0xff] ^
            crc32z_table[1][(hi >> 16) & 0xff] ^ crc32z_table[0][hi >> 24];
      s += 8;
      n -= 8;
    }
#endif
    while (n > 0) {
      crc = crc32z_table[0][(crc ^ *s) & 0xff] ^ (crc >> 8);
      s++;
      n--;
    }
    // adler32 per 5552-byte window via the closed form
    //   a' = a + S1,  b' = b + m*a + m*S1 - S2
    // with S1 = sum(s[k]), S2 = sum(k*s[k]) — both plain reductions the
    // compiler can vectorize, unlike the scalar b += a dependency chain
    s = p + off;
    n = blk;
    while (n > 0) {
      int64_t m = n > 5552 ? 5552 : n;
      uint64_t s1 = 0, s2 = 0;
      for (int64_t k = 0; k < m; k++) {
        s1 += s[k];
        s2 += static_cast<uint64_t>(k) * s[k];
      }
      uint64_t mm = static_cast<uint64_t>(m);
      uint64_t bb = b + mm * a + mm * s1 - s2;
      a = static_cast<uint32_t>((a + s1) % MOD);
      b = static_cast<uint32_t>(bb % MOD);
      s += m;
      n -= m;
    }
    off += blk;
  }
  out[0] = ~crc;
  out[1] = (b << 16) | a;
#endif  // TSNP_USE_ZLIB
}

}  // extern "C"
