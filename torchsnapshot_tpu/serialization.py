"""Zero-copy array (de)serialization + a safe object codec.

TPU-native analogue of the reference's serialization layer
(torchsnapshot/serialization.py:34-477), redesigned for JAX host buffers:

- Arrays are stored as raw little-endian C-contiguous bytes; dtype/shape live
  in the manifest.  ``memoryview`` over the numpy buffer gives zero-copy
  writes (reference ``tensor_as_memoryview``, serialization.py:177-251).
- bfloat16 (and fp8 variants) are first-class via ``ml_dtypes`` — no
  UntypedStorage tricks needed: numpy handles the buffer protocol for these
  extension dtypes directly.
- The object fallback is NOT pickle-by-default: we use a self-describing
  msgpack codec covering containers/primitives/numpy scalars+arrays
  (reference uses torch.save/pickle, serialization.py:268-275).  Arbitrary
  objects fall back to pickle only when the ``ALLOW_PICKLE_OBJECTS`` knob is
  on; payloads are tagged so readers can refuse pickles.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Tuple

import numpy as np

try:
    import ml_dtypes

    _ML_DTYPES = {
        "bfloat16": np.dtype(ml_dtypes.bfloat16),
        "float8_e4m3fn": np.dtype(ml_dtypes.float8_e4m3fn),
        "float8_e5m2": np.dtype(ml_dtypes.float8_e5m2),
        "float8_e4m3fnuz": np.dtype(getattr(ml_dtypes, "float8_e4m3fnuz", ml_dtypes.float8_e4m3fn)),
        "int4": np.dtype(ml_dtypes.int4),
        "uint4": np.dtype(ml_dtypes.uint4),
    }
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    _ML_DTYPES = {}

from . import knobs

# Serializer tags recorded in the manifest (reference Serializer enum,
# serialization.py:155-159).
BUFFER_PROTOCOL = "buffer_protocol"
SAFE_OBJECT = "safe_object"  # msgpack codec
PICKLE_OBJECT = "pickle"

# dtype-string table (reference serialization.py:34-110). We use numpy dtype
# names directly; ml_dtypes extension dtypes keep their canonical names.
_STD_DTYPES = [
    "float16", "float32", "float64",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64",
    "bool", "complex64", "complex128",
]


_DTYPE_NAME_CACHE: dict = {}


def dtype_to_string(dtype: Any) -> str:
    # memoized on the np.dtype object: the linear _ML_DTYPES scan per
    # array leaf is measurable planning cost at tens of thousands of
    # leaves (the async_take blocked window is exactly this planning)
    dt = np.dtype(dtype)
    cached = _DTYPE_NAME_CACHE.get(dt)
    if cached is not None:
        return cached
    name = None
    for mname, mdt in _ML_DTYPES.items():
        if dt == mdt:
            name = mname
            break
    if name is None:
        if dt.name not in _STD_DTYPES:
            raise ValueError(f"unsupported dtype for serialization: {dtype!r}")
        name = dt.name
    _DTYPE_NAME_CACHE[dt] = name
    return name


def string_to_dtype(s: str) -> np.dtype:
    if s in _ML_DTYPES:
        return _ML_DTYPES[s]
    if s in _STD_DTYPES:
        return np.dtype(s)
    raise ValueError(f"unknown serialized dtype: {s!r}")


def array_as_memoryview(arr: np.ndarray) -> memoryview:
    """Zero-copy view of a host array's bytes (contiguous + little-endian
    normalized; copies only when layout requires it)."""
    if arr.dtype.byteorder == ">":  # big-endian: normalize (rare)
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    try:
        return memoryview(arr).cast("B")
    except (ValueError, TypeError):
        # Extension dtypes (bfloat16, fp8, ...) don't implement the buffer
        # protocol; a uint8 view of the same memory does.
        return memoryview(arr.reshape(-1).view(np.uint8))


def array_from_buffer(buf: Any, dtype_str: str, shape: Tuple[int, ...]) -> np.ndarray:
    """Zero-copy reconstruction from raw bytes (reference
    tensor_from_memoryview, serialization.py:254-265). The returned array
    shares memory with ``buf`` and is read-only if ``buf`` is."""
    dtype = string_to_dtype(dtype_str)
    arr = np.frombuffer(buf, dtype=dtype)
    return arr.reshape(shape)


def serialized_size_bytes(shape, dtype: Any) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n * np.dtype(dtype).itemsize


_UINT_FOR_ITEMSIZE = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def fast_copyto(dst: np.ndarray, src: np.ndarray) -> None:
    """memcpy-speed ``np.copyto``. Same-dtype copies of extension dtypes
    (ml_dtypes bfloat16/fp8) otherwise go through numpy's per-element cast
    machinery at ~0.5 GB/s; routing them through a bit-identical
    unsigned-integer view runs at memory bandwidth (~10x), including for
    strided views. Falls back to casting ``np.copyto`` for dtype changes."""
    if (
        dst.dtype == src.dtype
        and not dst.dtype.hasobject
        and dst.dtype.itemsize in _UINT_FOR_ITEMSIZE
    ):
        u = _UINT_FOR_ITEMSIZE[dst.dtype.itemsize]
        np.copyto(dst.view(u), src.view(u))
    else:
        np.copyto(dst, src, casting="unsafe")


def fast_copy(src: np.ndarray) -> np.ndarray:
    """``np.copy`` at memory bandwidth (same extension-dtype caveat as
    :func:`fast_copyto`; ``np.copy`` of an ml_dtypes array is ~0.2 GB/s)."""
    dst = np.empty(src.shape, dtype=src.dtype)
    fast_copyto(dst, src)
    return dst


# ---------------------------------------------------------------------------
# Safe object codec (msgpack with extension types). Covers: None, bool, int,
# float, str, bytes, list, tuple, set, frozenset, dict (any hashable encodable
# keys), complex, numpy scalars and ndarrays (incl. bfloat16 via raw-bytes ext).
# ---------------------------------------------------------------------------

import msgpack

_EXT_TUPLE = 1
_EXT_SET = 2
_EXT_FROZENSET = 3
_EXT_COMPLEX = 4
_EXT_NDARRAY = 5
_EXT_NPSCALAR = 6
_EXT_BIGINT = 7
_EXT_DICT_NONSTR = 8  # dict with non-string keys: list of [k, v] pairs


def _default(obj: Any) -> Any:
    if isinstance(obj, tuple):
        return msgpack.ExtType(_EXT_TUPLE, _pack(list(obj)))
    if isinstance(obj, set):
        return msgpack.ExtType(_EXT_SET, _pack(sorted(obj, key=repr)))
    if isinstance(obj, frozenset):
        return msgpack.ExtType(_EXT_FROZENSET, _pack(sorted(obj, key=repr)))
    if isinstance(obj, complex):
        return msgpack.ExtType(_EXT_COMPLEX, _pack([obj.real, obj.imag]))
    if isinstance(obj, np.ndarray):
        payload = _pack(
            [dtype_to_string(obj.dtype), list(obj.shape),
             array_as_memoryview(obj).tobytes()]
        )
        return msgpack.ExtType(_EXT_NDARRAY, payload)
    if isinstance(obj, np.generic):
        arr = np.asarray(obj)
        payload = _pack([dtype_to_string(arr.dtype), arr.tobytes()])
        return msgpack.ExtType(_EXT_NPSCALAR, payload)
    if isinstance(obj, int):
        # out-of-range ints reach here (msgpack caps at 64-bit)
        return msgpack.ExtType(_EXT_BIGINT, str(obj).encode())
    if isinstance(obj, dict):
        # only reached when strict_map_key rejects: encode as pair list
        return msgpack.ExtType(_EXT_DICT_NONSTR, _pack([[k, v] for k, v in obj.items()]))
    raise TypeError(f"unencodable object of type {type(obj)}")


def _ext_hook(code: int, data: bytes) -> Any:
    if code == _EXT_TUPLE:
        return tuple(_unpack(data))
    if code == _EXT_SET:
        return set(_unpack(data))
    if code == _EXT_FROZENSET:
        return frozenset(_unpack(data))
    if code == _EXT_COMPLEX:
        re, im = _unpack(data)
        return complex(re, im)
    if code == _EXT_NDARRAY:
        dtype_str, shape, raw = _unpack(data)
        return array_from_buffer(raw, dtype_str, tuple(shape)).copy()
    if code == _EXT_NPSCALAR:
        dtype_str, raw = _unpack(data)
        return np.frombuffer(raw, dtype=string_to_dtype(dtype_str))[0]
    if code == _EXT_BIGINT:
        return int(data.decode())
    if code == _EXT_DICT_NONSTR:
        return {k: v for k, v in _unpack(data)}
    return msgpack.ExtType(code, data)


def _pack(obj: Any) -> bytes:
    return msgpack.packb(obj, default=_default, strict_types=True, use_bin_type=True)


def _unpack(data: Any) -> Any:
    return msgpack.unpackb(
        data, ext_hook=_ext_hook, raw=False, strict_map_key=False
    )


def serialize_object(obj: Any) -> Tuple[bytes, str]:
    """Serialize an arbitrary object; returns (payload, serializer_tag).

    Tries the safe msgpack codec first; falls back to pickle when the knob
    allows (reference object path uses torch.save unconditionally,
    io_preparers/object.py:69-82)."""
    try:
        return _pack(obj), SAFE_OBJECT
    except (TypeError, ValueError, OverflowError):
        pass
    if not knobs.is_pickle_allowed():
        raise TypeError(
            f"object of type {type(obj)} is not encodable by the safe codec "
            "and ALLOW_PICKLE_OBJECTS is disabled"
        )
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue(), PICKLE_OBJECT


def deserialize_object(payload: Any, serializer: str) -> Any:
    if serializer == SAFE_OBJECT:
        return _unpack(bytes(payload))
    if serializer == PICKLE_OBJECT:
        if not knobs.is_pickle_allowed():
            raise RuntimeError(
                "snapshot contains a pickle payload but ALLOW_PICKLE_OBJECTS "
                "is disabled"
            )
        return pickle.loads(bytes(payload))
    raise ValueError(f"unknown object serializer: {serializer!r}")
