"""Fan-out restore: read each replicated object once per SLICE, then
redistribute the bytes to sibling ranks over the coordination layer.

A flat restore has every rank GET every replicated object from the
durable tier — O(objects × ranks) GETs, a self-inflicted DDoS on the
bucket at multislice scale.  The shared-host cache
(storage/hostcache.py) already collapses that to once per HOST for
co-located processes; this module is the cross-host generalization:
for each shared object a deterministic **designated reader** rank per
slice (Topology.designated_reader — spread across the slice's hosts)
performs the one durable GET and publishes the bytes over the
coordination KV (``Coordinator.kv_publish_blob``: chunked, crc32
digest-verified, meta-key-last so presence implies completeness);
sibling ranks poll for the publication and consume it instead of
issuing their own GET.

Failure semantics — a dead reader degrades, never wedges, and never
stampedes: a sibling that sees no publication within
``FANOUT_TIMEOUT_S`` does NOT immediately issue its own durable GET
(at slice scale that synchronized burst is the very DDoS fan-out
exists to prevent).  Instead the slice re-elects: the next rank in the
stable ``Topology.reader_candidates`` rotation — agreed on every
process with zero communication — takes over the durable read AND the
publication, while the remaining siblings wait one more bounded window
for the takeover publication.  Only if that second window also passes
(both readers dead / publication broken) do siblings read direct, and
then in host-staggered waves: co-hosted processes collapse through the
shared-host cache's single-flight, and each host's wave starts
``_FALLBACK_STAGGER_S`` after the previous one, so the durable tier
sees a ramp instead of a thundering herd.
``topology.fanout_fallbacks`` counts affected OBJECTS (once per object
per rank), not raw read attempts; a digest mismatch or delivery error
still falls back directly (the bytes can't be trusted — correctness
over smoothness).  Publication itself is best-effort: a publish
failure costs peers their savings, not the restore.

Composition: the wrapper goes OUTSIDE the shared-host cache, so the
designated reader's one GET is itself host-deduped — per slice the
durable tier sees exactly one GET per object, regardless of how many
hosts or processes the slice spans.  A slice whose members all share
one host with the cache active skips fan-out entirely (the cache
already covers it; the KV hop would be pure overhead).

Scope: only storage locations under ``replicated/`` that every rank
reads (``shared_read_locations``) participate — per-rank and sharded
objects have per-rank readers, and slab-batched objects live under a
rank namespace; both take the direct path unchanged.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import threading
import time
from typing import Any, Dict, Iterable, Optional, Set

from .. import knobs, obs
from ..io_types import (
    ReadIO,
    StoragePlugin,
    WriteIO,
    resolve_read_destination,
)
from ..resilience.failpoints import failpoint
from ..storage.hostcache import host_cache_active
from ..transport import TransportUnavailable, count_fallback
from .model import Topology

logger = logging.getLogger(__name__)

_SHARED_PREFIX = "replicated/"
# how often a sibling re-probes the KV for its designated reader's
# publication (one kv_try_get per tick)
_FETCH_POLL_S = 0.025
# per-HOST wave spacing for the last-resort direct fallback (both
# elected readers silent): host k's processes start their direct read
# k * this many seconds after the first wave — long enough to spread
# the burst, short enough to be noise next to the two timeout windows
# already spent
_FALLBACK_STAGGER_S = 0.05


def fanout_enabled(topology: Topology) -> bool:
    """Whether this rank's restore should fan out (see module
    docstring).  "on" forces it whenever the slice has siblings; "auto"
    additionally requires an explicit topology and skips slices already
    covered by a same-host shared cache."""
    mode = knobs.get_fanout()
    if mode == "off":
        return False
    members = topology.ranks_in_slice(topology.slice_id)
    if len(members) < 2:
        return False
    if mode == "on":
        return True
    if not topology.explicit:
        return False
    if host_cache_active() and len(
        {topology.host_of[r] for r in members}
    ) == 1:
        # single-host slice with the shared cache active: the flock
        # single-flight already makes the slice cost one GET per object
        return False
    return True


def fanout_world_uniform(topology: Topology) -> bool:
    """Whether EVERY rank's ``fanout_enabled`` decision comes out True
    under this process's knobs — the collective fan-out session's
    precondition.  The session's gate protocol and broadcasts need all
    world processes participating; a single-member slice (or a
    single-host slice the shared cache already covers) opts its ranks
    out of fan-out entirely, and a session would stall waiting for
    their acks.  Evaluated from global topology state only, so every
    process computes the same answer (knob parity across the fleet is
    the same SPMD contract restore already documents)."""
    mode = knobs.get_fanout()
    if mode == "off":
        return False
    for s in sorted(set(topology.slice_of)):
        members = topology.ranks_in_slice(s)
        if len(members) < 2:
            return False
        if mode == "auto":
            if not topology.explicit:
                return False
            if host_cache_active() and len(
                {topology.host_of[r] for r in members}
            ) == 1:
                return False
    return True


def _entry_shared_locations(entry: Any) -> Iterable[str]:
    """The ``replicated/``-namespaced storage locations one manifest
    entry reads (whole object plus shard/chunk pieces)."""
    if not getattr(entry, "replicated", False):
        return
    loc = getattr(entry, "location", None)
    if isinstance(loc, str) and loc.startswith(_SHARED_PREFIX):
        yield loc
    for attr in ("shards", "chunks"):
        for piece in getattr(entry, attr, None) or ():
            ploc = getattr(piece, "location", None)
            if isinstance(ploc, str) and ploc.startswith(_SHARED_PREFIX):
                yield ploc


def shared_read_locations(manifest: Dict[str, Any]) -> Set[str]:
    """Storage locations every rank reads during a full restore: the
    ``replicated/``-namespaced extents of replicated entries (whole
    objects plus chunk pieces).  Slab-batched replicated leaves live
    under a rank namespace and are deliberately excluded — their slab
    mixes per-rank members whose ranges only one rank reads, and a
    designated reader would never publish those."""
    out: Set[str] = set()
    for entry in manifest.values():
        out.update(_entry_shared_locations(entry))
    return out


def ordered_shared_locations(
    manifest: Dict[str, Any],
    shared: Set[str],
    key_order: Iterable[str],
) -> list:
    """``shared`` in restore READ order: grouped by the owning app
    key's position in the restore's global key order (manifest logical
    paths lead with the app key), location-sorted within a key.  The
    collective fan-out session schedules its transfers in this order,
    so the schedule advances in step with the restore's per-key read
    phases — a plan sorted any other way would park the session waiting
    on a later key's object while every rank is still gated behind an
    earlier key's barrier."""
    pos = {k: i for i, k in enumerate(key_order)}
    best: Dict[str, int] = {}
    for p, entry in manifest.items():
        i = pos.get(p.split("/", 1)[0])
        if i is None:
            continue
        for loc in _entry_shared_locations(entry):
            if loc in shared and (loc not in best or i < best[loc]):
                best[loc] = i
    tail = sorted(p for p in shared if p not in best)
    return sorted(best, key=lambda loc: (best[loc], loc)) + tail


def _blob_prefix(uid: str, slice_id: int, path: str, byte_range: Any) -> str:
    """KV prefix for one (object, byte range) publication — hashed so
    arbitrary object paths never collide with the KV key grammar; the
    byte range is part of the identity because striped/codec reads of
    one object fan out as multiple ranged reads (identically planned on
    every rank)."""
    h = hashlib.sha256()
    h.update(path.encode())
    if byte_range is not None:
        h.update(f"|{byte_range[0]}-{byte_range[1]}".encode())
    return f"{uid}/s{slice_id}/{h.hexdigest()[:32]}"


async def publish_object(
    coordinator: Any, prefix: str, buf: Any, path: str
) -> int:
    """Best-effort publication of one read's bytes for this slice's
    siblings; returns the number of KV parts written (0 on failure —
    the caller's cleanup ledger).  Never raises: the designated
    reader's own restore must not fail because a publication could not
    be made — peers fall back to direct reads and the failure stays
    visible as their ``fanout_fallbacks``."""
    with obs.span("fanout/publish", path=path):
        try:
            failpoint("topology.fanout.publish", path=path)
            part = knobs.get_fanout_part_bytes()
            loop = asyncio.get_running_loop()
            n = await loop.run_in_executor(
                None, coordinator.kv_publish_blob, prefix, buf, part
            )
            obs.counter(obs.FANOUT_PUBLISHES).inc()
            obs.counter(obs.FANOUT_BYTES_REDISTRIBUTED).inc(n)
            return max(1, (n + part - 1) // part)
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            obs.swallowed_exception("topology.fanout.publish", e)
            return 0


async def fetch_published(
    coordinator: Any,
    prefix: str,
    path: str,
    timeout_s: float,
    transport: Any = None,
) -> Optional[bytes]:
    """Poll for the designated reader's publication of ``path``; the
    verified bytes, or None when the deadline passes or verification
    fails (the caller falls back to a direct durable read).  Polling
    runs from the event loop (one non-blocking probe per tick) so a
    host full of waiting siblings never parks scheduler threads.

    With a ``transport`` the device-registry announce is probed FIRST
    each tick (the publisher may have used either engine — its own
    transport could have degraded mid-publish), then the KV blob.  A
    ``TransportUnavailable`` from the probe demotes this wait to
    KV-only; it is not a fallback event (the publisher's engine choice
    decides where bytes actually travelled)."""
    with obs.span("fanout/fetch", path=path):
        loop = asyncio.get_running_loop()
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                data = None
                if transport is not None:
                    try:
                        data = await loop.run_in_executor(
                            None, transport.try_fetch, prefix
                        )
                    except TransportUnavailable:
                        transport = None
                if data is None:
                    data = await loop.run_in_executor(
                        None, coordinator.kv_try_fetch_blob, prefix
                    )
                    if data is not None:
                        # KV-leg consumption, metered under the same
                        # instrument family as the collective engine so
                        # the bench compares engines directly
                        obs.counter(obs.TRANSPORT_KV_OPS).inc()
                        obs.counter(obs.TRANSPORT_KV_BYTES).inc(
                            len(data)
                        )
            except ValueError as e:
                # digest/length mismatch: the publication cannot be
                # trusted — direct read, never corrupt bytes
                logger.warning(
                    "fan-out publication for %r failed verification "
                    "(%s); falling back to a direct read", path, e,
                )
                return None
            if data is not None:
                return data
            if time.monotonic() >= deadline:
                return None
            await asyncio.sleep(_FETCH_POLL_S)


class FanoutReadPlugin(StoragePlugin):
    """Per-restore storage wrapper implementing the read-once-per-slice
    protocol over ``inner`` (see module docstring).  Reads of shared
    locations route through the designated-reader election; everything
    else (per-rank objects, markers, writes, deletes) passes straight
    through."""

    def __init__(
        self,
        inner: StoragePlugin,
        coordinator: Any,
        topology: Topology,
        uid: str,
        shared_paths: Iterable[str],
        transport: Any = None,
    ) -> None:
        self.inner = inner
        self.coordinator = coordinator
        self.topology = topology
        self.uid = uid
        self.shared_paths = set(shared_paths)
        # engine-selected payload transport (transport/); None keeps
        # the pre-transport KV-blob behavior bit-for-bit
        self.transport = transport
        # a CollectiveFanoutSession once restore derives the read-
        # ordered plan (attached AFTER construction — the plan needs
        # the gathered global key order); None = per-op transport only
        self.transport_session: Any = None
        # capability delegation: non-shared reads (per-rank/sharded
        # state — usually the bulk) keep the inner plugin's zero-copy
        # mmap path and budget exemption.  Shared reads are still
        # planned identically on every rank (same want_mmap branch);
        # a sibling served from a publication hands back heap bytes,
        # which the read scheduler's existing declined-mmap handling
        # debits against the budget.
        self.supports_mmap_read = bool(
            getattr(inner, "supports_mmap_read", False)
        )
        self.mmap_budget_exempt = bool(
            getattr(inner, "mmap_budget_exempt", False)
        )
        self.supports_striped_write = bool(
            getattr(inner, "supports_striped_write", False)
        )
        self.supports_fused_digest = bool(
            getattr(inner, "supports_fused_digest", False)
        )
        # (prefix, nparts) of this rank's successful publications, so
        # cleanup_published can reclaim the transient KV blobs after
        # every slice member is past its reads.  Reads append on the
        # loop; cleanup runs on the restore caller — locked handoff
        self._pub_lock = threading.Lock()
        self._published: list = []
        # the shared locations THIS rank is the designated reader for:
        # the scheduler front-loads these so siblings wait the minimum
        # (scheduler.sync_execute_read_reqs publish_first ordering)
        self.local_publish_paths = {
            p
            for p in self.shared_paths
            if topology.designated_reader(p) == coordinator.rank
        }
        m = obs.REGISTRY
        self._m_durable = m.counter(obs.FANOUT_DURABLE_READS)
        self._m_saved = m.counter(obs.FANOUT_DURABLE_GETS_SAVED)
        self._m_fallbacks = m.counter(obs.FANOUT_FALLBACKS)
        # per-OBJECT fallback accounting: striped/codec restores issue
        # several ranged reads per object, and counting each would make
        # one broken object look like a fleet incident
        self._fallback_paths: Set[str] = set()

    def _count_fallback(self, path: str) -> None:
        with self._pub_lock:
            if path in self._fallback_paths:
                return
            self._fallback_paths.add(path)
        self._m_fallbacks.inc()

    def _local_transport(self) -> Any:
        """The transport, iff it can serve per-op publish/fetch in this
        process (the collective engine's in-process device-registry
        mode).  Session mode moves whole objects through the fan-out
        session instead, and its per-op API raising
        ``TransportUnavailable`` is by design, not a degrade."""
        t = self.transport
        if t is not None and getattr(t, "mode", None) == "local":
            return t
        return None

    async def _publish_payload(self, prefix: str, buf: Any, path: str):
        """Publish one read's bytes over the selected engine; returns
        the cleanup-ledger entry ``(engine, prefix, nparts)`` or None.
        A collective-engine failure mid-publish degrades THIS op to the
        KV blob path (``transport.fallbacks`` advances); the KV leg's
        own failure stays best-effort as before."""
        t = self._local_transport()
        if t is not None:
            try:
                loop = asyncio.get_running_loop()
                nparts = await loop.run_in_executor(
                    None, t.publish, prefix, buf
                )
                obs.counter(obs.FANOUT_PUBLISHES).inc()
                obs.counter(obs.FANOUT_BYTES_REDISTRIBUTED).inc(
                    obs.buf_nbytes(buf)
                )
                return ("collective", prefix, nparts)
            except Exception as e:  # noqa: BLE001 — mid-op degrade:
                # the payload must still reach the siblings
                count_fallback("fanout-publish", e)
        nparts = await publish_object(self.coordinator, prefix, buf, path)
        if nparts:
            obs.counter(obs.TRANSPORT_KV_OPS).inc()
            obs.counter(obs.TRANSPORT_KV_BYTES).inc(obs.buf_nbytes(buf))
            return ("kv", prefix, nparts)
        return None

    async def _read_and_publish(self, read_io: ReadIO, prefix: str) -> None:
        """The designated-reader duty: one durable GET, then publish
        the bytes for the slice's siblings."""
        await self.inner.read(read_io)
        self._m_durable.inc()
        entry = await self._publish_payload(
            prefix, read_io.buf, read_io.path
        )
        if entry is not None:
            with self._pub_lock:
                self._published.append(entry)

    def _deliver(self, read_io: ReadIO, data: bytes) -> bool:
        """Place redistributed bytes into the read's destination; False
        on a mismatch (the caller falls back to a direct read)."""
        try:
            out = resolve_read_destination(read_io.into, len(data))
            memoryview(out).cast("B")[:] = data
            read_io.buf = out
            self._m_saved.inc()
            return True
        except Exception as e:  # noqa: BLE001 — delivery mismatch:
            # e.g. an ``into`` destination sized for a different
            # extent; the direct read is always correct
            obs.swallowed_exception("topology.fanout.deliver", e)
            return False

    async def read(self, read_io: ReadIO) -> None:
        path = read_io.path
        if path not in self.shared_paths:
            await self.inner.read(read_io)
            return
        prefix = _blob_prefix(
            self.uid, self.topology.slice_id, path, read_io.byte_range
        )
        session = self.transport_session
        skey = (self.topology.slice_id, path)
        if session is not None and not session.covers(skey):
            session = None
        loop = asyncio.get_running_loop()
        if path in self.local_publish_paths:
            if session is not None:
                if read_io.byte_range is not None:
                    # ranged reads (striped/codec extents) ride the KV
                    # blob path per byte range; tell the session
                    # promptly so siblings get "skip", not a timeout
                    session.decline(skey)
                else:
                    await self.inner.read(read_io)
                    self._m_durable.inc()
                    data = bytes(
                        memoryview(read_io.buf).cast("B")
                    )
                    accepted = await loop.run_in_executor(
                        None, session.offer, skey, data, prefix
                    )
                    if accepted:
                        # the session owns delivery now: broadcast on
                        # its schedule, or KV-publish from its drain
                        # path (its ledger, its cleanup)
                        return
                    entry = await self._publish_payload(
                        prefix, data, path
                    )
                    if entry is not None:
                        with self._pub_lock:
                            self._published.append(entry)
                    return
            await self._read_and_publish(read_io, prefix)
            return
        timeout_s = knobs.get_fanout_timeout_s()
        if session is not None and read_io.byte_range is None:
            data = await loop.run_in_executor(
                None, session.consume, skey
            )
            if data is not None and self._deliver(read_io, data):
                return
            # skipped / degraded / mismatched delivery: fall into the
            # KV ladder below — the session's drain path (or the
            # source's inline publish) feeds it
        data = await fetch_published(
            self.coordinator, prefix, path, timeout_s,
            transport=self._local_transport(),
        )
        if data is None:
            # designated reader silent past the deadline (dead, hung,
            # or its publish failed): re-elect.  The candidates
            # rotation is identical on every process, so the slice
            # agrees with zero communication that the NEXT candidate
            # takes over the read+publish while everyone else waits
            # one more bounded window for the takeover publication.
            cands = self.topology.reader_candidates(path)
            alternate = cands[1] if len(cands) > 1 else cands[0]
            if self.coordinator.rank == alternate:
                logger.warning(
                    "fan-out: designated reader rank %d published "
                    "nothing for %r within %gs; rank %d taking over "
                    "the slice read", cands[0], path, timeout_s,
                    alternate,
                )
                self._count_fallback(path)
                await self._read_and_publish(read_io, prefix)
                return
            data = await fetch_published(
                self.coordinator, prefix, path, timeout_s,
                transport=self._local_transport(),
            )
            if data is None:
                # both elected readers silent: every sibling reads
                # direct — in host-staggered waves (co-hosted
                # processes collapse via the shared-host cache's
                # single-flight; each host's wave starts one stagger
                # after the previous), so the durable tier sees a
                # ramp, never a synchronized burst
                self._count_fallback(path)
                hosts_in_order: list = []
                for r in cands:
                    h = self.topology.host_of[r]
                    if h not in hosts_in_order:
                        hosts_in_order.append(h)
                my_host = self.topology.host_of[self.coordinator.rank]
                pos = (
                    hosts_in_order.index(my_host)
                    if my_host in hosts_in_order
                    else len(hosts_in_order)
                )
                if pos:
                    await asyncio.sleep(_FALLBACK_STAGGER_S * pos)
                self._m_durable.inc()
                await self.inner.read(read_io)
                return
        if self._deliver(read_io, data):
            return
        self._count_fallback(path)
        self._m_durable.inc()
        await self.inner.read(read_io)

    def cleanup_published(self) -> None:
        """Delete this rank's transient publications — KV blob keys
        (meta key first, so a straggler's poll sees clean absence and
        takes the normal timeout-fallback path) and device-registry
        entries with their announce keys.  Called by restore strictly
        AFTER the last cross-rank barrier — every slice member is past
        its reads by then, so nothing can still be consuming a
        publication.  Best-effort: a failed delete leaks one restore's
        blobs until job teardown, never fails the restore."""
        with self._pub_lock:
            published, self._published = self._published, []
        for engine, prefix, nparts in published:
            try:
                if engine == "collective" and self.transport is not None:
                    self.transport.cleanup(prefix, nparts)
                else:
                    self.coordinator.kv_try_delete(f"{prefix}/meta")
                    for i in range(nparts):
                        self.coordinator.kv_try_delete(f"{prefix}/p{i}")
            except Exception as e:  # noqa: BLE001 — best-effort cleanup
                obs.swallowed_exception("topology.fanout.cleanup", e)

    # ------------------------------------------------- pass-throughs

    async def write(self, write_io: WriteIO) -> None:
        await self.inner.write(write_io)

    async def delete(self, path: str) -> None:
        await self.inner.delete(path)

    async def stat(self, path: str) -> int:
        return await self.inner.stat(path)

    async def link_from(self, base_url: str, path: str) -> None:
        await self.inner.link_from(base_url, path)

    async def close(self) -> None:
        await self.inner.close()
