"""The multislice topology model: rank → host → slice placement.

At 1k–10k-chip multislice scale the fleet is not flat: ranks within a
slice share fast ICI, slices talk over slower DCN, and durable storage
is slower still — so "who is co-located with whom" decides both where
replicated state should be WRITTEN (once per fleet, writers spread
across slices/hosts to balance per-slice durable egress) and how it
should be READ back (once per slice, redistributed to siblings over
the coordination layer).  ``Topology`` is the single source of truth
for that placement; ``detect_topology`` builds it:

- explicit spec (``TORCHSNAPSHOT_TPU_TOPOLOGY="0,0,1,1"``, identical on
  every process): zero-communication parse — the test/orchestrator
  path;
- ``"flat"``: topology awareness off (the pre-multislice behavior);
- ``"auto"``: per-process hints (``TOPOLOGY_SLICE_ID``/
  ``TOPOLOGY_HOST_ID`` knobs, the jax device ``slice_index`` on real
  multislice pods, the hostname) are exchanged once per operation over
  the coordination KV (``kv_exchange`` under the caller's uid prefix —
  every rank computes the identical map).

The descriptor is deliberately tiny and immutable: the partitioner's
pure-deterministic contract (identical assignment on every process
from identical inputs) extends to topology-aware assignment only
because the Topology itself is identical on every process.
"""

from __future__ import annotations

import json
import logging
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import knobs, obs

logger = logging.getLogger(__name__)

# The last-detected topology of this process, for flight-record
# attribution (obs.aggregate.rank_payload stamps the rank's slice/host
# so rank 0 can roll per-slice rows without a second exchange).
_CURRENT: Optional["Topology"] = None

# Auto-detection memo: placement (hostname, knob hints, jax
# slice_index) is static for a process's lifetime, so the O(world) KV
# gather runs once per (knob values, world, rank) instead of once per
# take/restore — at 1k ranks that's the difference between O(world²)
# KV gets per checkpoint step and O(world) sets.  The rank is part of
# the key so thread-per-rank test harnesses sharing one process each
# detect their own view.  Every rank still PUBLISHES its hint on every
# operation (one idempotent kv_set), so a peer whose cache key changed
# mid-job (knob flip) re-gathers without wedging on absent keys.
_DETECT_CACHE: Dict[Tuple, "Topology"] = {}


def _dense(ids: Sequence[Any]) -> Tuple[int, ...]:
    """Remap arbitrary (sortable-as-string) ids to dense 0..K-1, stable
    under the sorted order of their string forms — identical on every
    process given identical inputs."""
    order = {v: i for i, v in enumerate(sorted({str(x) for x in ids}))}
    return tuple(order[str(x)] for x in ids)


class Topology:
    """Immutable rank → (slice, host) placement for one job.

    ``explicit`` records whether the placement carries REAL co-location
    information (a spec or exchanged hints) vs the trivial fallback —
    auto behaviors (write spread, fan-out) only engage on explicit
    topologies, so a job that configured nothing behaves exactly as
    before this subsystem existed."""

    __slots__ = ("rank", "world_size", "slice_of", "host_of", "explicit")

    def __init__(
        self,
        rank: int,
        world_size: int,
        slice_of: Sequence[Any],
        host_of: Optional[Sequence[Any]] = None,
        explicit: bool = True,
    ) -> None:
        if len(slice_of) != world_size:
            raise ValueError(
                f"slice_of has {len(slice_of)} entries for "
                f"world_size={world_size}"
            )
        if host_of is not None and len(host_of) != world_size:
            raise ValueError(
                f"host_of has {len(host_of)} entries for "
                f"world_size={world_size}"
            )
        self.rank = rank
        self.world_size = world_size
        self.slice_of = _dense(slice_of)
        # unknown hosts default to one host per rank: no false
        # co-location, and host-load tie-breaks degrade to rank loads
        self.host_of = (
            _dense(host_of) if host_of is not None else tuple(range(world_size))
        )
        self.explicit = explicit

    @classmethod
    def flat(cls, rank: int, world_size: int) -> "Topology":
        """The trivial topology: one slice, one rank per host, no
        co-location knowledge — every topology-aware behavior off."""
        return cls(
            rank, world_size, (0,) * world_size, explicit=False
        )

    @classmethod
    def from_spec(cls, spec: str, rank: int, world_size: int) -> "Topology":
        """Parse an explicit per-rank slice list ("0,0,1,1").  Each
        element may optionally carry a host id ("0/h0,0/h1,...")."""
        fields = [f.strip() for f in spec.split(",") if f.strip()]
        if len(fields) != world_size:
            raise ValueError(
                f"topology spec has {len(fields)} entries for "
                f"world_size={world_size}: {spec!r}"
            )
        slices: List[str] = []
        hosts: List[Optional[str]] = []
        for f in fields:
            s, _, h = f.partition("/")
            slices.append(s)
            hosts.append(h or None)
        # "\x00" can never appear in a spec field, so a generated
        # placeholder for an unknown host can't collide with a
        # user-supplied host id (a collision would fabricate false
        # co-location — the dangerous direction)
        host_of = (
            [h if h is not None else f"\x00r{i}" for i, h in enumerate(hosts)]
            if any(h is not None for h in hosts)
            else None
        )
        return cls(rank, world_size, slices, host_of)

    # ------------------------------------------------------- structure

    @property
    def num_slices(self) -> int:
        return len(set(self.slice_of))

    @property
    def num_hosts(self) -> int:
        return len(set(self.host_of))

    @property
    def slice_id(self) -> int:
        return self.slice_of[self.rank]

    @property
    def host_id(self) -> int:
        return self.host_of[self.rank]

    def ranks_in_slice(self, slice_id: int) -> Tuple[int, ...]:
        return tuple(
            r for r in range(self.world_size)
            if self.slice_of[r] == slice_id
        )

    def hosts_in_slice(self, slice_id: int) -> Tuple[int, ...]:
        return tuple(
            sorted({self.host_of[r] for r in self.ranks_in_slice(slice_id)})
        )

    @property
    def multislice(self) -> bool:
        return self.num_slices > 1

    def co_located(self, a: int, b: int) -> bool:
        return self.host_of[a] == self.host_of[b]

    # ----------------------------------------------------- assignments

    def designated_reader(self, key: str, slice_id: Optional[int] = None) -> int:
        """The rank in ``slice_id`` (default: this rank's slice) that
        pulls ``key`` from the durable tier on behalf of its slice.
        Deterministic on every process; consecutive keys spread across
        the slice's members (hosts first, then ranks within a host) so
        per-host durable ingress stays balanced."""
        return self.reader_candidates(key, slice_id)[0]

    def reader_candidates(
        self, key: str, slice_id: Optional[int] = None
    ) -> Tuple[int, ...]:
        """The slice's FAILOVER ORDER for reading ``key``: every member
        rank, rotated in the stable (host, rank) order so the designated
        reader comes first.  Identical on every process, so when the
        designated reader dies mid-restore the siblings agree — with no
        extra communication — that ``candidates[1]`` takes over the
        durable read and the publication (fanout.py re-election)."""
        members = self.ranks_in_slice(
            self.slice_id if slice_id is None else slice_id
        )
        ordered = sorted(members, key=lambda r: (self.host_of[r], r))
        idx = zlib.crc32(key.encode()) % len(ordered)
        return tuple(ordered[idx:] + ordered[:idx])

    def replica_preference(self, rank: Optional[int] = None) -> Tuple[int, ...]:
        """Every OTHER rank, ordered best-replica-target-first for
        ``rank`` (default: this rank): different-SLICE ranks before
        same-slice ones, different-HOST before co-hosted within each
        group, ring distance as the deterministic tiebreak.  A slice
        preemption takes out every host in the slice at once, so a
        replica that survives it must live across the slice boundary —
        same-slice (and worst, same-host) targets are kept only as the
        tail so a single-slice job still gets its ring placement.
        Pure and identical on every process (same inputs), like every
        other Topology assignment."""
        r = self.rank if rank is None else rank
        n = self.world_size
        return tuple(
            sorted(
                (c for c in range(n) if c != r),
                key=lambda c: (
                    self.slice_of[c] == self.slice_of[r],
                    self.host_of[c] == self.host_of[r],
                    (c - r) % n,
                ),
            )
        )

    def describe(self) -> Dict[str, Any]:
        """Small JSON-safe summary for flight records / logs."""
        return {
            "slice": self.slice_id,
            "host": self.host_id,
            "num_slices": self.num_slices,
            "num_hosts": self.num_hosts,
            "explicit": self.explicit,
        }


def replica_candidate_order(
    topology: Optional["Topology"], rank: int, n: int
) -> Tuple[int, ...]:
    """The ONE candidate ordering every replica-placement site uses
    (tier/plugin.py targets, the continuous loop's peer choice and its
    recovery probe order): ``Topology.replica_preference`` when the
    topology is explicit AND sized for the peer list, else the
    successor ring — byte-identical to the pre-topology placement.
    Centralized so write-side placement and read-side probing can
    never diverge on the rule."""
    if (
        topology is not None
        and getattr(topology, "explicit", False)
        and topology.world_size == n
    ):
        return topology.replica_preference(rank)
    return tuple((rank + d) % n for d in range(1, n))


def current_topology_info() -> Optional[Dict[str, Any]]:
    """The last-detected topology's summary (flight-record stamp), or
    None when nothing EXPLICIT was detected — flat/unconfigured jobs
    keep their flight records free of a topology section nobody
    configured."""
    if _CURRENT is None or not _CURRENT.explicit:
        return None
    return _CURRENT.describe()


def _jax_slice_hint() -> Optional[int]:
    """The local jax device's multislice ``slice_index``, when the
    process is part of an initialized multi-controller job — never
    triggers a backend init (a tunneled backend's init can block for
    minutes, and a single-process run has nothing to detect)."""
    try:
        from jax._src import distributed

        if distributed.global_state.client is None:
            return None
        import jax

        idx = getattr(jax.local_devices()[0], "slice_index", None)
        return int(idx) if idx is not None else None
    except Exception as e:  # noqa: BLE001 — detection is best-effort
        obs.swallowed_exception("topology.jax_slice_hint", e)
        return None


def _host_hint() -> str:
    override = knobs.get_topology_host_id()
    if override:
        return override
    import socket

    return socket.gethostname()


def detect_topology(
    coordinator: Any,
    exchange_prefix: Optional[str] = None,
    slice_hint: Optional[int] = None,
    host_hint: Optional[str] = None,
) -> Topology:
    """Build this job's Topology (see module docstring).  In "auto"
    mode with world > 1 this performs ONE kv_exchange under
    ``exchange_prefix`` (callers derive it from their operation uid so
    every take/restore's exchange uses fresh keys; when omitted, the
    per-instance uid counter names it — foreground program order only).
    ``slice_hint``/``host_hint`` override the knob/jax/hostname probes
    for tests and embedders that know their placement."""
    with obs.span("topology/detect", rank=coordinator.rank):
        rank, world = coordinator.rank, coordinator.world_size
        spec = knobs.get_topology()
        if spec == "flat":
            topo = Topology.flat(rank, world)
        elif spec != "auto":
            try:
                topo = Topology.from_spec(spec, rank, world)
            except ValueError as e:
                logger.warning(
                    "rank %d: unusable TOPOLOGY spec (%s); running flat",
                    rank, e,
                )
                topo = Topology.flat(rank, world)
        else:
            s_hint = (
                slice_hint
                if slice_hint is not None
                else knobs.get_topology_slice_id()
            )
            if s_hint is None:
                s_hint = _jax_slice_hint()
            h_hint = host_hint if host_hint is not None else _host_hint()
            if world == 1:
                topo = Topology(
                    rank, 1, (0,), (0,), explicit=s_hint is not None
                )
            else:
                if exchange_prefix is None:
                    exchange_prefix = coordinator._next_uid("topo")
                # publish ALWAYS (idempotent, one kv_set) so a peer
                # re-detecting under this operation's prefix never
                # waits on a key a cache-hitting rank skipped
                coordinator.kv_set(
                    f"{exchange_prefix}/{rank}",
                    json.dumps([s_hint, h_hint]),
                )
                cache_key = (spec, s_hint, h_hint, world, rank)
                cached = _DETECT_CACHE.get(cache_key)
                if cached is not None:
                    topo = cached
                else:
                    gathered = [
                        json.loads(
                            coordinator.kv_get(f"{exchange_prefix}/{r}")
                        )
                        for r in range(world)
                    ]
                    slice_hints = [g[0] for g in gathered]
                    hosts = [str(g[1]) for g in gathered]
                    known = [s for s in slice_hints if s is not None]
                    if known and len(known) != world:
                        # mixed hints are a misconfiguration (some
                        # ranks placed, others not) — co-location
                        # claims built on them would be wrong in the
                        # dangerous direction
                        logger.warning(
                            "rank %d: %d/%d ranks reported a slice "
                            "hint; ignoring partial placement and "
                            "running flat",
                            rank, len(known), world,
                        )
                    explicit = len(known) == world
                    slices = slice_hints if explicit else [0] * world
                    topo = Topology(
                        rank, world, slices, hosts, explicit=explicit
                    )
                    _DETECT_CACHE[cache_key] = topo
        global _CURRENT
        _CURRENT = topo
        obs.gauge(obs.TOPOLOGY_SLICES).set(topo.num_slices)
        return topo
