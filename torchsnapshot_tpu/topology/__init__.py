"""Hierarchical multislice topology: the rank → host → slice model,
DCN-aware write partitioning hooks, and the fan-out restore.

- ``model.py`` — the ``Topology`` descriptor and ``detect_topology``
  (explicit spec / per-process hints / jax multislice probe, exchanged
  once per operation over the coordination KV).
- ``fanout.py`` — read-once-per-slice restore: designated per-slice
  reader ranks pull each replicated object from the durable tier
  exactly once and redistribute the bytes to siblings over the
  coordination layer (chunked KV blobs, digest-verified, direct-read
  fallback on reader death).

The write-side half lives in ``partitioner.py`` /
``preparers/sharded.py``, which accept a ``Topology`` to spread
replicated and sharded-replica writers across slices and hosts.
See docs/multislice.md.
"""

from .fanout import (  # noqa: F401
    FanoutReadPlugin,
    fanout_enabled,
    fanout_world_uniform,
    fetch_published,
    ordered_shared_locations,
    publish_object,
    shared_read_locations,
)
from .model import (  # noqa: F401
    Topology,
    current_topology_info,
    detect_topology,
    replica_candidate_order,
)

__all__ = [
    "Topology",
    "detect_topology",
    "current_topology_info",
    "replica_candidate_order",
    "FanoutReadPlugin",
    "fanout_enabled",
    "fanout_world_uniform",
    "shared_read_locations",
    "ordered_shared_locations",
    "publish_object",
    "fetch_published",
]
