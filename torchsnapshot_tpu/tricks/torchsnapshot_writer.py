"""Write checkpoints the reference library can restore.

The mirror of ``read_torchsnapshot``: a JAX pytree exports into the
reference's on-disk format, so a checkpoint trained here hands back to a
torch job (or any reference-era tooling) with no JAX on the other side.

Format produced (reference, by file:line — same contract the reader
documents):

- ``.snapshot_metadata``: JSON (their YAML loader accepts it —
  manifest.py:442-475), ``version 0.1.0``, ``world_size 1``.
- One ``Tensor`` entry per array leaf, serializer ``buffer_protocol``
  (raw C-order bytes, serialization.py:177-265), torch dtype names.
- Containers (``dict``/``list``) and inline primitives with the
  reference's codecs (manifest.py:335-400); ``/`` in keys %-escaped
  (flatten.py:215-226).

Sharded/global jax.Arrays are consolidated to dense host arrays first
(the export targets a single-process reference restore — exporting a
sharded layout would require the destination's process topology, which
a torch-side job defines, not us).

Dtypes without a torch equivalent that buffer-protocol restore handles
(e.g. fp8) raise; bf16 exports fine (torch.bfloat16).
"""

from __future__ import annotations

import base64
import json
import struct
from typing import Any, Dict, List, Tuple

import numpy as np

from ..io_types import WriteIO
from ..utils.asyncio_utils import run_in_fresh_loop

_NP_TO_TORCH: List[Tuple[str, str]] = [
    ("float32", "torch.float32"),
    ("float64", "torch.float64"),
    ("float16", "torch.float16"),
    ("bfloat16", "torch.bfloat16"),
    ("int8", "torch.int8"),
    ("int16", "torch.int16"),
    ("int32", "torch.int32"),
    ("int64", "torch.int64"),
    ("uint8", "torch.uint8"),
    ("bool", "torch.bool"),
    ("complex64", "torch.complex64"),
    ("complex128", "torch.complex128"),
]


def _torch_dtype_name(dtype: np.dtype) -> str:
    name = dtype.name
    for np_name, torch_name in _NP_TO_TORCH:
        if name == np_name:
            return torch_name
    raise ValueError(
        f"dtype {name!r} has no reference (torch) equivalent — cast the "
        f"leaf before exporting"
    )


def _encode_key(key: str) -> str:
    # reference flatten._encode (flatten.py:215-222): RFC-3986 subset
    return key.replace("%", "%25").replace("/", "%2F")


def _primitive_entry(obj: Any) -> Dict[str, Any]:
    if isinstance(obj, bool):  # before int: bool is an int subclass
        t, sv = "bool", str(obj)
    elif isinstance(obj, int):
        t, sv = "int", str(obj)
    elif isinstance(obj, str):
        t, sv = "str", obj
    elif isinstance(obj, bytes):
        t, sv = "bytes", base64.b64encode(obj).decode()
    elif isinstance(obj, float):
        t = "float"
        sv = base64.b64encode(struct.pack("d", obj)).decode()
    else:
        raise TypeError(f"not a primitive: {type(obj)}")
    return {
        "type": t,
        "serialized_value": sv,
        "replicated": False,
        "readable": None,
    }


def _to_host_array(obj: Any) -> np.ndarray:
    """Dense host array from numpy / (possibly sharded) jax.Array."""
    mod = type(obj).__module__.split(".")[0]
    if mod in ("jax", "jaxlib"):
        import jax

        if isinstance(obj, jax.Array):
            if not obj.is_fully_addressable:
                raise ValueError(
                    "cannot export a partially-addressable array from one "
                    "process; gather it (e.g. jax.device_get on a fully-"
                    "replicated resharding) first"
                )
            return np.asarray(jax.device_get(obj))
    return np.asarray(obj)


def write_torchsnapshot(path: str, app_state: Dict[str, Any]) -> None:
    """Export ``{key: pytree-or-Stateful}`` as a reference-format
    snapshot that ``torchsnapshot.Snapshot(path).restore(...)`` (or
    ``read_object``) consumes directly.

    Array leaves become ``Tensor`` entries; int/str/bool/float/bytes are
    inlined; dicts and lists/tuples become containers.  State is taken
    via ``state_dict()`` when the value is Stateful, else used as-is.
    """
    from ..storage import url_to_storage_plugin

    manifest: Dict[str, Any] = {}
    # (location, source leaf) — bytes materialize inside the bounded
    # write tasks, so peak extra host memory is ~concurrency leaves, not
    # the whole checkpoint (which is exactly what a migration exports)
    writes: List[Tuple[str, Any]] = []

    def visit(logical: str, obj: Any) -> None:
        if hasattr(obj, "state_dict") and not isinstance(
            obj, (dict, list, tuple, np.ndarray)
        ):
            obj = obj.state_dict()
        if isinstance(obj, dict):
            str_keys = [str(k) for k in obj.keys()]
            if len(set(str_keys)) < len(str_keys):
                # the reference raises on this too (flatten.py:144-162):
                # colliding coerced keys would silently drop a leaf
                raise ValueError(
                    f"dict at {logical!r} has keys that collide under "
                    f"str(): {sorted(obj.keys(), key=str)!r}"
                )
            manifest[logical] = {
                "type": "dict",
                # int keys stay ints: DictEntry.keys is
                # List[Union[str, int]] (reference manifest.py:320)
                "keys": [
                    k if isinstance(k, int) else str(k) for k in obj.keys()
                ],
            }
            for k, v in obj.items():
                visit(f"{logical}/{_encode_key(str(k))}", v)
            return
        if isinstance(obj, (list, tuple)):
            manifest[logical] = {"type": "list"}
            for i, v in enumerate(obj):
                visit(f"{logical}/{i}", v)
            return
        if isinstance(obj, (bool, int, str, bytes, float)):
            manifest[logical] = _primitive_entry(obj)
            return
        source = obj
        if not (hasattr(obj, "dtype") and hasattr(obj, "shape")):
            obj = np.asarray(obj)  # np scalars / 0-d oddities: tiny
        if np.dtype(obj.dtype) == np.dtype(object):
            # e.g. None in optimizer state: the reference round-trips it
            # as a pickled object entry; this exporter is pickle-free, so
            # name the leaf and its actual value instead of letting
            # _torch_dtype_name fail on dtype('O') with no logical path
            raise ValueError(
                f"leaf {logical!r} is not exportable: "
                f"{type(source).__name__} value {source!r:.80} has no "
                f"torchsnapshot Tensor/primitive equivalent (the "
                f"reference stores such leaves as pickled objects). Drop "
                f"it or convert it to an array/primitive before exporting."
            )
        if getattr(obj, "is_fully_addressable", True) is False:
            # cheap metadata check kept at PLAN time: failing inside the
            # async write tasks would upload sibling leaves first and
            # leave partial junk in the destination
            raise ValueError(
                f"{logical!r} is a partially-addressable jax.Array; gather "
                f"it (e.g. jax.device_get on a fully-replicated resharding) "
                f"before exporting"
            )
        location = logical  # one object per leaf: no byte_range needed
        # dtype/shape come from the leaf's metadata — the host
        # materialization (device_get for jax leaves) is deferred to the
        # bounded write task, so exporting a device-resident checkpoint
        # never holds the whole payload on the host at once
        manifest[logical] = {
            "type": "Tensor",
            "location": location,
            "serializer": "buffer_protocol",
            "dtype": _torch_dtype_name(np.dtype(obj.dtype)),
            "shape": [int(s) for s in obj.shape],
            "replicated": False,
        }
        writes.append((location, obj))

    for key in sorted(app_state):
        visit(f"0/{key}", app_state[key])

    metadata = {"version": "0.1.0", "world_size": 1, "manifest": manifest}
    storage = url_to_storage_plugin(path)
    try:

        async def flush() -> None:
            import asyncio

            sem = asyncio.Semaphore(16)

            async def one(loc: str, leaf: Any) -> None:
                async with sem:
                    # host materialization (device_get for jax leaves)
                    # AND .tobytes() (C-order bytes regardless of
                    # layout) happen here, under the semaphore, and are
                    # dropped as soon as the write lands
                    data = _to_host_array(leaf).tobytes()
                    await storage.write(WriteIO(path=loc, buf=data))

            await asyncio.gather(*(one(l, a) for l, a in writes))
            # metadata LAST: its presence is the reference's commit
            # marker (snapshot.py:202-209)
            await storage.write(
                WriteIO(
                    path=".snapshot_metadata",
                    buf=json.dumps(metadata, indent=2).encode(),
                    durable=True,
                )
            )

        run_in_fresh_loop(flush())
    finally:
        storage.sync_close()
