"""Orbax interop: migrate checkpoints between orbax and torchsnapshot_tpu.

Most existing JAX training setups checkpoint with orbax; these helpers let
a user switch frameworks (either direction) without retraining — the role
the reference's DeepSpeed/FSDP tricks play for users migrating between
torch checkpoint formats (tricks/deepspeed.py:19-103).
"""

from __future__ import annotations

import os
from typing import Any, Optional


def export_to_orbax(path: str, tree: Any) -> None:
    """Write a pytree as an orbax StandardCheckpoint."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), tree)


def import_from_orbax(path: str, template: Optional[Any] = None) -> Any:
    """Read an orbax StandardCheckpoint into a pytree; ``template`` (a
    matching pytree of arrays/ShapeDtypeStructs with shardings) drives
    placement, mirroring Snapshot.restore's template semantics."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        if template is not None:
            return ckptr.restore(os.path.abspath(path), template)
        return ckptr.restore(os.path.abspath(path))


def migrate_orbax_to_snapshot(
    orbax_path: str, snapshot_path: str, key: str = "state"
) -> None:
    """orbax checkpoint → torchsnapshot_tpu snapshot (one app-state key)."""
    from ..snapshot import Snapshot
    from ..stateful import PyTreeState, StateDict

    tree = import_from_orbax(orbax_path)
    # Dict-rooted trees (the orbax norm) go through StateDict so the raw
    # containers reach flatten untouched: lists stay ListEntries and
    # None leaves survive, keeping migrate_snapshot_to_orbax's inflate a
    # faithful inverse.  (PyTreeState's named rendering would rewrite
    # lists as string-keyed dicts and drop None — jax treats None as an
    # empty subtree.)  Non-dict roots fall back to PyTreeState, whose
    # named paths match what a direct snapshot of that tree would use.
    stateful = StateDict(tree) if isinstance(tree, dict) else PyTreeState(tree)
    Snapshot.take(snapshot_path, {key: stateful})


def migrate_snapshot_to_orbax(
    snapshot_path: str, orbax_path: str, key: str = "state"
) -> None:
    """torchsnapshot_tpu snapshot → orbax checkpoint (one app-state key).

    Exports **rank 0's view** (plus all replicated and merged sharded
    entries). Per-rank state saved exclusively by other ranks is not part
    of that view; a warning is emitted when any exists under ``key``.
    """
    import logging

    from ..flatten import inflate
    from ..manifest import is_container_entry
    from ..manifest_ops import get_manifest_for_rank
    from ..preparers import prepare_read
    from ..scheduler import get_process_memory_budget_bytes, sync_execute_read_reqs
    from ..snapshot import Snapshot
    from ..storage import url_to_storage_plugin

    snap = Snapshot(snapshot_path)
    metadata = snap.metadata
    manifest = get_manifest_for_rank(metadata, 0)
    if metadata.world_size > 1:
        dropped = {
            k.partition("/")[2]
            for k in metadata.manifest
            if not k.startswith("0/")
        }
        dropped = {
            p
            for p in dropped
            if (p == key or p.startswith(key + "/")) and p not in manifest
        }
        if dropped:
            logging.getLogger(__name__).warning(
                "exporting rank 0's view only; %d per-rank entries from "
                "other ranks are not included (e.g. %s)",
                len(dropped),
                sorted(dropped)[0],
            )
    # rebuild the key's subtree without templates (host arrays)

    key_manifest = {
        p: e for p, e in manifest.items() if p == key or p.startswith(key + "/")
    }
    if not key_manifest:
        raise KeyError(f"{key!r} not in snapshot")
    containers = {}
    read_reqs = []
    futures = {}
    for lpath, entry in key_manifest.items():
        if is_container_entry(entry):
            containers[lpath] = entry
            continue
        reqs, fut = prepare_read(entry)
        read_reqs.extend(reqs)
        futures[lpath] = fut
    storage = url_to_storage_plugin(snapshot_path)
    cas_reads = snap._cas_reads()
    try:
        sync_execute_read_reqs(
            read_reqs, storage, get_process_memory_budget_bytes(), rank=0,
            # codec-compressed objects must decode here like every other
            # read path — otherwise the export writes frame bytes — and
            # chunk-ref'd objects (cas/) must assemble from the pool
            # (they have no per-step storage object at all)
            codec_tables=snap._codec_tables(),
            cas_reads=cas_reads,
        )
    finally:
        storage.sync_close()
        if cas_reads is not None:
            cas_reads[0].sync_close()
    tree = inflate(containers, {p: f.obj for p, f in futures.items()}, prefix=key)
    export_to_orbax(orbax_path, tree)
