"""Adapters for torch (CPU) modules/optimizers.

Reference: torchsnapshot/tricks/ddp.py:17-47 — a DDP-wrapped module saves
keys prefixed with ``module.``; the adapter strips the prefix on save and
re-adds it on load so checkpoints interchange between wrapped and
unwrapped models.
"""

from __future__ import annotations

from typing import Any, Dict

_DDP_PREFIX = "module."


class TorchModuleAdapter:
    def __init__(self, module: Any) -> None:
        self.module = module

    def state_dict(self) -> Dict[str, Any]:
        sd = self.module.state_dict()
        if all(k.startswith(_DDP_PREFIX) for k in sd):
            sd = {k[len(_DDP_PREFIX) :]: v for k, v in sd.items()}
        return sd

    def load_state_dict(self, state_dict: Dict[str, Any], strict: bool = True) -> None:
        own = self.module.state_dict()
        if own and all(k.startswith(_DDP_PREFIX) for k in own):
            state_dict = {
                k if k.startswith(_DDP_PREFIX) else _DDP_PREFIX + k: v
                for k, v in state_dict.items()
            }
        self.module.load_state_dict(state_dict, strict=strict)


class TorchOptimizerAdapter:
    """Routes optimizer state through the optimizer's own (de)hydration —
    and converts numpy leaves back to torch tensors on load: when the
    restoring optimizer has no state yet (fresh process), the snapshot has
    no tensor templates to restore into, so array leaves come back as
    numpy (the FSDP-trick analogue, reference tricks/fsdp.py:39-51)."""

    def __init__(self, optimizer: Any) -> None:
        self.optimizer = optimizer

    def state_dict(self) -> Dict[str, Any]:
        return self.optimizer.state_dict()

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        import numpy as np
        import torch

        def conv(x: Any) -> Any:
            if isinstance(x, np.ndarray):
                return torch.from_numpy(np.ascontiguousarray(x))
            if isinstance(x, dict):
                return {k: conv(v) for k, v in x.items()}
            if isinstance(x, list):
                return [conv(v) for v in x]
            if isinstance(x, tuple):
                return tuple(conv(v) for v in x)
            return x

        self.optimizer.load_state_dict(conv(state_dict))
