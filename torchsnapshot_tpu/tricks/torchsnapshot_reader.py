"""Read checkpoints written by facebookresearch/torchsnapshot.

The one thing a migrating user can't regenerate is their trained
checkpoints.  This module reads the reference's on-disk format directly
into host arrays / python values, so a reference-era snapshot restores
into a JAX training state with no torch run required (torch IS required
only for ``torch_save``-serialized payloads).

Format contract implemented here (reference, by file:line):

- ``.snapshot_metadata`` is JSON (a YAML subset, written via json.dumps
  for speed — manifest.py:442-448); entries are tagged unions dispatched
  on ``type`` (manifest.py:450-475).
- Manifest keys are ``<rank>/<logical_path>`` per-rank views
  (io_preparer.py:52-61); ``/`` inside user dict keys is %-escaped
  (flatten.py:215-226, RFC-3986 subset).
- Containers: ``dict``/``OrderedDict`` carry ``keys``; ``list`` children
  sit at integer path components (flatten.py:20-77).
- Primitives are inlined: int/str/bool as strings, bytes as base64,
  float as base64-packed little-endian f64 (manifest.py:335-400).
- ``Tensor`` entries: ``location`` (+ optional ``byte_range``),
  ``serializer`` ∈ {buffer_protocol, torch_save}, ``dtype`` like
  ``torch.bfloat16``, ``shape`` (manifest.py:49-95).  buffer_protocol is
  raw C-order bytes (serialization.py:177-265).
- ``ChunkedTensor``: ``chunks`` of {offsets, sizes, tensor}
  (manifest.py:171-210); ``ShardedTensor``: ``shards`` of the same shape
  (manifest.py:118-168), with each rank's manifest listing only its own
  shards — the full tensor is the union across rank views;
  ``DTensor`` adds mesh/dim_map metadata and possibly-duplicated
  replicated shards (manifest.py:211-261).
- ``object`` entries are ``torch.save`` pickles (io_preparers/object.py)
  — decoded only when the pickle knob allows.
"""

from __future__ import annotations

import base64
import json
import struct
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import unquote

import numpy as np

import logging

from .. import knobs
from ..io_types import ReadIO
from ..utils.asyncio_utils import run_in_fresh_loop

logger = logging.getLogger(__name__)

_TORCH_DTYPES: Dict[str, Any] = {}

# one summary warning per read_torchsnapshot call, not one per decoded
# piece (a torchrec checkpoint can hold hundreds of quantized tables,
# and a chunked tensor decodes many pieces)
_quant_warned = False


def _warn_dequantized(kind: str, dtype: Any) -> None:
    global _quant_warned
    if _quant_warned:
        return
    _quant_warned = True
    logger.warning(
        "importing quantized payload(s) (first: %s, dtype %s): "
        "dequantized to float32 — JAX has no affine-quantized dtype, so "
        "scales/zero-points are consumed by the import; re-quantize "
        "after migration if needed (warning shown once per import)",
        kind, dtype,
    )


def _np_dtype(torch_name: str) -> np.dtype:
    if not _TORCH_DTYPES:
        import ml_dtypes

        _TORCH_DTYPES.update(
            {
                "torch.float32": np.dtype(np.float32),
                "torch.float64": np.dtype(np.float64),
                "torch.float16": np.dtype(np.float16),
                "torch.bfloat16": np.dtype(ml_dtypes.bfloat16),
                "torch.int8": np.dtype(np.int8),
                "torch.int16": np.dtype(np.int16),
                "torch.int32": np.dtype(np.int32),
                "torch.int64": np.dtype(np.int64),
                "torch.uint8": np.dtype(np.uint8),
                "torch.bool": np.dtype(np.bool_),
                "torch.complex64": np.dtype(np.complex64),
                "torch.complex128": np.dtype(np.complex128),
                "torch.float8_e4m3fn": np.dtype(ml_dtypes.float8_e4m3fn),
                "torch.float8_e5m2": np.dtype(ml_dtypes.float8_e5m2),
            }
        )
    try:
        return _TORCH_DTYPES[torch_name]
    except KeyError:
        raise ValueError(
            f"unsupported reference dtype {torch_name!r} — no numpy/"
            f"ml_dtypes equivalent (quantized payloads import via their "
            f"own serializers and dequantize to float32)"
        ) from None


# quantized storage dtypes: underlying integer layout per element
# (reference serialization.py:85-87,105-108)
_QTENSOR_STORAGE = {
    "torch.qint8": np.dtype(np.int8),
    "torch.quint8": np.dtype(np.uint8),
    "torch.qint32": np.dtype(np.int32),
}
_QTENSOR_SERIALIZERS = ("per_tensor_qtensor", "per_channel_qtensor")


def _decode_qtensor(
    data: bytes, serializer: str, dtype: str, shape: List[int]
) -> np.ndarray:
    """Decode the reference's custom quantized-tensor payloads
    (serialization.py:278-477), dequantizing to float32 — JAX has no
    affine-quantized dtype, so the import surfaces VALUES, with a
    warning that the quantization parameters are consumed.

    per_tensor (serialization.py:278-311):
      int storage | f64 q_scale | i64 q_zero_point
    per_channel (serialization.py:368-409):
      i64 axis | int storage | f64 scales[shape[axis]] |
      i64 zero_points[shape[axis]]
    Dequantization: (int_value - zero_point) * scale.
    """
    storage_dtype = _QTENSOR_STORAGE.get(dtype)
    if storage_dtype is None:
        raise ValueError(
            f"{serializer} entry with non-quantized dtype {dtype!r}"
        )
    n = 1
    for s in shape:
        n *= s
    data_sz = n * storage_dtype.itemsize
    if serializer == "per_tensor_qtensor":
        if len(data) != data_sz + 16:
            raise ValueError(
                f"per_tensor_qtensor payload is {len(data)} bytes; "
                f"dtype {dtype} shape {tuple(shape)} implies {data_sz + 16}"
            )
        ints = np.frombuffer(data, storage_dtype, count=n).reshape(shape)
        (scale,) = struct.unpack("d", data[data_sz : data_sz + 8])
        (zero_point,) = struct.unpack("q", data[data_sz + 8 : data_sz + 16])
        out = ((ints.astype(np.float64) - zero_point) * scale).astype(
            np.float32
        )
    else:
        (axis,) = struct.unpack("q", data[:8])
        if not 0 <= axis < len(shape):
            raise ValueError(
                f"per_channel_qtensor axis {axis} invalid for shape "
                f"{tuple(shape)}"
            )
        ch = shape[axis]
        if len(data) != 8 + data_sz + 16 * ch:
            raise ValueError(
                f"per_channel_qtensor payload is {len(data)} bytes; dtype "
                f"{dtype} shape {tuple(shape)} axis {axis} implies "
                f"{8 + data_sz + 16 * ch}"
            )
        ints = np.frombuffer(data, storage_dtype, count=n, offset=8).reshape(
            shape
        )
        scales = np.frombuffer(data, np.float64, count=ch, offset=8 + data_sz)
        zero_points = np.frombuffer(
            data, np.int64, count=ch, offset=8 + data_sz + 8 * ch
        )
        bshape = [1] * len(shape)
        bshape[axis] = ch
        out = (
            (ints.astype(np.float64) - zero_points.reshape(bshape))
            * scales.reshape(bshape)
        ).astype(np.float32)
    _warn_dequantized(serializer, dtype)
    return out


def _read_bytes(storage, location: str, byte_range: Optional[List[int]]) -> bytes:
    read_io = ReadIO(
        path=location,
        byte_range=tuple(byte_range) if byte_range else None,
    )
    run_in_fresh_loop(storage.read(read_io))
    return bytes(memoryview(read_io.buf).cast("B"))


class _BlobCache:
    """Prefetches every blob the manifest references with ONE event loop
    and bounded concurrency, so a many-entry checkpoint on object
    storage doesn't pay per-blob loop setup + serial latency.

    Each prefetched blob is refcounted by how many consuming leaves
    reference it (replicated shards can share one key) and EVICTED as
    its last ``get`` is served: without eviction, peak host memory
    during an import is raw-blobs + assembled-arrays (~2x checkpoint
    size), and a checkpoint that fits in RAM once can OOM mid-decode.
    With it, raw bytes shrink as assembled arrays grow, holding the sum
    near 1x."""

    def __init__(self, storage, concurrency: int = 16) -> None:
        self._storage = storage
        self._concurrency = concurrency
        self._blobs: Dict[Tuple[str, Optional[Tuple[int, int]]], bytes] = {}
        self._refs: Dict[Tuple[str, Optional[Tuple[int, int]]], int] = {}

    @staticmethod
    def _key(entry: dict) -> Tuple[str, Optional[Tuple[int, int]]]:
        br = entry.get("byte_range")
        return entry["location"], (tuple(br) if br else None)

    def prefetch(self, tensorish_entries: List[dict]) -> None:
        import asyncio

        keys = []
        for entry in tensorish_entries:
            for sub in (
                entry.get("chunks") or entry.get("shards") or [entry]
            ):
                tensor = sub.get("tensor", sub)
                if "location" in tensor:
                    keys.append(self._key(tensor))
        for k in keys:
            self._refs[k] = self._refs.get(k, 0) + 1
        keys = [k for k in dict.fromkeys(keys) if k not in self._blobs]

        async def fetch_all() -> None:
            sem = asyncio.Semaphore(self._concurrency)

            async def one(key):
                loc, br = key
                async with sem:
                    read_io = ReadIO(path=loc, byte_range=br)
                    await self._storage.read(read_io)
                self._blobs[key] = bytes(memoryview(read_io.buf).cast("B"))

            await asyncio.gather(*(one(k) for k in keys))

        if keys:
            run_in_fresh_loop(fetch_all())

    def get(self, entry: dict) -> bytes:
        key = self._key(entry)
        if key in self._blobs:
            data = self._blobs[key]
        else:
            data = _read_bytes(self._storage, key[0], key[1])
            if self._refs.get(key, 0) > 1:  # more consumers coming
                self._blobs[key] = data
        n = self._refs.get(key, 0)
        if n <= 1:
            self._refs.pop(key, None)
            self._blobs.pop(key, None)  # last consumer: evict
        else:
            self._refs[key] = n - 1
        return data


def _decode_primitive(entry: dict) -> Any:
    t, sv = entry["type"], entry["serialized_value"]
    if t == "int":
        return int(sv)
    if t == "str":
        return sv
    if t == "bool":
        if sv not in ("True", "False"):
            raise ValueError(f"bad bool serialized_value {sv!r}")
        return sv == "True"
    if t == "bytes":
        return base64.b64decode(sv.encode())
    if t == "float":
        return struct.unpack("d", base64.b64decode(sv.encode()))[0]
    raise ValueError(f"unknown primitive type {t!r}")


def _decode_tensor(blobs: "_BlobCache", entry: dict) -> np.ndarray:
    data = blobs.get(entry)
    if entry.get("serializer") in _QTENSOR_SERIALIZERS:
        return _decode_qtensor(
            data, entry["serializer"], entry["dtype"], entry["shape"]
        )
    if entry.get("serializer") == "torch_save":
        tensor = _torch_load(data)
        if getattr(tensor, "is_quantized", False):
            # the CURRENT reference serializes quantized tensors via
            # torch_save (io_preparers/tensor.py:70-73 falls back for
            # any non-buffer-protocol dtype); the custom qtensor
            # serializers below cover older-format snapshots
            _warn_dequantized("torch_save", tensor.dtype)
            return tensor.dequantize().numpy().astype(np.float32)
        try:
            return tensor.numpy()
        except TypeError:
            raise ValueError(
                f"torch_save tensor of dtype {tensor.dtype} has no numpy "
                f"equivalent — cast the leaf before saving, or load this "
                f"snapshot once with the reference library"
            ) from None
    dtype = _np_dtype(entry["dtype"])
    arr = np.frombuffer(data, dtype=dtype)
    return arr.reshape(entry["shape"]).copy()


def _torch_load(data: bytes) -> Any:
    if not knobs.is_pickle_allowed():
        raise RuntimeError(
            "entry uses the reference's torch_save (pickle) serializer; "
            "decoding requires TORCHSNAPSHOT_TPU_ALLOW_PICKLE_OBJECTS=1 "
            "and must only be used on trusted snapshots"
        )
    import io

    import torch

    return torch.load(io.BytesIO(data), weights_only=False)


def _dedup_pieces(pieces: List[dict]) -> List[dict]:
    """Replicated shards repeat the same box across rank views; keep one
    per (offsets, sizes) so coverage accounting and reads stay exact."""
    seen = {}
    for piece in pieces:
        seen.setdefault(
            (tuple(piece["offsets"]), tuple(piece["sizes"])), piece
        )
    return list(seen.values())


def _dtensor_expected_boxes(entry: dict) -> Optional[int]:
    """How many distinct shard boxes a DTensor's mesh+dim_map implies
    (the product of mesh-dim sizes that appear in dim_map; reference
    manifest.py:222-261) — lets a union-derived shape detect a LOST
    shard that bounding-box derivation alone cannot."""
    mesh, dim_map = entry.get("mesh"), entry.get("dim_map")
    if mesh is None or dim_map is None:
        return None
    try:
        mesh_shape = np.asarray(mesh).shape
        sharded_mesh_dims = {
            md
            for dm in dim_map
            for md in (dm if isinstance(dm, (list, tuple)) else [dm])
            if md is not None and md >= 0
        }
        n = 1
        for md in sharded_mesh_dims:
            if md < len(mesh_shape):
                n *= mesh_shape[md]
        return n
    except Exception:  # malformed mesh metadata: skip the extra check
        return None


def _assemble_pieces(
    blobs: "_BlobCache",
    shape: List[int],
    dtype: str,
    pieces: List[dict],
    expected_boxes: Optional[int] = None,
) -> np.ndarray:
    """Paste {offsets, sizes, tensor} pieces (chunks or shards) into a
    dense array; a union that leaves holes raises instead of returning
    uninitialized memory."""
    pieces = _dedup_pieces(pieces)
    if expected_boxes is not None and len(pieces) != expected_boxes:
        raise ValueError(
            f"DTensor shard union has {len(pieces)} distinct boxes but "
            f"mesh/dim_map imply {expected_boxes} — a rank's shards are "
            f"missing from the manifest"
        )
    covered = sum(int(np.prod(p["sizes"])) for p in pieces)
    total = int(np.prod(shape))
    if covered != total:
        raise ValueError(
            f"shard/chunk union covers {covered} of {total} elements of "
            f"shape {tuple(shape)} — incomplete or overlapping pieces "
            f"(elasticity-trimmed or corrupted manifest?)"
        )
    # quantized pieces decode to float32: legacy custom serializers OR
    # the current reference's torch_save chunks/shards under a
    # quantized entry dtype (io_preparer chunks any tensor; quantized
    # chunks get the torch_save serializer)
    quantized = dtype in _QTENSOR_STORAGE or any(
        p["tensor"].get("serializer") in _QTENSOR_SERIALIZERS for p in pieces
    )
    out = np.empty(
        tuple(shape), dtype=np.float32 if quantized else _np_dtype(dtype)
    )
    for piece in pieces:
        sub = _decode_tensor(blobs, piece["tensor"])
        slices = tuple(
            slice(o, o + s) for o, s in zip(piece["offsets"], piece["sizes"])
        )
        out[slices] = sub.reshape(piece["sizes"])
    return out


def _decode_leaf(blobs: "_BlobCache", entry: dict) -> Any:
    t = entry["type"]
    if t in ("int", "str", "bool", "bytes", "float"):
        return _decode_primitive(entry)
    if t == "Tensor":
        return _decode_tensor(blobs, entry)
    if t in ("ChunkedTensor", "ShardedTensor", "DTensor"):
        pieces = entry.get("chunks") or entry.get("shards") or []
        if not pieces:
            raise ValueError(
                f"{t} entry records no shards/chunks — trimmed or "
                f"corrupted manifest"
            )
        # ChunkedTensor records shape/dtype (manifest.py:171-210);
        # Sharded/DTensor entries do NOT — the global shape is the
        # bounding box of the shard union and the dtype comes from any
        # shard's tensor entry (manifest.py:118-168, 211-261).  A union
        # missing a TRAILING shard shrinks the bounding box undetectably
        # for plain ShardedTensor; DTensor entries are additionally
        # validated against the shard count mesh+dim_map implies.
        shape = entry.get("shape")
        dtype = entry.get("dtype")
        if shape is None or dtype is None:
            ndim = len(pieces[0]["offsets"])
            if shape is None:
                shape = [
                    max(p["offsets"][d] + p["sizes"][d] for p in pieces)
                    for d in range(ndim)
                ]
            if dtype is None:
                dtype = pieces[0]["tensor"]["dtype"]
        return _assemble_pieces(
            blobs, shape, dtype, pieces,
            expected_boxes=_dtensor_expected_boxes(entry),
        )
    if t == "object":
        return _torch_load(blobs.get(entry))
    raise ValueError(f"unknown entry type {t!r}")


_CONTAINER_TYPES = ("dict", "OrderedDict", "list")


def _merge_sharded_across_ranks(manifest: dict) -> dict:
    """Per-rank manifests carry only that rank's shards of a sharded
    tensor; the full tensor is the union across every rank's view
    (reference manifest_ops.py:111-177), deduped by box."""
    merged: Dict[str, dict] = {}
    for key, entry in manifest.items():
        if entry.get("type") not in ("ShardedTensor", "DTensor"):
            continue
        _, _, suffix = key.partition("/")
        if suffix not in merged:
            merged[suffix] = {**entry, "shards": []}
        merged[suffix]["shards"].extend(entry.get("shards") or [])
    for slot in merged.values():
        slot["shards"] = _dedup_pieces(slot["shards"])
    return merged


def _parse_metadata(raw: bytes) -> Dict[str, Any]:
    try:
        return json.loads(raw)
    except ValueError:  # hand-edited YAML that isn't the JSON subset
        import yaml

        return yaml.safe_load(raw)


def peek_torchsnapshot(path: str) -> Dict[str, Any]:
    """Parse a reference snapshot's metadata without reading payloads:
    ``{"version", "world_size", "manifest"}`` — lets callers (e.g. the
    CLI) check world_size before committing to a one-rank view; pass the
    result to ``read_torchsnapshot(metadata=...)`` to avoid a second
    metadata fetch."""
    from ..storage import url_to_storage_plugin

    storage = url_to_storage_plugin(path)
    try:
        raw = _read_bytes(storage, ".snapshot_metadata", None)
    finally:
        storage.sync_close()
    return _parse_metadata(raw)


def read_torchsnapshot(
    path: str, rank: int = 0, metadata: Optional[Dict[str, Any]] = None
) -> Dict[str, Any]:
    """Load a reference-format snapshot into a nested state dict of host
    numpy arrays / python values.

    ``rank``: which rank's view to materialize (rank 0 sees every
    replicated and sharded entry fully assembled — the right choice when
    consolidating a distributed reference checkpoint into one JAX
    process; a multi-host import can pass its own rank).

    The result restores into JAX as-is::

        state = read_torchsnapshot("/ckpts/step100")
        params = jax.tree.map(jnp.asarray, state["model"])
    """
    from ..storage import url_to_storage_plugin

    global _quant_warned
    _quant_warned = False  # one summary warning per import
    storage = url_to_storage_plugin(path)
    try:
        if metadata is None:
            metadata = _parse_metadata(
                _read_bytes(storage, ".snapshot_metadata", None)
            )
        manifest: Dict[str, dict] = metadata["manifest"]
        sharded_full = _merge_sharded_across_ranks(manifest)

        # This rank's view: its own entries, plus rank 0's REPLICATED
        # entries — the reference consolidates replicated entries into
        # rank 0's manifest only (partitioner.py:311-355), and overlays
        # them onto every other rank's view at read time
        # (manifest_ops.py:35-109).  Containers ride along so an
        # overlaid leaf always has its ancestors.
        view: Dict[str, dict] = {}
        for key, entry in sorted(manifest.items()):
            if key.startswith(f"{rank}/"):
                view[key.partition("/")[2]] = entry
        if rank != 0:
            rank0 = {
                key.partition("/")[2]: entry
                for key, entry in manifest.items()
                if key.startswith("0/")
            }
            overlaid = [
                s
                for s, e in rank0.items()
                if s not in view
                and e["type"] not in _CONTAINER_TYPES
                and e.get("replicated")
            ]
            for suffix in overlaid:
                view[suffix] = rank0[suffix]
                # ancestors ride along so list/dict types reconstruct
                # correctly (spurious unrelated containers do NOT)
                parent = suffix.rpartition("/")[0]
                while parent and parent not in view:
                    if parent in rank0:
                        view[parent] = rank0[parent]
                    parent = parent.rpartition("/")[0]

        flat: Dict[str, Any] = {}
        containers: Dict[str, dict] = {}
        leaf_entries: List[dict] = []
        for suffix, entry in view.items():
            if entry["type"] in _CONTAINER_TYPES:
                containers[suffix] = entry
            else:
                leaf_entries.append(
                    sharded_full.get(suffix, entry)
                )
        blobs = _BlobCache(storage)
        blobs.prefetch(leaf_entries)
        for suffix, entry in view.items():
            if entry["type"] not in _CONTAINER_TYPES:
                flat[suffix] = _decode_leaf(
                    blobs, sharded_full.get(suffix, entry)
                )
        return _inflate(containers, flat)
    finally:
        storage.sync_close()


def _inflate(containers: Dict[str, dict], flat: Dict[str, Any]) -> Dict[str, Any]:
    """Rebuild the nested structure from container entries + leaves
    (mirror of reference inflate, flatten.py:79-141)."""
    root: Dict[str, Any] = {}

    def dict_key(parent_path: str, comp: str) -> Any:
        """Original dict key for a path component: the container's
        ``keys`` list preserves int keys (List[Union[str, int]],
        reference manifest.py:320) that the path stringifies."""
        decoded = unquote(comp)
        entry = containers.get(parent_path)
        if entry:
            for k in entry.get("keys", ()):
                if str(k) == decoded:
                    return k
        return decoded

    def new_container(entry: dict) -> Any:
        """Dicts are pre-seeded from the entry's recorded ``keys`` so the
        imported dict keeps the reference's original iteration order
        (reference inflate seeds via dict.fromkeys(entry.keys),
        flatten.py:79-141) — leaves then fill the placeholder slots
        without reordering; order-sensitive consumers (OrderedDict
        state) see the keys exactly as saved."""
        if entry["type"] == "list":
            return []
        return dict.fromkeys(entry.get("keys", ()))

    def ensure(path: str) -> Any:
        """The container object at logical ``path``, creating ancestors."""
        if path == "":
            return root
        parent_path, _, comp = path.rpartition("/")
        parent = ensure(parent_path)
        entry = containers.get(path, {"type": "dict"})
        if isinstance(parent, list):
            idx = int(comp)
            while len(parent) <= idx:
                parent.append(None)
            if parent[idx] is None:
                parent[idx] = new_container(entry)
            return parent[idx]
        key = dict_key(parent_path, comp)
        if key not in parent or parent[key] is None:
            parent[key] = new_container(entry)
        return parent[key]

    for path, entry in sorted(containers.items()):
        ensure(path)
    for path, value in sorted(flat.items()):
        parent_path, _, comp = path.rpartition("/")
        parent = ensure(parent_path)
        if isinstance(parent, list):
            idx = int(comp)
            while len(parent) <= idx:
                parent.append(None)
            parent[idx] = value
        else:
            parent[dict_key(parent_path, comp)] = value
    return root
