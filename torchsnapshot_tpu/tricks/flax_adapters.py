"""Flax/optax conveniences over PyTreeState."""

from __future__ import annotations

from typing import Any, Dict

from ..stateful import PyTreeState


class FlaxTrainStateAdapter(PyTreeState):
    """Checkpoint a flax TrainState; exposes step separately so resumable
    loops can read it without touching params (mirrors the reference's
    examples/simple_example.py progress pattern)."""

    @property
    def step(self) -> int:
        import numpy as np

        return int(np.asarray(self.tree.step))

    def state_dict(self) -> Dict[str, Any]:
        return super().state_dict()
