"""Framework adapters ("tricks").

Reference: torchsnapshot/tricks/{ddp,fsdp,deepspeed}.py.  The TPU-native
mapping:

- DDP's "strip the ``module.`` prefix" trick → ``TorchModuleAdapter``
  (works for torch CPU modules checkpointed through this library).
- FSDP's optimizer-state routing → unnecessary on JAX: optimizer state is
  an ordinary pytree whose leaves carry their own NamedShardings; the
  sharded preparer handles them like any other array (SURVEY §2.1 row 5:
  "no special casing needed under GSPMD").  ``FlaxTrainStateAdapter`` is a
  thin convenience over PyTreeState.
- DeepSpeed ZeRO-3's engine monkey-patch → same story: a fully-sharded
  optax state checkpoints through the ShardedArray path unchanged.
"""

from .flax_adapters import FlaxTrainStateAdapter  # noqa: F401
from .torch_module import TorchModuleAdapter, TorchOptimizerAdapter  # noqa: F401
from .torchsnapshot_reader import read_torchsnapshot  # noqa: F401
from .torchsnapshot_writer import write_torchsnapshot  # noqa: F401
