"""DLRM-style recommendation model: the embedding-parallel workload family.

The reference validates its checkpointer against torchrec's DLRM with
row-wise-sharded embedding tables (benchmarks/torchrec/main.py:92-104,
tests/gpu_tests/test_torchrec.py); this is the TPU-native equivalent
workload: big embedding tables row-sharded over a flat "ep" mesh axis
(model-parallel embeddings), dense MLP towers replicated, dot-product
feature interaction, and a jit-able train step.  The checkpointer sees
exactly the layout torchrec produces — per-table row shards — and the
resharding restore covers world-size changes the same way.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DLRMConfig:
    # one entry per sparse feature: number of embedding rows
    table_rows: Tuple[int, ...] = (1 << 16,) * 8
    embed_dim: int = 128
    dense_in: int = 13
    bottom_mlp: Tuple[int, ...] = (512, 256, 128)
    top_mlp: Tuple[int, ...] = (1024, 1024, 512, 256, 1)
    dtype: Any = jnp.float32

    @staticmethod
    def tiny() -> "DLRMConfig":
        return DLRMConfig(
            table_rows=(64, 32, 16),
            embed_dim=8,
            dense_in=4,
            bottom_mlp=(16, 8),
            top_mlp=(16, 1),
        )


class _MLP(nn.Module):
    dims: Sequence[int]
    dtype: Any

    @nn.compact
    def __call__(self, x):
        for i, d in enumerate(self.dims):
            x = nn.Dense(d, dtype=self.dtype)(x)
            if i < len(self.dims) - 1:
                x = nn.relu(x)
        return x


class DLRM(nn.Module):
    cfg: DLRMConfig

    @nn.compact
    def __call__(self, dense, sparse_ids):
        """dense: [b, dense_in] float; sparse_ids: [b, n_tables] int32."""
        cfg = self.cfg
        bottom = _MLP(cfg.bottom_mlp, cfg.dtype, name="bottom_mlp")(dense)
        embs = []
        for t, rows in enumerate(cfg.table_rows):
            table = self.param(
                f"table_{t}",
                nn.initializers.normal(stddev=1.0 / cfg.embed_dim),
                (rows, cfg.embed_dim),
                cfg.dtype,
            )
            embs.append(jnp.take(table, sparse_ids[:, t], axis=0))
        # dot-product interaction over [bottom] + embeddings
        feats = jnp.stack([bottom[..., : cfg.embed_dim]] + embs, axis=1)
        inter = jnp.einsum("bnd,bmd->bnm", feats, feats)
        n = feats.shape[1]
        iu = jnp.triu_indices(n, k=1)
        inter_flat = inter[:, iu[0], iu[1]]
        top_in = jnp.concatenate([bottom, inter_flat], axis=-1)
        return _MLP(cfg.top_mlp, cfg.dtype, name="top_mlp")(top_in)[..., 0]


def embedding_sharding_rules(mesh, path: str, shape: Tuple[int, ...]):
    """Row-shard embedding tables over every mesh axis; replicate MLPs
    (the torchrec ROW_WISE layout, expressed as a PartitionSpec)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if "table_" in path and len(shape) == 2:
        return NamedSharding(mesh, P(tuple(mesh.axis_names), None))
    return NamedSharding(mesh, P())


def make_train_state(cfg: DLRMConfig, seed: int = 0, mesh=None):
    import optax
    from flax.training import train_state

    model = DLRM(cfg)
    dense = jnp.zeros((2, cfg.dense_in), cfg.dtype)
    ids = jnp.zeros((2, len(cfg.table_rows)), jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), dense, ids)
    tx = optax.adagrad(1e-2)  # torchrec's default optimizer family
    ts = train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=tx
    )
    if mesh is not None:
        import jax.tree_util as jtu

        flat, treedef = jtu.tree_flatten_with_path(ts)
        placed = [
            jax.device_put(
                x, embedding_sharding_rules(mesh, jtu.keystr(kp), getattr(x, "shape", ()))
            )
            if hasattr(x, "shape") and x.ndim > 0
            else x
            for kp, x in flat
        ]
        ts = jtu.tree_unflatten(treedef, placed)
    return ts


def loss_fn(params, apply_fn, dense, sparse_ids, labels):
    logits = apply_fn(params, dense, sparse_ids)
    # binary cross-entropy with logits
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * labels
        + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )


def train_step(ts, dense, sparse_ids, labels):
    loss, grads = jax.value_and_grad(loss_fn)(
        ts.params, ts.apply_fn, dense, sparse_ids, labels
    )
    return ts.apply_gradients(grads=grads), loss
