"""Flagship benchmark model: a llama-style decoder-only transformer in flax.

The reference validates its checkpointer against real workloads — a 1.9B
FSDP transformer (benchmarks/fsdp/main.py:36-43) and DDP ResNet
(benchmarks/ddp) — so this repo bundles an equivalent TPU-native workload:
bf16 params, RMSNorm + rotary + SwiGLU blocks, `jax.checkpoint` remat on
each block, and a pjit-able train step whose params/optimizer state carry
real dp/tp NamedShardings for the checkpointer to exercise.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    d_ff: int = 11008
    max_seq: int = 2048
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @staticmethod
    def tiny() -> "TransformerConfig":
        return TransformerConfig(
            vocab=256, d_model=64, n_layers=2, n_heads=4, d_ff=128, max_seq=64
        )


def _rope(x: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
    # x: [b, s, h, hd]
    hd = x.shape[-1]
    half = hd // 2
    freq = 1.0 / (10000 ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freq  # [b, s, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps)).astype(x.dtype) * scale


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        hd = cfg.d_model // cfg.n_heads
        dense = lambda name: nn.Dense(  # noqa: E731
            cfg.d_model, use_bias=False, dtype=cfg.dtype, name=name
        )
        q = dense("wq")(x).reshape(*x.shape[:2], cfg.n_heads, hd)
        k = dense("wk")(x).reshape(*x.shape[:2], cfg.n_heads, hd)
        v = dense("wv")(x).reshape(*x.shape[:2], cfg.n_heads, hd)
        q, k = _rope(q, positions), _rope(k, positions)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(hd).astype(
            cfg.dtype
        )
        seq = x.shape[1]
        mask = jnp.tril(jnp.ones((seq, seq), dtype=bool))
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(
            cfg.dtype
        )
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        out = out.reshape(*x.shape[:2], cfg.d_model)
        return nn.Dense(
            cfg.d_model, use_bias=False, dtype=cfg.dtype, name="wo"
        )(out)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gate = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype, name="gate")(x)
        up = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype, name="w1")(x)
        return nn.Dense(
            cfg.d_model, use_bias=False, dtype=cfg.dtype, name="w2"
        )(nn.silu(gate) * up)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        x = x + Attention(self.cfg, name="attn")(
            RMSNorm(name="norm1")(x), positions
        )
        x = x + MLP(self.cfg, name="mlp")(RMSNorm(name="norm2")(x))
        return x


class TransformerLM(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        x = nn.Embed(
            cfg.vocab, cfg.d_model, dtype=cfg.dtype, name="embed"
        )(tokens)
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1]), tokens.shape
        )
        block = Block
        if cfg.remat:
            block = nn.remat(Block)  # trade FLOPs for HBM
        for i in range(cfg.n_layers):
            x = block(cfg, name=f"layer{i}")(x, positions)
        x = RMSNorm(name="norm_f")(x)
        return nn.Dense(
            cfg.vocab, use_bias=False, dtype=jnp.float32, name="lm_head"
        )(x)


def make_train_state(
    cfg: TransformerConfig, seed: int = 0, mesh=None
):
    """Init params (+ optax adamw state); optionally place on a mesh per
    the tp/dp rules so the checkpointer sees real shardings."""
    import optax
    from flax.training import train_state

    model = TransformerLM(cfg)
    tokens = jnp.zeros((1, min(cfg.max_seq, 8)), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), tokens)
    tx = optax.adamw(3e-4, weight_decay=0.01)
    ts = train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=tx
    )
    if mesh is not None:
        from ..parallel.mesh import shard_pytree

        ts = shard_pytree(ts, mesh)
    return ts


def loss_fn(params, apply_fn, tokens):
    logits = apply_fn(params, tokens[:, :-1])
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def train_step(ts, tokens):
    """One LM training step — jit/pjit this over a mesh for the multi-chip
    path (data batch sharded over 'dp', params per the tp rules)."""
    loss, grads = jax.value_and_grad(loss_fn)(
        ts.params, ts.apply_fn, tokens
    )
    return ts.apply_gradients(grads=grads), loss
