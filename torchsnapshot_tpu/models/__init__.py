from .transformer import TransformerLM, TransformerConfig, make_train_state, train_step  # noqa: F401
