"""Event fan-out: registered handlers + entry-point discovery.

Reference: torchsnapshot/event_handlers.py:23-60.  Every public API call is
bracketed with an event carrying a unique id, duration and success flag
(reference call sites snapshot.py:174-179 etc.).
"""

from __future__ import annotations

import contextlib
import logging
import time
import uuid
from typing import Callable, Iterator, List

from .event import Event

# module-level on purpose: these run inside `except` blocks, where a
# lazy import could itself raise during interpreter teardown and
# escape into the operation being observed (obs never imports this
# module at import time, so no cycle)
from .obs import swallowed_exception
from .obs.metrics import EVENT_HANDLER_ERRORS, counter

logger = logging.getLogger(__name__)

_ENTRY_POINT_GROUP = "torchsnapshot_tpu.event_handlers"
_handlers: List[Callable[[Event], None]] = []
_entry_point_handlers: List[Callable[[Event], None]] = []
_entry_points_loaded = False


def register_event_handler(handler: Callable[[Event], None]) -> None:
    _handlers.append(handler)


def unregister_event_handler(handler: Callable[[Event], None]) -> None:
    try:
        _handlers.remove(handler)
    except ValueError:
        raise ValueError(
            f"cannot unregister event handler {handler!r}: it was never "
            f"registered (or was already unregistered)"
        ) from None


def _load_entry_point_handlers() -> None:
    global _entry_points_loaded
    if _entry_points_loaded:
        return
    _entry_points_loaded = True
    try:
        from importlib.metadata import entry_points

        eps = entry_points()
        group = (
            eps.select(group=_ENTRY_POINT_GROUP)
            if hasattr(eps, "select")
            else eps.get(_ENTRY_POINT_GROUP, [])
        )
        for ep in group:
            try:
                _entry_point_handlers.append(ep.load())
            except Exception:
                logger.exception("failed to load event handler %r", ep.name)
    except Exception as e:
        # no importlib.metadata / broken distribution metadata: events
        # still fire to directly-registered handlers — but leave a
        # trace, a silently-skipped discovery would read as "my
        # entry-point collector never sees events" with zero evidence
        swallowed_exception("event_handlers.entry_point_discovery", e)


def _fire(event: Event) -> None:
    _load_entry_point_handlers()
    if event.timestamp is None:
        event.timestamp = time.monotonic()
    for handler in _handlers + _entry_point_handlers:
        try:
            handler(event)
        except Exception:
            # log first: telemetry accounting must never displace the
            # primary evidence if the inc itself misbehaves
            logger.exception("event handler raised for %r", event.name)
            counter(EVENT_HANDLER_ERRORS).inc()


def _obs_span_cm(event: Event):
    """A tracer span bracketing the event's operation when tracing is
    enabled, else the shared no-op (lazy import: obs.tracer fires span
    events back through this module)."""
    from .obs import tracer as _tracer

    if not _tracer.ENABLED:
        return _tracer.NULL_CM
    # fire_event=False: the event itself fires below — a span/<name>
    # echo of the same bracket would double every telemetry record
    return _tracer.span(event.name, fire_event=False)


@contextlib.contextmanager
def log_event(event: Event) -> Iterator[Event]:
    """Bracket an operation: fires the event on exit with a monotonic
    timestamp, unique_id, duration and is_success attached.  When span
    tracing is enabled, the bracket also records a span of the same
    name, so top-level API events appear in Perfetto traces."""
    event.metadata.setdefault("unique_id", uuid.uuid4().hex)
    begin = time.monotonic()
    with _obs_span_cm(event):
        try:
            yield event
            event.metadata["is_success"] = True
        except BaseException:
            event.metadata["is_success"] = False
            raise
        finally:
            event.timestamp = time.monotonic()
            event.metadata["duration_s"] = event.timestamp - begin
            _fire(event)
