"""Distributed control plane: object gathers, barriers, and a KV store.

TPU-native replacement for the reference's two-channel design
(pg_wrapper.py:17-91 NCCL/Gloo collectives + dist_store.py:24-196 TCPStore):
on JAX, *both* channels collapse into the coordination-service KV store —
``jax.distributed``'s client exposes key_value_set / blocking_key_value_get /
wait_at_barrier, which (a) carries small control-plane objects fine and
(b) never touches ICI, so it is safe from the async-snapshot background
thread (the reference's "no collectives in this method" constraint,
snapshot.py:1010, holds by construction).

Implementations:
- ``LocalCoordinator``  — single process, no-ops.
- ``JaxCoordinator``    — multi-controller via jax.distributed's KV client.
- ``FileCoordinator``   — shared-filesystem KV for multi-process CPU tests
  (the analogue of the reference's file-based c10d rendezvous in
  test_utils.py:188-243).

All gathers/barriers are built on four KV primitives (set/get/delete/
barrier), so the three backends share the same semantics by construction.
"""

from __future__ import annotations

import abc
import contextlib
import logging
import os
import threading
import time
import uuid
from base64 import b64decode, b64encode
from typing import Any, Iterator, List, Optional

from . import obs
from .resilience import abort as _abort
from .resilience.failpoints import failpoint
from .serialization import deserialize_object, serialize_object

logger = logging.getLogger(__name__)

_DEFAULT_TIMEOUT_S = 600.0
# abort-aware waits poll the poison key at this cadence: a peer's abort
# surfaces within ~this interval instead of the full wait timeout
_ABORT_POLL_S = 0.5


def _is_timeoutish(e: BaseException) -> bool:
    """Did a bounded KV wait merely time out (vs. fail)?  Covers the
    builtin TimeoutError (FileCoordinator) and the jax coordination
    client's DEADLINE_EXCEEDED XlaRuntimeError."""
    if isinstance(e, TimeoutError):
        return True
    name = type(e).__name__
    r = repr(e).upper()
    return "Timeout" in name or "DEADLINE_EXCEEDED" in r or "DEADLINE" in r


class Coordinator(abc.ABC):
    """Uniform control-plane interface (reference PGWrapper,
    pg_wrapper.py:17-91).

    Beyond the KV/barrier primitives, the base class carries the
    cross-rank ABORT protocol (resilience/abort.py): ``poison(scope,
    cause)`` broadcasts an abort under one KV key, and inside an
    ``abort_scope(scope)`` every ``kv_get``/``barrier`` wait polls that
    key — a peer's unrecoverable failure surfaces as a typed
    ``SnapshotAbortedError`` within seconds instead of wedging the rank
    until the wait timeout.  The scope is per-thread (a background
    promotion thread's scope never leaks onto the foreground take)."""

    @property
    @abc.abstractmethod
    def rank(self) -> int: ...

    @property
    @abc.abstractmethod
    def world_size(self) -> int: ...

    @abc.abstractmethod
    def _kv_set_impl(self, key: str, value: str) -> None: ...

    @abc.abstractmethod
    def _kv_get_impl(self, key: str, timeout_s: float) -> str: ...

    @abc.abstractmethod
    def kv_try_get(self, key: str) -> Optional[str]: ...

    @abc.abstractmethod
    def _barrier_impl(self, name: str, timeout_s: float) -> None: ...

    def kv_set(self, key: str, value: str) -> None:
        failpoint("coord.kv_set", key=key)
        self._kv_set_impl(key, value)

    def kv_get(self, key: str, timeout_s: float = _DEFAULT_TIMEOUT_S) -> str:
        """Blocking get: waits until the key exists.  Abort-aware inside
        an ``abort_scope``; death-aware inside a ``liveness_scope``
        (raises ``RankDeadError`` when a peer's heartbeat goes stale
        instead of waiting out the full deadline)."""
        failpoint("coord.kv_get", key=key)
        scope = self._current_abort_scope()
        monitor = self._current_liveness()
        if scope is None and monitor is None:
            return self._kv_get_impl(key, timeout_s)
        return self._polling_kv_get(key, timeout_s, scope, monitor)

    def barrier(
        self, name: Optional[str] = None, timeout_s: float = _DEFAULT_TIMEOUT_S
    ) -> None:
        """Barrier; auto-names from the per-instance op counter when no name
        is given (coordination calls happen in identical program order on
        every rank).  Explicit names must be globally unique per use — JAX
        barrier ids are single-use.  Abort-aware inside an ``abort_scope``:
        runs as a two-phase KV barrier over the abort-aware ``kv_get``
        (the native barrier wait is opaque and can't poll poison)."""
        name = name or self._next_uid("bar")
        failpoint("coord.barrier", name=name)
        # always-on barrier phase clock: the flight record's straggler
        # attribution (obs/aggregate) reads this rank's cumulative
        # barrier-wait seconds — a fast rank's take time hides in here
        # while it waits for the straggler
        t0 = time.monotonic()
        try:
            self._barrier_inner(name, timeout_s)
        finally:
            obs.histogram(obs.PHASE_BARRIER_S).observe(
                time.monotonic() - t0
            )

    def _barrier_inner(self, name: str, timeout_s: float) -> None:
        scope = self._current_abort_scope()
        monitor = self._current_liveness()
        if scope is None and monitor is None:
            self._barrier_impl(name, timeout_s)
            return
        if scope is not None:
            self.raise_if_poisoned(scope)
        if monitor is not None:
            monitor.check()
        if self.world_size == 1:
            return
        # one deadline for the WHOLE barrier (matching the native
        # implementation's bound) — not timeout_s per arrive key
        deadline = time.monotonic() + timeout_s
        self._kv_set_impl(f"{name}/aa/arrive/{self.rank}", "1")
        if self.rank == 0:
            for r in range(self.world_size):
                self.kv_get(
                    f"{name}/aa/arrive/{r}",
                    max(0.0, deadline - time.monotonic()),
                )
            self._kv_set_impl(f"{name}/aa/depart", "1")
        else:
            self.kv_get(
                f"{name}/aa/depart", max(0.0, deadline - time.monotonic())
            )

    # ---- cross-rank abort (resilience/abort.py) ------------------------

    def poison(
        self, scope: str, cause: str, site: str = ""
    ) -> _abort.AbortInfo:
        """Broadcast an abort of ``scope``: peers blocked in abort-aware
        waits raise ``SnapshotAbortedError`` naming this rank and
        ``cause``.  Never raises — poisoning runs on failure paths and
        must not mask the original error."""
        info = _abort.AbortInfo(
            origin_rank=self.rank, cause=cause, site=site
        )
        obs.counter(obs.RESILIENCE_ABORTS).inc()
        logger.warning(
            "rank %d poisoning scope %r at %s: %s",
            self.rank, scope, site or "?", cause,
        )
        try:
            self._kv_set_impl(
                _abort.poison_key(scope), _abort.encode_poison(info)
            )
        except Exception as e:  # noqa: BLE001 — best-effort broadcast
            obs.swallowed_exception("coordination.poison", e)
        return info

    def check_poison(self, scope: str) -> Optional[_abort.AbortInfo]:
        raw = self.kv_try_get(_abort.poison_key(scope))
        return _abort.decode_poison(raw) if raw else None

    def raise_if_poisoned(self, scope: str) -> None:
        info = self.check_poison(scope)
        if info is not None:
            raise _abort.SnapshotAbortedError(info, scope=scope)

    def _current_abort_scope(self) -> Optional[str]:
        tls = self.__dict__.get("_abort_tls")
        return getattr(tls, "scope", None) if tls is not None else None

    @contextlib.contextmanager
    def abort_scope(self, scope: str) -> Iterator[None]:
        """While active, this THREAD's kv_get/barrier waits poll
        ``scope``'s poison key (per-thread on purpose: the async-commit
        and tier-promotion threads scope their own waits without
        touching the foreground program order)."""
        tls = self.__dict__.setdefault("_abort_tls", threading.local())
        prev = getattr(tls, "scope", None)
        tls.scope = scope
        try:
            yield
        finally:
            tls.scope = prev

    def _abortable_kv_get(
        self, key: str, timeout_s: float, scope: str
    ) -> str:
        return self._polling_kv_get(key, timeout_s, scope, None)

    def _polling_kv_get(
        self, key: str, timeout_s: float, scope: Optional[str], monitor: Any
    ) -> str:
        """The shared short-poll wait: between probes it checks the
        abort scope's poison key and/or the liveness monitor, so a
        peer's failure (poison) or death (stale heartbeat) surfaces as
        a typed error within one poll interval."""
        deadline = time.monotonic() + timeout_s
        while True:
            if scope is not None:
                self.raise_if_poisoned(scope)
            if monitor is not None:
                monitor.check()
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"kv_get timed out waiting for {key!r} "
                    f"(abort-aware, scope {scope!r})"
                )
            try:
                return self._kv_get_impl(
                    key, min(_ABORT_POLL_S, remaining)
                )
            except Exception as e:  # noqa: BLE001 — timeouts poll on
                if not _is_timeoutish(e):
                    raise

    # ---- rank liveness (resilience/liveness.py) ------------------------

    def _current_liveness(self) -> Any:
        tls = self.__dict__.get("_liveness_tls")
        return getattr(tls, "monitor", None) if tls is not None else None

    @contextlib.contextmanager
    def liveness_scope(self, monitor: Any) -> Iterator[None]:
        """While active, this THREAD's kv_get/barrier waits check
        ``monitor`` (a ``resilience.liveness.LivenessMonitor``) each
        poll tick and raise ``RankDeadError`` when a peer's heartbeat
        stamp goes stale — per-thread for the same reason as
        ``abort_scope``."""
        tls = self.__dict__.setdefault("_liveness_tls", threading.local())
        prev = getattr(tls, "monitor", None)
        tls.monitor = monitor
        try:
            yield
        finally:
            tls.monitor = prev

    def dead_ranks(self) -> list:
        """Peers the current thread's liveness monitor considers dead
        (empty outside a ``liveness_scope`` — without heartbeats there
        is no death evidence)."""
        monitor = self._current_liveness()
        return monitor.dead_ranks() if monitor is not None else []

    # ---- derived object-level ops --------------------------------------

    def _encode(self, obj: Any) -> str:
        payload, tag = serialize_object(obj)
        return tag + ":" + b64encode(payload).decode("ascii")

    def _decode(self, s: str) -> Any:
        tag, payload = s.split(":", 1)
        return deserialize_object(b64decode(payload.encode("ascii")), tag)

    def _next_uid(self, op: str) -> str:
        # Every rank performs coordination calls in the same program order,
        # so a per-instance counter yields matching keys across ranks.
        n = getattr(self, "_op_counter", 0)
        self._op_counter = n + 1
        return f"{op}/{n}"

    def kv_exchange(
        self,
        prefix: str,
        value: str,
        timeout_s: float = _DEFAULT_TIMEOUT_S,
    ) -> List[str]:
        """KV-only allgather of one small STRING per rank under EXPLICIT
        keys (``{prefix}/{rank}``) — no barrier, no uid counters, no
        collectives, so it is safe from background threads (async-commit
        and tier-promotion threads, where ``all_gather_object`` is
        forbidden: its per-instance uid counter belongs to the foreground
        program order).  ``prefix`` must be unique per use across the job
        (callers derive it from a commit uid); keys are idempotent —
        re-setting the same value is harmless."""
        if self.world_size == 1:
            return [value]
        self.kv_set(f"{prefix}/{self.rank}", value)
        return [
            self.kv_get(f"{prefix}/{r}", timeout_s)
            for r in range(self.world_size)
        ]

    def kv_try_delete(self, key: str) -> None:
        """Best-effort KV key deletion (cleanup of transient
        publications — fan-out blobs).  Base implementation is a no-op:
        a backend without deletion merely retains the key until
        teardown, never fails the caller."""

    def kv_publish_blob(
        self, prefix: str, data: Any, part_bytes: int = 4 * 1024 * 1024
    ) -> int:
        """Publish one binary blob under EXPLICIT keys for asymmetric
        one-to-many redistribution (the fan-out restore's transport,
        topology/fanout.py).  The blob is split into ``part_bytes``
        chunks (``{prefix}/p{i}``, base64) with a ``{prefix}/meta`` key
        written LAST carrying ``nparts:total:crc32`` — meta presence
        therefore implies every part is present, and the crc32 lets the
        fetch side verify the reassembled bytes before trusting them.
        No barrier, no uid counters: safe from any thread, legal under
        rank-conditional branches (only the publisher calls this).
        ``prefix`` must be unique per blob across the job (namespace
        REUSE is the exception the sweep below exists for).  Returns
        the blob's byte length.

        Leak repair: a publisher killed between the cleanup path's
        meta-key delete and its part deletes leaves orphaned
        ``{prefix}/p{i}`` keys (meta gone, parts stranded until the KV
        itself is torn down).  The next publish under the same prefix
        reclaims them: indices below the new ``nparts`` are simply
        overwritten, and after the meta write a tail sweep deletes
        every contiguous leftover part at/above ``nparts``
        (``kv_sweep_blob``) — so namespace reuse self-heals instead of
        accreting dead keys."""
        import zlib

        view = memoryview(data).cast("B")
        part = max(1, int(part_bytes))
        n = view.nbytes
        nparts = (n + part - 1) // part
        for i in range(nparts):
            chunk = view[i * part : min((i + 1) * part, n)]
            self.kv_set(
                f"{prefix}/p{i}", b64encode(chunk).decode("ascii")
            )
        self.kv_set(f"{prefix}/meta", f"{nparts}:{n}:{zlib.crc32(view)}")
        self.kv_sweep_blob(prefix, beyond=nparts)
        return n

    def kv_sweep_blob(self, prefix: str, beyond: int = 0) -> int:
        """Best-effort reclaim of leaked blob part keys under
        ``prefix``: deletes ``{prefix}/p{i}`` for ``i = beyond,
        beyond+1, ...`` until the first missing index (parts are
        written contiguously from 0, so the first gap proves the end).
        ``beyond=0`` is a full sweep and deletes ``{prefix}/meta``
        FIRST — preserving the meta-last invariant for any concurrent
        fetcher (meta present implies every part present).  Returns
        the number of part keys deleted; never raises past the KV's
        own best-effort delete semantics."""
        start = max(0, int(beyond))
        if start == 0:
            self.kv_try_delete(f"{prefix}/meta")
        swept = 0
        i = start
        while self.kv_try_get(f"{prefix}/p{i}") is not None:
            self.kv_try_delete(f"{prefix}/p{i}")
            swept += 1
            i += 1
        if swept:
            obs.counter(obs.TRANSPORT_SWEPT_PARTS).inc(swept)
        return swept

    def kv_try_fetch_blob(
        self, prefix: str, timeout_s: float = _DEFAULT_TIMEOUT_S
    ) -> Optional[bytes]:
        """Non-blocking probe + fetch of a blob published by
        ``kv_publish_blob``: None when ``{prefix}/meta`` is not (yet)
        present; otherwise the reassembled, crc-verified bytes.  The
        meta-last publication order makes the part gets below
        effectively immediate once meta exists.  Raises ``ValueError``
        on a digest/length mismatch — the caller decides whether to
        retry or fall back."""
        import zlib

        raw = self.kv_try_get(f"{prefix}/meta")
        if raw is None:
            return None
        try:
            nparts_s, total_s, crc_s = raw.split(":")
            nparts, total, crc = int(nparts_s), int(total_s), int(crc_s)
        except ValueError as e:
            raise ValueError(
                f"malformed blob meta under {prefix!r}: {raw!r}"
            ) from e
        buf = bytearray()
        for i in range(nparts):
            buf += b64decode(
                self.kv_get(f"{prefix}/p{i}", timeout_s).encode("ascii")
            )
        if len(buf) != total or zlib.crc32(bytes(buf)) != crc:
            raise ValueError(
                f"blob under {prefix!r} failed digest verification "
                f"({len(buf)} of {total} bytes)"
            )
        return bytes(buf)

    def all_gather_object(self, obj: Any) -> List[Any]:
        """Gather an object from every rank (reference
        pg_wrapper.py all_gather_object)."""
        if self.world_size == 1:
            return [obj]
        uid = self._next_uid("ag")
        self.kv_set(f"{uid}/{self.rank}", self._encode(obj))
        out = [self._decode(self.kv_get(f"{uid}/{r}")) for r in range(self.world_size)]
        self.barrier(f"{uid}/done")
        return out

    def broadcast_object(self, obj: Any, src: int = 0) -> Any:
        """Broadcast an object from ``src`` (reference
        pg_wrapper.py broadcast_object_list)."""
        if self.world_size == 1:
            return obj
        uid = self._next_uid("bc")
        if self.rank == src:
            self.kv_set(uid, self._encode(obj))
            result = obj
        else:
            result = self._decode(self.kv_get(uid))
        self.barrier(f"{uid}/done")
        return result


class LocalCoordinator(Coordinator):
    """Single-process fallback (reference PGWrapper(pg=None) branch)."""

    def __init__(self) -> None:
        self._kv: dict = {}

    @property
    def rank(self) -> int:
        return 0

    @property
    def world_size(self) -> int:
        return 1

    def _kv_set_impl(self, key: str, value: str) -> None:
        self._kv[key] = value

    def _kv_get_impl(self, key: str, timeout_s: float) -> str:
        return self._kv[key]

    def kv_try_get(self, key: str) -> Optional[str]:
        return self._kv.get(key)

    def kv_try_delete(self, key: str) -> None:
        self._kv.pop(key, None)

    def _barrier_impl(self, name: str, timeout_s: float) -> None:
        pass


class JaxCoordinator(Coordinator):
    """Multi-controller coordination over jax.distributed's KV service.

    Requires ``jax.distributed.initialize()`` to have been called (the
    norm on multi-host TPU pods).
    """

    def __init__(self, namespace: Optional[str] = None) -> None:
        import jax

        from jax._src import distributed

        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized; use LocalCoordinator "
                "for single-process runs"
            )
        self._client = client
        self._rank = jax.process_index()
        self._world = jax.process_count()
        self._ns = namespace or "tsnp"

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world

    def _k(self, key: str) -> str:
        return f"{self._ns}/{key}"

    def _kv_set_impl(self, key: str, value: str) -> None:
        self._client.key_value_set(self._k(key), value)

    def _kv_get_impl(self, key: str, timeout_s: float) -> str:
        return self._client.blocking_key_value_get(
            self._k(key), max(1, int(timeout_s * 1000))
        )

    def kv_try_get(self, key: str) -> Optional[str]:
        try:
            return self._client.key_value_try_get(self._k(key))
        except Exception:
            return None

    def kv_try_delete(self, key: str) -> None:
        try:
            self._client.key_value_delete(self._k(key))
        except Exception as e:  # noqa: BLE001 — cleanup is best-effort
            obs.swallowed_exception("coordination.kv_try_delete", e)

    def _barrier_impl(self, name: str, timeout_s: float) -> None:
        self._client.wait_at_barrier(self._k(name), int(timeout_s * 1000))


class FileCoordinator(Coordinator):
    """Shared-directory KV + barriers for multi-process tests on one host."""

    def __init__(self, root: str, rank: int, world_size: int, poll_s: float = 0.01):
        self.root = root
        self._rank = rank
        self._world = world_size
        self._poll_s = poll_s
        os.makedirs(root, exist_ok=True)

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key.replace("/", "%2F"))

    def _kv_set_impl(self, key: str, value: str) -> None:
        path = self._path(key)
        tmp = path + f".tmp.{uuid.uuid4().hex}"
        with open(tmp, "w") as f:
            f.write(value)
        os.replace(tmp, path)

    def _kv_get_impl(self, key: str, timeout_s: float) -> str:
        deadline = time.monotonic() + timeout_s
        path = self._path(key)
        while True:
            try:
                with open(path, "r") as f:
                    return f.read()
            except FileNotFoundError:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"kv_get timed out waiting for {key!r}")
                time.sleep(self._poll_s)

    def kv_try_get(self, key: str) -> Optional[str]:
        try:
            with open(self._path(key), "r") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def kv_try_delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass  # already gone / never set: best-effort by contract

    def _barrier_impl(self, name: str, timeout_s: float) -> None:
        # two-phase: everyone arrives, rank 0 releases
        # (reference LinearBarrier, dist_store.py:91-196)
        self.kv_set(f"{name}/arrive/{self._rank}", "1")
        if self._rank == 0:
            for r in range(self._world):
                self.kv_get(f"{name}/arrive/{r}", timeout_s)
            self.kv_set(f"{name}/depart", "1")
        else:
            self.kv_get(f"{name}/depart", timeout_s)


def kv_watch(
    coordinator: Coordinator,
    key: str,
    last: "Optional[str]" = None,
    timeout_s: float = 0.0,
    poll_s: float = 0.025,
) -> "Optional[str]":
    """Watch helper for announce-style keys: poll ``kv_try_get(key)``
    until its value exists AND differs from ``last``, or ``timeout_s``
    elapses (returns None).  This is the publication subsystem's fast
    path (publish/subscriber.py) — one non-blocking probe per tick, so
    a host full of waiting subscribers never parks threads in a
    blocking ``kv_get``, and a timeout is a NORMAL return (the caller
    falls back to its durable poll, the fanout degrade-never-wedge
    contract).  Any probe error also returns None: a broken announce
    channel must degrade the watcher, not wedge it."""
    deadline = time.monotonic() + max(0.0, timeout_s)
    while True:
        try:
            value = coordinator.kv_try_get(key)
        except Exception as e:  # noqa: BLE001 — degrade to durable poll
            obs.swallowed_exception("coordination.kv_watch", e)
            return None
        if value is not None and value != last:
            return value
        if time.monotonic() >= deadline:
            return None
        time.sleep(min(poll_s, max(0.0, deadline - time.monotonic())))


def get_default_coordinator() -> Coordinator:
    """JaxCoordinator when jax.distributed is initialized, else local."""
    try:
        from jax._src import distributed

        if distributed.global_state.client is not None:
            return JaxCoordinator()
    except Exception as e:
        # jax absent or its internal layout changed: single-process
        # coordination is the right degraded mode, but record the
        # fallback — a pod job silently coordinating locally is exactly
        # the misconfiguration this trace exists to diagnose (obs is a
        # module-level import: a lazy import here could itself raise
        # and replace the exception being handled)
        obs.swallowed_exception("coordination.jax_probe", e)
    return LocalCoordinator()
