"""Budgeted async execution engine for write/read pipelines.

TPU-native analogue of the reference scheduler (torchsnapshot/
scheduler.py:222-463).  Same discipline:

- Write path: ``ready_for_staging → staging → ready_for_io → io → done``.
  A request is admitted to staging iff its cost fits the remaining host
  memory budget, or the pipeline is empty (guaranteed progress for oversized
  items) (reference scheduler.py:266-277).  The budget is debited by the
  declared staging cost and corrected to the actual buffer size once staging
  completes (reference scheduler.py:308-312).
- Concurrent storage ops are capped per process (default 16,
  knobs.get_max_per_rank_io_concurrency; reference scheduler.py:279-290).
- Once all staging completes, control returns to the caller with a
  ``PendingIOWork`` while storage I/O keeps draining — this is what makes
  ``async_take`` "unblock after staging" fall out of the same code path
  (reference scheduler.py:299,334-339).
- Read path is the mirror image: admit reads under the consuming-cost
  budget, chain each completed read into a consume task (reference
  scheduler.py:386-446).

Design difference vs the reference: instead of nesting event loops in the
caller's thread, the pipeline runs on a dedicated event-loop *thread* owned
by the scheduler.  The training thread regains control the moment staging
finishes; residual I/O keeps running on the loop thread with no involvement
from the caller — which is exactly the execution model async snapshots need
on TPU (the background work never issues collectives, so it can never race
with XLA's ICI traffic).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Awaitable, Callable, List, Optional, Tuple

import psutil

from . import _csrc
from . import codec as codec_mod
from . import knobs
from .cas import store as cas_store_mod
from .io_types import (
    ReadIO,
    ReadReq,
    StoragePlugin,
    WriteIO,
    WriteReq,
    check_read_crc,
    is_mmap_backed,
)
from .obs import buf_nbytes as _buf_nbytes
from .obs import metrics as obs_metrics
from .obs import tracer as obs_tracer
from .resilience.failpoints import failpoint
from .storage import stripe

# Parts of one streamed object in flight (staged-but-unwritten or
# writing) at a time.  This bound IS the budget reservation for the
# whole object: a streamed 8GB tensor reserves 4 parts' worth of host
# memory instead of 8GB, which is what lets objects larger than the
# budget move under it (the progress rule used to admit them alone).
_STREAM_WINDOW_PARTS = 4

logger = logging.getLogger(__name__)

_MAX_PER_RANK_MEMORY_BUDGET_BYTES = 32 * 1024 * 1024 * 1024
_AVAILABLE_MEMORY_MULTIPLIER = 0.6


def _apply_checksum_sinks(buf, sinks, digest_sink=None, precomputed=None) -> None:
    """Feed each sink the crc32 of its byte range of the staged buffer
    (WriteReq.checksum_sinks contract, io_types.py); ``digest_sink``
    additionally receives the whole object's (crc32, adler32, size).

    ``precomputed``: {(start, end): (crc32, adler32, size)} recorded by
    the stager while it packed the bytes (the native fused copy+digest
    pass, batcher.BatchedBufferStager) — matching spans skip hashing
    entirely.  When the sink ranges exactly tile the buffer (a slab:
    members packed back-to-back; or one whole-buffer sink), the object
    digest FOLDS from the per-piece values (utils/checksums.py) instead
    of re-reading every byte; with a full precomputed set the staged
    data is not touched at all here."""
    from . import _csrc
    from .utils.checksums import (
        adler32_fast,
        combine_piece_digests,
        crc32_fast,
    )

    view = memoryview(buf).cast("B")
    pre = precomputed or {}
    sinks = list(sinks or ())  # a generator would be empty on re-iteration
    spans = [
        (0, view.nbytes) if rng is None else (rng[0], rng[1])
        for _, rng in sinks
    ]
    ordered = sorted(set(spans))
    can_fold = (
        digest_sink is not None
        and spans
        and len(ordered) == len(spans)
        and ordered[0][0] == 0
        and ordered[-1][1] == view.nbytes
        and all(a[1] == b[0] for a, b in zip(ordered, ordered[1:]))
    )
    piece_digests = {}
    for (sink, rng), span in zip(sinks, spans):
        hit = pre.get(span)
        if hit is not None and hit[2] == span[1] - span[0]:
            crc = hit[0]
            adler = hit[1]
        else:
            piece = view[span[0] : span[1]]
            crc = crc32_fast(piece)
            adler = adler32_fast(piece) if can_fold else None
        sink(crc)
        if can_fold:
            piece_digests[span] = (crc, adler, span[1] - span[0])
    if digest_sink is None:
        return
    if can_fold:
        crc, adler, total = combine_piece_digests(
            [piece_digests[s] for s in ordered]
        )
        digest_sink([crc, adler, total])
    else:
        # one interleaved native pass when available; else two fast ones
        d = _csrc.digest(view)
        if d is None:
            d = (crc32_fast(view), adler32_fast(view))
        digest_sink([d[0], d[1], view.nbytes])


async def _encode_staged_buffer(
    p: "_WritePipeline",
    wr: WriteReq,
    spec: "codec_mod.WriteSpec",
    executor: Optional[ThreadPoolExecutor],
):
    """Whole-staged writes' compress stage: encode the staged buffer as
    stripe-part-sized frames CONCURRENTLY on the staging executor (a
    multi-part object's frames encode in parallel; a small object is one
    frame), assemble the stored byte stream, and hand the frame table to
    the write's codec_sink.  The raw buffer is released on return — the
    caller replaces ``p.buf`` with the encoded stream, so storage I/O
    and budget accounting both see stored bytes."""
    import numpy as np

    view = memoryview(p.buf).cast("B")
    raw_size = view.nbytes
    if raw_size == 0:
        return p.buf  # nothing to encode; stays a raw (table-less) object
    part_size = knobs.get_stripe_part_size_bytes()
    spans = stripe.plan_parts(raw_size, part_size)
    stride = getattr(wr.buffer_stager, "codec_filter_stride", 0)
    frames = await asyncio.gather(
        *(
            codec_mod.encode_frame_async(
                view[lo:hi], spec, stride, executor,
                path=wr.path, part=i,
            )
            for i, (lo, hi) in enumerate(spans)
        )
    )
    frame_lens = [len(f) for f in frames]
    stored_size = sum(frame_lens)
    out = np.empty(stored_size, dtype=np.uint8)
    pos = 0
    for i, n in enumerate(frame_lens):
        out[pos : pos + n] = np.frombuffer(frames[i], dtype=np.uint8)
        # drop each frame as it lands: peak memory stays raw + stored
        # instead of raw + 2x stored while the stream assembles
        frames[i] = None
        pos += n
    stored_digest = None
    if knobs.write_checksums_enabled():
        def _digest_stored():
            from ._csrc import digest as native_digest
            from .utils.checksums import adler32_fast, crc32_fast

            d = native_digest(out)
            if d is None:
                d = (crc32_fast(out), adler32_fast(out))
            return [d[0], d[1], stored_size]

        if executor is not None:
            stored_digest = await asyncio.get_running_loop().run_in_executor(
                executor, _digest_stored
            )
        else:
            stored_digest = _digest_stored()
    wr.codec_sink(
        codec_mod.make_table(
            spec.codec, part_size, raw_size, frame_lens, stored_digest,
        )
    )
    return out


def get_process_memory_budget_bytes(local_process_count: int = 1) -> int:
    """Host-memory budget for staging (reference scheduler.py:47-67)."""
    override = knobs.get_per_rank_memory_budget_bytes()
    if override is not None:
        return override
    available = psutil.virtual_memory().available
    budget = int(available * _AVAILABLE_MEMORY_MULTIPLIER / max(1, local_process_count))
    return min(budget, _MAX_PER_RANK_MEMORY_BUDGET_BYTES)


class _LoopThread:
    """A dedicated event-loop thread that outlives the submitting call."""

    def __init__(self, name: str = "tsnp-io-loop") -> None:
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        # Warm the lazy native-library loader BEFORE the loop runs:
        # load() may open /proc/cpuinfo and even compile the .so on
        # its first call in a process, and the first digest/codec user
        # is otherwise an async pipeline task — a multi-second compile
        # on the event loop stalls every in-flight pipeline at once
        # (surfaced by snaplint effect-escape; load() is memoized, so
        # this costs one no-op lock acquire ever after).  Best-effort:
        # a loader failure here must not kill the thread before
        # run_forever, or every submit() would hang on a dead loop —
        # the first real native user re-hits load() and degrades to
        # the pure-python path as before.
        try:
            _csrc.load()
        except Exception:  # noqa: BLE001
            logger.warning(
                "native fastio warm-up failed; continuing without it",
                exc_info=True,
            )
        self.loop.run_forever()

    def submit(self, coro: Awaitable) -> concurrent.futures.Future:
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def shutdown(self) -> None:
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join()
        self.loop.close()


class _Budget:
    def __init__(self, total: int) -> None:
        self.total = total
        self.used = 0

    def fits(self, cost: int) -> bool:
        return self.used + cost <= self.total

    def debit(self, cost: int) -> None:
        self.used += cost

    def credit(self, cost: int) -> None:
        self.used -= cost


class _WritePipeline:
    """One write request's journey through the pipeline (reference
    scheduler.py:70-97)."""

    __slots__ = (
        "write_req",
        "staging_cost",
        "admission_cost",
        "stream_spans",
        "buf",
        "buf_size",
        "deduped",
        "defer_digest",
        # chunk-store accounting (cas/): bytes actually written vs
        # skipped because the content was already pooled
        "cas_written",
        "cas_shared",
    )

    def __init__(self, write_req: WriteReq) -> None:
        self.write_req = write_req
        self.staging_cost = write_req.buffer_stager.get_staging_cost_bytes()
        # what budget admission actually debits: the full staging cost,
        # except for part-streamed striped writes, which reserve only a
        # window of parts (set in _execute_write_pipelines)
        self.admission_cost = self.staging_cost
        # part spans when this pipeline stage→writes per part through
        # the stripe engine instead of staging whole
        self.stream_spans = None
        self.buf = None
        self.buf_size = 0
        self.deduped = False
        # checksums deferred to the write itself (fused digest-while-
        # writing on honoring plugins; post-write fallback otherwise)
        self.defer_digest = False
        self.cas_written = 0
        self.cas_shared = 0


class PendingIOWork:
    """Handle for storage I/O still draining after staging completed
    (reference PendingIOWork, scheduler.py:196-216)."""

    def __init__(
        self,
        fut: Optional[concurrent.futures.Future],
        loop_thread: _LoopThread,
        executor: ThreadPoolExecutor,
        stats: dict,
        starter: Optional[Callable[[], concurrent.futures.Future]] = None,
    ) -> None:
        self._fut = fut
        self._starter = starter
        self._loop_thread = loop_thread
        self._executor = executor
        self._stats = stats
        self._completed = False
        # the caller's sync_complete and the commit thread can both
        # reach ensure_started: without the lock a deferred pipeline
        # could be spun up TWICE (two budget admissions, double writes)
        self._start_lock = threading.Lock()

    def ensure_started(self) -> concurrent.futures.Future:
        """Kick off the pipeline if construction deferred it (the
        async_take path defers so the commit thread — not the caller's
        blocked window — pays for pipeline spin-up and the GIL contention
        of the first staging memcpys)."""
        with self._start_lock:
            if self._fut is None:
                self._fut = self._starter()
            return self._fut

    def sync_complete(self) -> None:
        if self._completed:
            return
        try:
            self.ensure_started().result()
        finally:
            self._completed = True
            self._executor.shutdown(wait=False)
            self._loop_thread.shutdown()
        elapsed = self._stats.get("end_ts", time.monotonic()) - self._stats["begin_ts"]
        gb = self._stats["bytes_written"] / 1e9
        if elapsed > 0 and gb > 0:
            logger.info(
                "Wrote %.3f GB in %.2fs (%.2f GB/s)", gb, elapsed, gb / elapsed
            )

    @property
    def bytes_written(self) -> int:
        return self._stats["bytes_written"]


_PROGRESS_INTERVAL_S = 10.0


class _WriteReporter:
    """Periodic pipeline progress log (reference _WriteReporter,
    scheduler.py:98-177: stageable/staging/writable/writing counts, budget
    usage, GB written)."""

    def __init__(self, budget: "_Budget", stats: dict) -> None:
        self.budget = budget
        self.stats = stats
        self.last_ts = time.monotonic()

    def maybe_report(
        self, stageable: int, staging: int, writable: int, writing: int
    ) -> None:
        now = time.monotonic()
        if now - self.last_ts < _PROGRESS_INTERVAL_S:
            return
        self.last_ts = now
        logger.info(
            "write pipeline: %d stage-able | %d staging | %d writable | "
            "%d writing | budget %.1f/%.1f MB | %.2f GB written",
            stageable,
            staging,
            writable,
            writing,
            self.budget.used / 1e6,
            self.budget.total / 1e6,
            self.stats["bytes_written"] / 1e9,
        )


async def _execute_write_pipelines(
    pipelines: List[_WritePipeline],
    storage: StoragePlugin,
    budget: _Budget,
    executor: ThreadPoolExecutor,
    staging_done: threading.Event,
    stats: dict,
) -> None:
    # Part-streaming eligibility: a stager that can produce parts, a
    # plugin that can absorb them, an object over the stripe threshold,
    # and no interior checksum ranges (slab member sinks need the whole
    # buffer) or pending dedup decision (link-vs-write needs the object
    # digest before any byte moves).  Eligible pipelines reserve only a
    # window of parts from the budget and stage→write each part through
    # the stripe engine.
    #
    # Codec (codec.py): resolved ONCE per pipeline run — CODEC=raw
    # resolves to None here and the whole layer vanishes (zero per-part
    # cost).  Only writes carrying a codec_sink participate: the sink is
    # how the per-object frame table reaches the manifest, and a write
    # without one (external callers, metadata) could never be decoded.
    codec_spec = codec_mod.resolve_write_spec()
    part_size = knobs.get_stripe_part_size_bytes()
    stream_floor = knobs.get_stripe_min_object_size_bytes()
    for p in pipelines:
        wr = p.write_req
        if wr.cas is not None:
            # CAS part pipeline (cas/store.cas_streamed_write): large
            # objects stage→digest→store per CHUNK, so an unchanged
            # part skips its write and releases its admission window
            # the moment its digest resolves.  Needs whole-buffer-only
            # checksum sinks (interior slab ranges want the assembled
            # buffer) and the same size floor as striping — chunk puts
            # need no striped-write plugin capability (each chunk is an
            # ordinary whole-object write).
            if (
                stream_floor is not None
                and p.staging_cost >= stream_floor
                and all(
                    rng is None for _, rng in (wr.checksum_sinks or ())
                )
            ):
                spans = wr.buffer_stager.part_plan(wr.cas.chunk_size)
                if (
                    spans
                    and len(spans) > 1
                    and spans[-1][1] == p.staging_cost
                ):
                    p.stream_spans = spans
                    p.admission_cost = min(
                        p.staging_cost,
                        _STREAM_WINDOW_PARTS * wr.cas.chunk_size,
                    )
            continue
        if (
            wr.dedup is None
            and stripe.write_eligible(p.staging_cost, storage)
            and all(rng is None for _, rng in (wr.checksum_sinks or ()))
        ):
            spans = wr.buffer_stager.part_plan(part_size)
            if spans and len(spans) > 1 and spans[-1][1] == p.staging_cost:
                p.stream_spans = spans
                p.admission_cost = min(
                    p.staging_cost, _STREAM_WINDOW_PARTS * part_size
                )

    ready_for_staging = deque(pipelines)
    ready_for_io: deque = deque()
    staging_tasks: set = set()
    io_tasks: set = set()
    stream_tasks: set = set()
    io_concurrency = knobs.get_max_per_rank_io_concurrency()
    reporter = _WriteReporter(budget, stats)
    # observability: counters/gauges are always on (one locked arithmetic
    # op per pipeline transition); spans exist only under the TRACE knob.
    # Budget-admission spans open per request at pipeline start and close
    # at admission, so queue-wait time is first-class in the trace; a
    # flow id recorded at staging completion links each staging span to
    # its storage-I/O span (the Perfetto async arrow).
    m_staged = obs_metrics.counter(obs_metrics.BYTES_STAGED)
    m_written = obs_metrics.counter(obs_metrics.BYTES_WRITTEN)
    m_deduped = obs_metrics.counter(obs_metrics.BYTES_DEDUPED)
    m_budget = obs_metrics.gauge(obs_metrics.BUDGET_BYTES_IN_USE)
    m_ioq = obs_metrics.gauge(obs_metrics.IO_QUEUE_DEPTH)
    # always-on phase clocks: per-operation deltas of these feed the
    # cross-rank flight record's straggler attribution (obs/aggregate)
    m_phase_stage = obs_metrics.histogram(obs_metrics.PHASE_STAGE_S)
    m_phase_encode = obs_metrics.histogram(obs_metrics.PHASE_ENCODE_S)
    m_phase_write = obs_metrics.histogram(obs_metrics.PHASE_WRITE_S)
    tracer = obs_tracer.get_tracer()
    adm_spans: dict = {}
    flow_ids: dict = {}
    if obs_tracer.ENABLED:
        for p in pipelines:
            adm_spans[id(p)] = tracer.begin(
                "pipeline/budget_admission",
                path=p.write_req.path,
                bytes=p.staging_cost,
            )

    def _admitted(p: _WritePipeline) -> None:
        m_budget.set(budget.used)
        sp = adm_spans.pop(id(p), None)
        if sp is not None:
            tracer.end(sp, fire_event=True)

    # smallest pending admission cost: lets a wake where nothing can fit
    # skip the admission scan in O(1) instead of rotating the whole
    # deque on every task completion (O(n^2) across a large take)
    min_pending_cost = min((p.admission_cost for p in pipelines), default=0)

    async def stage_one(p: _WritePipeline) -> _WritePipeline:
        with obs_tracer.span(
            "pipeline/staging", path=p.write_req.path, cost=p.staging_cost
        ) as sp:
            await _stage_one_inner(p)
            if sp is not None:
                sp.attrs["bytes"] = p.buf_size
                # flow arrow anchor: this staging span's end links to
                # the matching pipeline/io span's start in the export
                sp.flow_out = flow_ids[id(p)] = obs_tracer.next_flow_id()
        return p

    async def _stage_one_inner(p: _WritePipeline) -> _WritePipeline:
        # clock starts BEFORE the failpoint so injected delay<ms>
        # slowness lands in the phase the flight record attributes
        t_stage = time.perf_counter()
        failpoint("scheduler.stage", path=p.write_req.path)
        p.buf = await p.write_req.buffer_stager.stage_buffer(executor)
        p.buf_size = _buf_nbytes(p.buf)
        wr = p.write_req
        # chunk-store writes never encode (chunk keys ARE raw digests;
        # compressing would re-key identical content per take) and never
        # defer digests (the skip-write decision needs them pre-write)
        will_encode = (
            codec_spec is not None
            and wr.codec_sink is not None
            and wr.cas is None
        )
        if (wr.checksum_sinks or wr.digest_sink) and (
            knobs.write_checksums_enabled()
        ):
            precomputed = getattr(wr.buffer_stager, "piece_digests", None)
            if (
                (
                    # stripe-eligible writes defer when the plugin's
                    # part handles fuse digests (the folded per-part
                    # digests replace this pass); whole-object writes
                    # defer on the plugin-level fused write
                    getattr(storage, "supports_fused_part_digest", False)
                    if stripe.write_eligible(p.buf_size, storage)
                    else getattr(storage, "supports_fused_digest", False)
                )
                and wr.dedup is None
                and wr.cas is None
                and not will_encode  # fused digest would hash STORED bytes
                and precomputed is None
                and all(
                    rng is None or (rng[0] == 0 and rng[1] == p.buf_size)
                    for _, rng in (wr.checksum_sinks or ())
                )
            ):
                # whole-buffer sinks, no dedup decision pending: defer
                # to write_one, where an honoring plugin digests each
                # block cache-hot in the SAME pass that writes it —
                # one read of the staged bytes instead of two.  Dedup
                # writes can't defer (the link-vs-write decision needs
                # the digest first), and slab writes already fold from
                # the pack's per-member digests.
                p.defer_digest = True
                m_phase_stage.observe(time.perf_counter() - t_stage)
                return p
            # content checksums into the manifest (entries are serialized
            # at commit, strictly after staging completes) — off-loop,
            # the staged buffer is immutable from here on
            await asyncio.get_running_loop().run_in_executor(
                executor,
                _apply_checksum_sinks,
                p.buf,
                wr.checksum_sinks,
                wr.digest_sink,
                precomputed,
            )
        m_phase_stage.observe(time.perf_counter() - t_stage)
        if will_encode and not (
            wr.dedup is not None and wr.object_digest == wr.dedup[1]
        ):
            # compress stage (codec.py): digests above ran on the RAW
            # bytes; the staged buffer is replaced by its encoded frames
            # here, so everything downstream (striping decision, budget
            # correction, bytes_written stats) sees STORED bytes.  A
            # write whose dedup digest matched the base skips encoding
            # entirely — it will link, not move bytes.
            t_enc = time.perf_counter()
            p.buf = await _encode_staged_buffer(p, wr, codec_spec, executor)
            p.buf_size = _buf_nbytes(p.buf)
            m_phase_encode.observe(time.perf_counter() - t_enc)
        return p

    async def write_one(p: _WritePipeline) -> _WritePipeline:
        with obs_tracer.span(
            "pipeline/io", path=p.write_req.path, bytes=p.buf_size
        ) as sp:
            if sp is not None:
                fid = flow_ids.pop(id(p), None)
                if fid is not None:
                    sp.flow_in = fid
            t_write = time.perf_counter()
            try:
                return await _write_one_inner(p)
            finally:
                m_phase_write.observe(time.perf_counter() - t_write)

    async def _write_one_inner(p: _WritePipeline) -> _WritePipeline:
        failpoint("scheduler.write", path=p.write_req.path)
        wr = p.write_req
        if wr.cas is not None:
            # content-addressed skip-write short-circuit: digest the
            # staged buffer in chunk-size spans and move only the
            # chunks no committed step already pooled; the chunk table
            # (not a per-step object) is what reaches the manifest
            _table, p.cas_written, p.cas_shared = (
                await cas_store_mod.chunked_write(
                    wr.cas, wr.path, p.buf, executor
                )
            )
            return p
        if wr.dedup is not None and wr.object_digest == wr.dedup[1]:
            # content unchanged vs the base snapshot: link/server-side
            # copy instead of moving the bytes again.  Any failure
            # (plugin without link_from, base object gone, S3's 5GiB
            # CopyObject cap) degrades to the normal write — dedup is an
            # optimization, never a correctness dependency.
            try:
                await storage.link_from(wr.dedup[0], wr.path)
                stats["deduped_bytes"] = (
                    stats.get("deduped_bytes", 0) + p.buf_size
                )
                # the linked object is a byte-copy of the BASE's stored
                # object; if the base was codec-encoded, this snapshot's
                # manifest must carry the base's frame table verbatim
                if wr.codec_sink is not None and wr.dedup_codec is not None:
                    wr.codec_sink(dict(wr.dedup_codec))
                p.deduped = True
                return p
            except Exception as e:  # noqa: BLE001
                logger.info(
                    "dedup link for %r failed (%r); writing normally",
                    wr.path, e,
                )
        if stripe.write_eligible(p.buf_size, storage):
            # whole-staged striped write: the buffer exists, so split it
            # into concurrent parts (true multipart on s3, compose parts
            # on gcs, engine/offset-parallel pwrite on fs).  When the
            # digest was deferred (_stage_one_inner: the plugin's part
            # handles fuse), each part's (crc32, adler32) rides its
            # write and the folded result replaces the staging-phase
            # pass; a declining handle degrades to that one extra pass.
            d = await stripe.striped_write(
                storage, wr.path, p.buf, want_digests=p.defer_digest
            )
            if p.defer_digest:
                if d is None:
                    await asyncio.get_running_loop().run_in_executor(
                        executor,
                        _apply_checksum_sinks,
                        p.buf,
                        wr.checksum_sinks,
                        wr.digest_sink,
                        None,
                    )
                else:
                    for sink, _rng in wr.checksum_sinks or ():
                        sink(d[0])
                    if wr.digest_sink is not None:
                        wr.digest_sink([d[0], d[1], d[2]])
            return p
        wio = WriteIO(path=wr.path, buf=p.buf, want_digest=p.defer_digest)
        await storage.write(wio)
        if p.defer_digest:
            d = wio.digests
            if d is None:
                # plugin didn't fuse: compute now (same values, one
                # extra pass — exactly what the old order always paid)
                await asyncio.get_running_loop().run_in_executor(
                    executor,
                    _apply_checksum_sinks,
                    p.buf,
                    wr.checksum_sinks,
                    wr.digest_sink,
                    None,
                )
            else:
                for sink, _rng in wr.checksum_sinks or ():
                    sink(d[0])
                if wr.digest_sink is not None:
                    wr.digest_sink([d[0], d[1], p.buf_size])
        return p

    async def stream_one(p: _WritePipeline) -> _WritePipeline:
        """Per-part stage→write streaming through the stripe engine: a
        part's copy completes → its write dispatches immediately while
        later parts are still staging.  Budget debit/credit, retries,
        failpoints, breaker accounting and spans/metrics all sit at
        part granularity inside the engine."""
        wr = p.write_req
        want = bool(wr.checksum_sinks or wr.digest_sink) and (
            knobs.write_checksums_enabled()
        )

        def on_part_staged(n: int) -> None:
            m_staged.inc(n)

        def on_part_done(n: int) -> None:
            stats["bytes_written"] += n
            m_written.inc(n)

        stream_codec = (
            codec_spec if wr.codec_sink is not None else None
        )
        with obs_tracer.span(
            "pipeline/stream", path=wr.path, bytes=p.staging_cost,
            parts=len(p.stream_spans),
        ):
            # both scheduler failpoints fire so existing stage/write
            # chaos schedules keep covering streamed objects
            failpoint("scheduler.stage", path=wr.path)
            failpoint("scheduler.write", path=wr.path)
            if wr.cas is not None:
                # CAS part pipeline: stage→digest→store per chunk;
                # unchanged chunks skip their write and on_part_done
                # reports 0 bytes for them, so accounting below sees
                # only content that moved; skipped bytes feed
                # bytes_deduped like the whole-staged CAS path does
                digests = await cas_store_mod.cas_streamed_write(
                    wr.cas,
                    wr.path,
                    wr.buffer_stager,
                    p.stream_spans,
                    executor,
                    window_parts=_STREAM_WINDOW_PARTS,
                    on_part_staged=on_part_staged,
                    on_part_done=on_part_done,
                    on_part_shared=m_deduped.inc,
                )
            else:
                digests = await stripe.streamed_part_write(
                    storage,
                    wr.path,
                    wr.buffer_stager,
                    p.stream_spans,
                    executor,
                    window_parts=_STREAM_WINDOW_PARTS,
                    on_part_staged=on_part_staged,
                    on_part_done=on_part_done,
                    want_digests=want,
                    codec_spec=stream_codec,
                    filter_stride=getattr(
                        wr.buffer_stager, "codec_filter_stride", 0
                    ),
                    codec_sink=wr.codec_sink,
                )
        p.buf_size = p.staging_cost
        if want and digests:
            from .utils.checksums import combine_piece_digests

            crc, adler, total = combine_piece_digests(digests)
            for sink, _rng in wr.checksum_sinks or ():
                sink(crc)
            if wr.digest_sink is not None:
                wr.digest_sink([crc, adler, total])
        return p

    def _launch(p: _WritePipeline) -> None:
        if p.stream_spans is not None:
            stream_tasks.add(asyncio.ensure_future(stream_one(p)))
        else:
            staging_tasks.add(asyncio.ensure_future(stage_one(p)))

    def dispatch_staging() -> None:
        # Scan ALL pending requests, admitting every one that fits the
        # remaining budget — the deque is largest-first, so breaking at
        # a non-fitting head would idle smaller items that DO fit
        # (head-of-line blocking; reference scheduler.py:266-277 iterates
        # the whole ready set).  If nothing fits and nothing is in
        # flight, admit one oversized item to guarantee progress.
        nonlocal min_pending_cost
        if not ready_for_staging:
            return
        if budget.fits(min_pending_cost):
            new_min = None
            for _ in range(len(ready_for_staging)):
                p = ready_for_staging.popleft()
                if budget.fits(p.admission_cost):
                    budget.debit(p.admission_cost)
                    _admitted(p)
                    _launch(p)
                else:
                    ready_for_staging.append(p)
                    if new_min is None or p.admission_cost < new_min:
                        new_min = p.admission_cost
            min_pending_cost = new_min or 0
            if not ready_for_staging:
                return
        if (
            not staging_tasks
            and not stream_tasks
            and not io_tasks
            and not ready_for_io
        ):
            # rotation preserves the largest-first order, so the head is
            # the largest pending item; admitting it leaves min unchanged
            p = ready_for_staging.popleft()
            budget.debit(p.admission_cost)
            _admitted(p)
            _launch(p)
            if not ready_for_staging:
                min_pending_cost = 0

    def dispatch_io() -> None:
        while ready_for_io and len(io_tasks) < io_concurrency:
            p = ready_for_io.popleft()
            io_tasks.add(asyncio.ensure_future(write_one(p)))
        m_ioq.set(len(ready_for_io))

    try:
        while (
            ready_for_staging
            or staging_tasks
            or ready_for_io
            or io_tasks
            or stream_tasks
        ):
            dispatch_staging()
            dispatch_io()
            reporter.maybe_report(
                len(ready_for_staging),
                len(staging_tasks) + len(stream_tasks),
                len(ready_for_io),
                len(io_tasks),
            )
            if not staging_tasks and not io_tasks and not stream_tasks:
                continue
            # timeout keeps the reporter ticking through long stalls (e.g.
            # one giant storage write in flight)
            done, _ = await asyncio.wait(
                staging_tasks | io_tasks | stream_tasks,
                return_when=asyncio.FIRST_COMPLETED,
                timeout=_PROGRESS_INTERVAL_S,
            )
            for task in done:
                if task in staging_tasks:
                    staging_tasks.discard(task)
                    p = task.result()
                    # correct declared cost to actual buffer size
                    # (reference scheduler.py:308-312)
                    budget.credit(p.staging_cost - p.buf_size)
                    m_budget.set(budget.used)
                    m_staged.inc(p.buf_size)
                    ready_for_io.append(p)
                    m_ioq.set(len(ready_for_io))
                elif task in stream_tasks:
                    # streamed pipelines account bytes per part inside
                    # the engine; only the window reservation returns
                    stream_tasks.discard(task)
                    p = task.result()
                    budget.credit(p.admission_cost)
                    m_budget.set(budget.used)
                else:
                    io_tasks.discard(task)
                    p = task.result()
                    if p.write_req.cas is not None:
                        # chunked objects account what actually moved;
                        # skipped chunk bytes are the dedup win
                        stats["bytes_written"] += p.cas_written
                        m_written.inc(p.cas_written)
                        if p.cas_shared:
                            m_deduped.inc(p.cas_shared)
                    elif not p.deduped:  # linked objects moved no bytes
                        stats["bytes_written"] += p.buf_size
                        m_written.inc(p.buf_size)
                    else:
                        m_deduped.inc(p.buf_size)
                    budget.credit(p.buf_size)
                    m_budget.set(budget.used)
                    p.buf = None
            if (
                not ready_for_staging
                and not staging_tasks
                and not stream_tasks
            ):
                # a streamed pipeline's source stays referenced until
                # its LAST part stages, so "staging done" (the point the
                # caller may mutate training state again) must wait for
                # in-flight streams too
                staging_done.set()
        stats["end_ts"] = time.monotonic()
        staging_done.set()
    except BaseException:
        staging_done.set()  # unblock the waiting caller; error surfaces via fut
        for t in staging_tasks | io_tasks | stream_tasks:
            t.cancel()
        raise
    finally:
        # requests never admitted (error/cancel path) close their
        # admission spans here so the trace has no dangling opens
        for sp in adm_spans.values():
            sp.attrs["error"] = True
            tracer.end(sp, fire_event=True)
        adm_spans.clear()


def sync_execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    wait_for_staging: bool = True,
) -> PendingIOWork:
    """Stage all write requests under the memory budget; return once staging
    completes, with residual storage I/O draining in the background
    (reference sync_execute_write_reqs, scheduler.py:342-357).

    With ``wait_for_staging=False`` the call returns immediately and the
    whole pipeline (staging + storage I/O) drains on the loop thread — used
    by ``async_take`` after ``eager_offload_write_reqs`` has already made
    every buffer independent of training state, which moves the unblock
    point from staged-in-client-RAM to DMA-dispatched (the pipeline
    itself kicks off lazily from the commit thread's sync_complete so the
    caller's blocked window pays for nothing but planning + dispatch)."""
    executor = ThreadPoolExecutor(
        max_workers=knobs.get_staging_threads(), thread_name_prefix="tsnp-staging"
    )
    # Largest-first staging keeps the budget well-packed and starts the
    # biggest D2H transfers earliest.
    pipelines = sorted(
        (_WritePipeline(wr) for wr in write_reqs),
        key=lambda p: p.staging_cost,
        reverse=True,
    )
    budget = _Budget(memory_budget_bytes)
    staging_done = threading.Event()
    stats = {"bytes_written": 0, "begin_ts": time.monotonic()}
    loop_thread = _LoopThread()

    def _start() -> concurrent.futures.Future:
        return loop_thread.submit(
            _execute_write_pipelines(
                pipelines, storage, budget, executor, staging_done, stats
            )
        )

    if not wait_for_staging:
        # Unblock-early path: every buffer is already independent of
        # training state (eager_offload_write_reqs), so nothing here needs
        # to run before control returns.  Defer the pipeline kick-off to
        # the background thread that calls sync_complete().
        return PendingIOWork(None, loop_thread, executor, stats, starter=_start)

    fut = _start()
    while not staging_done.wait(timeout=0.05):
        if fut.done():
            break
    pending = PendingIOWork(fut, loop_thread, executor, stats)
    if fut.done() and fut.exception() is not None:
        pending.sync_complete()  # raises
    return pending


async def _execute_copy_pipelines(
    paths: List[str],
    src_storage: StoragePlugin,
    dst_storage: StoragePlugin,
    budget: _Budget,
    io_concurrency: int,
    counter_name: str,
) -> int:
    """Copy whole objects src→dst, admitted under the host-memory budget
    (each in-flight copy buffers its full payload; an oversized object is
    admitted alone — the same progress rule as the write pipeline)."""
    m_promoted = obs_metrics.counter(counter_name)
    sem = asyncio.Semaphore(io_concurrency)
    cond = asyncio.Condition()
    in_use = 0

    async def one(path: str) -> int:
        nonlocal in_use
        nbytes = await src_storage.stat(path)
        async with cond:
            await cond.wait_for(
                lambda: in_use == 0 or in_use + nbytes <= budget.total
            )
            in_use += nbytes
        try:
            async with sem:
                with obs_tracer.span(
                    "tier/promote_object", path=path, bytes=nbytes
                ):
                    read_io = ReadIO(path=path)
                    await src_storage.read(read_io)
                    await dst_storage.write(
                        WriteIO(path=path, buf=read_io.buf)
                    )
            m_promoted.inc(nbytes)
            return nbytes
        finally:
            async with cond:
                in_use -= nbytes
                cond.notify_all()

    copied = await asyncio.gather(*(one(p) for p in paths))
    return sum(copied)


async def _execute_buffer_writes(
    items: List[Tuple[str, Any]],
    dst_storage: StoragePlugin,
    budget: _Budget,
    io_concurrency: int,
    counter_name: str,
    failpoint_site: Optional[str] = None,
    span_label: str = "scheduler/buffer_write",
    transport: Any = None,
) -> int:
    """Write already-staged ``(path, buf)`` pairs to ``dst_storage``,
    admitted under the host-memory budget: the buffers exist either
    way, but admission bounds how many a retrying/backpressured target
    can hold IN FLIGHT at once (each queued write can buffer its
    payload again inside the plugin — temp copies, retry bodies), with
    the same oversized-item progress rule as the copy pipeline.

    ``transport`` routes each payload through the engine's fabric leg
    (``Transport.device_move`` — a digest-verified device round-trip on
    the collective engine, identity on KV) before the write.  Any
    transport failure degrades THAT payload to the original staged
    bytes with ``transport.fallbacks`` advancing; correctness never
    depends on the fabric."""
    m_written = obs_metrics.counter(counter_name)
    sem = asyncio.Semaphore(io_concurrency)
    cond = asyncio.Condition()
    in_use = 0

    async def one(path: str, buf: Any) -> int:
        nonlocal in_use
        nbytes = memoryview(buf).cast("B").nbytes
        async with cond:
            await cond.wait_for(
                lambda: in_use == 0 or in_use + nbytes <= budget.total
            )
            in_use += nbytes
        try:
            if failpoint_site is not None:
                failpoint(failpoint_site, path=path)
            out = buf
            if transport is not None:
                from .transport import count_fallback

                loop = asyncio.get_running_loop()
                try:
                    out = await loop.run_in_executor(
                        None, transport.device_move, buf
                    )
                except Exception as e:  # noqa: BLE001 — fabric-leg
                    # failure must cost speed, never the replica
                    count_fallback("buffer-write", e)
                    out = buf
            async with sem:
                with obs_tracer.span(span_label, path=path, bytes=nbytes):
                    await dst_storage.write(WriteIO(path=path, buf=out))
            m_written.inc(nbytes)
            return nbytes
        finally:
            async with cond:
                in_use -= nbytes
                cond.notify_all()

    written = await asyncio.gather(*(one(p, b) for p, b in items))
    return sum(written)


def sync_execute_buffer_writes(
    items: List[Tuple[str, Any]],
    dst_storage: StoragePlugin,
    memory_budget_bytes: int,
    counter_name: str,
    failpoint_site: Optional[str] = None,
    span_label: str = "scheduler/buffer_write",
    loop_thread: Optional[_LoopThread] = None,
    transport: Any = None,
) -> int:
    """Write staged ``(path, buf)`` pairs concurrently under the staging
    memory budget; returns bytes written.  This is the continuous
    checkpoint loop's replication engine (continuous/loop.py): per-step
    delta chunks ride this to the local and peer fast roots as budgeted
    background work, so replication can never out-buffer the budget a
    host sized for takes (the same admission discipline as staging and
    tier promotion).  ``loop_thread`` lets a per-step caller reuse ONE
    long-lived event-loop thread (it stays alive after the call)
    instead of paying thread+loop churn on every training step; omitted,
    a private one is created and torn down like the copy engine's."""
    if not items:
        return 0
    budget = _Budget(memory_budget_bytes)
    own_loop = loop_thread is None
    lt = loop_thread or _LoopThread(name="tsnp-continuous-loop")
    try:
        return lt.submit(
            _execute_buffer_writes(
                items,
                dst_storage,
                budget,
                knobs.get_max_per_rank_io_concurrency(),
                counter_name,
                failpoint_site,
                span_label,
                transport,
            )
        ).result()
    finally:
        if own_loop:
            lt.shutdown()


async def _execute_chunk_reads(
    items: List[Tuple[str, Optional[Tuple[int, int]], Optional[str], int]],
    storage: StoragePlugin,
    budget: _Budget,
    io_concurrency: int,
    span_label: str,
) -> List[bytes]:
    """Read ``(path, byte_range, content_key, nbytes)`` items under the
    host-memory budget, verifying keyed payloads against their embedded
    (crc32, adler32, size) digest — a torn or stale copy fails closed.
    Results come back in submission order."""
    from .utils.checksums import adler32_fast, crc32_fast

    sem = asyncio.Semaphore(io_concurrency)
    cond = asyncio.Condition()
    in_use = 0
    out: List[Optional[bytes]] = [None] * len(items)

    async def one(i: int) -> None:
        nonlocal in_use
        path, byte_range, key, nbytes = items[i]
        async with cond:
            await cond.wait_for(
                lambda: in_use == 0 or in_use + nbytes <= budget.total
            )
            in_use += nbytes
        try:
            async with sem:
                with obs_tracer.span(
                    span_label, path=path, bytes=nbytes
                ):
                    io = ReadIO(path=path, byte_range=byte_range)
                    await storage.read(io)
            view = memoryview(io.buf).cast("B")
            if key is not None and (
                view.nbytes != cas_store_mod.key_size(key)
                or cas_store_mod.chunk_key(
                    (crc32_fast(view), adler32_fast(view), view.nbytes)
                )
                != key
            ):
                raise IOError(
                    f"chunk {key} at {path!r} failed its content "
                    f"check ({view.nbytes} bytes)"
                )
            if view.nbytes != nbytes:
                raise IOError(
                    f"ranged read of {path!r} returned {view.nbytes} "
                    f"bytes, expected {nbytes}"
                )
            out[i] = bytes(view)
        finally:
            async with cond:
                in_use -= nbytes
                cond.notify_all()

    results = await asyncio.gather(
        *(one(i) for i in range(len(items))), return_exceptions=True
    )
    errs = [r for r in results if isinstance(r, BaseException)]
    if errs:
        raise errs[0]
    # every slot filled: a None would have surfaced as an error above
    return [b for b in out if b is not None]


def sync_execute_chunk_reads(
    items: List[Tuple[str, Optional[Tuple[int, int]], Optional[str], int]],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    priorities: Optional[List[int]] = None,
    span_label: str = "scheduler/chunk_read",
    loop_thread: Optional[_LoopThread] = None,
) -> List[bytes]:
    """Verified ranged/content-addressed reads for delta subscribers
    (publish/subscriber.py): fetch ``(path, byte_range, content_key,
    nbytes)`` items concurrently under the staging memory budget and
    return payloads in the caller's order.  ``priorities`` reuses the
    restore priority classes (ReadReq.priority discipline from
    sync_execute_read_reqs): a stable sort dispatches lower classes
    first, so a serving fleet can front-load the leaves its next
    request needs while bulk deltas trail — within a class, submission
    order is preserved.  ``loop_thread`` lets a long-lived watcher
    reuse one event-loop thread across polls instead of paying
    thread+loop churn per update."""
    if not items:
        return []
    order = list(range(len(items)))
    if priorities is not None and any(priorities):
        order.sort(key=lambda i: priorities[i])
    budget = _Budget(memory_budget_bytes)
    own_loop = loop_thread is None
    lt = loop_thread or _LoopThread(name="tsnp-publish-loop")
    try:
        fetched = lt.submit(
            _execute_chunk_reads(
                [items[i] for i in order],
                storage,
                budget,
                knobs.get_max_per_rank_io_concurrency(),
                span_label,
            )
        ).result()
    finally:
        if own_loop:
            lt.shutdown()
    out: List[bytes] = [b""] * len(items)
    for pos, i in enumerate(order):
        out[i] = fetched[pos]
    return out


def sync_execute_copy_reqs(
    paths: List[str],
    src_storage: StoragePlugin,
    dst_storage: StoragePlugin,
    memory_budget_bytes: int,
    counter_name: Optional[str] = None,
) -> int:
    """Copy the named objects from ``src_storage`` to ``dst_storage``
    under the staging memory budget; returns bytes copied.  This is the
    tier promoter's engine (tier/promoter.py): write-back fast-tier
    payloads ride this to the durable tier in the background, with the
    same budget discipline as staging so a promotion burst can never
    OOM a host that sized its budget for takes.  Peer replication
    (tier/plugin.py) reuses it with ``counter_name`` pointed at the
    replication counter."""
    if not paths:
        return 0
    budget = _Budget(memory_budget_bytes)
    loop_thread = _LoopThread(name="tsnp-promote-loop")
    try:
        return loop_thread.submit(
            _execute_copy_pipelines(
                paths,
                src_storage,
                dst_storage,
                budget,
                knobs.get_max_per_rank_io_concurrency(),
                counter_name or obs_metrics.BYTES_PROMOTED,
            )
        ).result()
    finally:
        loop_thread.shutdown()


class _ReadPipeline:
    __slots__ = ("read_req", "consuming_cost", "admission_cost", "use_mmap", "buf")

    def __init__(self, read_req: ReadReq) -> None:
        self.read_req = read_req
        self.consuming_cost = read_req.buffer_consumer.get_consuming_cost_bytes()
        # what budget admission debits: the consuming cost, except for
        # mmap-served reads, which admit at 0 (set in
        # _execute_read_pipelines) — their pages are file-backed and
        # reclaimable, so they occupy no heap the budget protects
        self.admission_cost = self.consuming_cost
        self.use_mmap = False
        self.buf = None


async def _execute_read_pipelines(
    pipelines: List[_ReadPipeline],
    storage: StoragePlugin,
    budget: _Budget,
    executor: ThreadPoolExecutor,
    codec_tables: Optional[dict] = None,
    cas_reads: Optional[tuple] = None,
) -> None:
    # Zero-copy serving (io_types.ReadIO.want_mmap): raw reads against
    # a plugin whose reads NEVER transit the heap (mmap_budget_exempt —
    # fs, the host cache, tiers whose both legs qualify) are served as
    # read-only file-backed mappings and admitted BUDGET-EXEMPT —
    # serializing reclaimable page mappings behind the host staging
    # budget would throttle a many-reader cold start for no
    # memory-safety gain.  Deliberately keyed on the STRICT capability,
    # not supports_mmap_read: a tier over a raw cloud durable keeps its
    # budgeted, striped reads on the degraded fallback path.  Codec
    # frames and CAS chunk refs need a byte transform, so they keep the
    # copying (budgeted) path; a read with an ``into`` destination is
    # already one-touch and wants the bytes in ITS buffer, not a
    # foreign mapping.
    mmap_capable = knobs.mmap_enabled() and getattr(
        storage, "mmap_budget_exempt", False
    )
    for p in pipelines:
        rr = p.read_req
        if (
            mmap_capable
            and rr.into is None
            and not (codec_tables and rr.path in codec_tables)
            and not (cas_reads is not None and rr.path in cas_reads[1])
        ):
            p.use_mmap = True
            p.admission_cost = 0
    ready_for_io = deque(pipelines)
    io_tasks: set = set()
    consume_tasks: set = set()
    io_concurrency = knobs.get_max_per_rank_io_concurrency()
    # observability twins of the write loop's instruments, direction-
    # suffixed: an async_take's background drain can overlap a restore
    # in this process, so the pipelines get separate gauges
    m_read = obs_metrics.counter(obs_metrics.BYTES_READ)
    m_budget = obs_metrics.gauge(obs_metrics.BUDGET_BYTES_IN_USE_READ)
    m_ioq = obs_metrics.gauge(obs_metrics.IO_QUEUE_DEPTH_READ)
    # restore-side phase clocks (flight-record straggler attribution)
    m_phase_read = obs_metrics.histogram(obs_metrics.PHASE_READ_S)
    m_phase_consume = obs_metrics.histogram(obs_metrics.PHASE_CONSUME_S)
    tracer = obs_tracer.get_tracer()
    adm_spans: dict = {}
    if obs_tracer.ENABLED:
        for p in pipelines:
            adm_spans[id(p)] = tracer.begin(
                "pipeline/budget_admission",
                path=p.read_req.path,
                bytes=p.consuming_cost,
            )

    def _admitted(p: _ReadPipeline) -> None:
        m_budget.set(budget.used)
        sp = adm_spans.pop(id(p), None)
        if sp is not None:
            tracer.end(sp, fire_event=True)

    # smallest pending admission cost — O(1) skip of the admission scan
    # on wakes where nothing can fit (see the write loop's twin)
    min_pending_cost = min((p.admission_cost for p in pipelines), default=0)

    # striped reads need the object's byte length up front; a whole-
    # object read only knows its consuming-cost ESTIMATE, so resolve it
    # with a stat — but never through the base-class default, which
    # "stats" by reading the whole object (all shipped plugins override
    # it with a cheap metadata call)
    cheap_stat = type(storage).stat is not StoragePlugin.stat

    async def _striped_read(p: _ReadPipeline, sp) -> bool:
        """Fan a large read out as parallel ranged part GETs through the
        stripe engine (storage/stripe.py).  Returns False when the read
        turns out ineligible (size below threshold once known) so the
        caller falls through to the single-stream path."""
        rr = p.read_req
        if rr.byte_range is not None:
            offset, length = rr.byte_range[0], rr.byte_range[1] - rr.byte_range[0]
        else:
            if not cheap_stat:
                return False
            offset, length = 0, await storage.stat(rr.path)
        if not stripe.read_eligible(length):
            return False
        if sp is not None:
            sp.attrs["striped"] = True
        p.buf = await stripe.striped_read(
            storage, rr.path, offset=offset, length=length, into=rr.into
        )
        return True

    async def read_one(p: _ReadPipeline) -> _ReadPipeline:
        with obs_tracer.span(
            "pipeline/io",
            path=p.read_req.path,
            cost=p.consuming_cost,
            op="read",
        ) as sp:
            # clock before failpoint: injected delay must be attributed
            t_read = time.perf_counter()
            failpoint("scheduler.read", path=p.read_req.path)
            try:
                return await _read_one_inner(p, sp)
            finally:
                m_phase_read.observe(time.perf_counter() - t_read)

    async def _read_one_inner(p: _ReadPipeline, sp) -> _ReadPipeline:
        rr = p.read_req
        if cas_reads is not None:
            ctable = cas_reads[1].get(rr.path)
            if ctable is not None:
                # chunk-ref'd object (cas/): no per-step storage object
                # exists at this location — assemble the RAW byte range
                # from the shared chunk pool (parallel ranged chunk
                # reads, into-honoring).  Chunked objects are never
                # codec-encoded or striped, so this subsumes both.
                p.buf = await cas_store_mod.chunked_read(
                    cas_reads[0],
                    rr.path,
                    ctable,
                    byte_range=rr.byte_range,
                    into=rr.into,
                )
                if sp is not None:
                    sp.attrs["cas"] = True
                    sp.attrs["bytes"] = _buf_nbytes(p.buf)
                return p
        table = codec_tables.get(rr.path) if codec_tables else None
        if table is not None:
            # codec-encoded object (codec.py): the byte range is a
            # RAW range — map it to the overlapping frames, read
            # them as parallel ranged GETs and decode concurrently
            # on the consume executor.  Subsumes the striped-read
            # fan-out (frames ARE the parts).
            p.buf = await codec_mod.framed_read(
                storage,
                rr.path,
                table,
                byte_range=rr.byte_range,
                into=rr.into,
                executor=executor,
            )
            if sp is not None:
                sp.attrs["codec"] = table.get("codec")
                sp.attrs["bytes"] = _buf_nbytes(p.buf)
            return p
        if p.use_mmap:
            # one map call serves any size — fanning out parallel ranged
            # GETs (striping) would only buy page-cache copies, so the
            # striped path is deliberately skipped here
            read_io = ReadIO(
                path=rr.path, byte_range=rr.byte_range, want_mmap=True
            )
            await storage.read(read_io)
            p.buf = read_io.buf
            if _buf_nbytes(p.buf) and not is_mmap_backed(p.buf):
                # the plugin declined the mapping (e.g. a tiered read
                # whose fast copy is gone falling back to a cloud
                # durable): these bytes ARE heap — debit them so a
                # burst of declined reads can't blow past the budget
                # unaccounted.  May transiently overshoot the total;
                # further admission stalls until the consume credits
                # it back, which is exactly the wanted backpressure.
                p.admission_cost = p.consuming_cost
                budget.debit(p.admission_cost)
                m_budget.set(budget.used)
            if sp is not None:
                sp.attrs["mmap"] = is_mmap_backed(p.buf)
                sp.attrs["bytes"] = _buf_nbytes(p.buf)
            return p
        if stripe.read_eligible(
            rr.byte_range[1] - rr.byte_range[0]
            if rr.byte_range is not None
            else p.consuming_cost
        ) and await _striped_read(p, sp):
            if sp is not None:
                sp.attrs["bytes"] = _buf_nbytes(p.buf)
            return p
        read_io = ReadIO(
            path=rr.path,
            byte_range=rr.byte_range,
            into=rr.into,
        )
        await storage.read(read_io)
        p.buf = read_io.buf
        if sp is not None:
            sp.attrs["bytes"] = _buf_nbytes(p.buf)
        return p

    async def consume_one(p: _ReadPipeline) -> _ReadPipeline:
        with obs_tracer.span(
            "pipeline/consume",
            path=p.read_req.path,
            cost=p.consuming_cost,
        ) as sp:
            if sp is not None:
                # actual size, not the pre-read estimate (object entries
                # declare cost 1) — p.buf is released below, measure now
                sp.attrs["bytes"] = _buf_nbytes(p.buf)
            t_consume = time.perf_counter()
            if (
                p.read_req.expected_crc32 is not None
                and knobs.verify_on_restore()
            ):
                await asyncio.get_running_loop().run_in_executor(
                    executor, check_read_crc, p.read_req, p.buf
                )
            await p.read_req.buffer_consumer.consume_buffer(p.buf, executor)
            p.buf = None
            m_phase_consume.observe(time.perf_counter() - t_consume)
            return p

    try:
        while ready_for_io or io_tasks or consume_tasks:
            # admit reads under the consuming-cost budget, scanning past
            # non-fitting items so one big read can't idle small ones
            # (reference scheduler.py:386-446)
            if (
                ready_for_io
                and len(io_tasks) < io_concurrency
                and budget.fits(min_pending_cost)
            ):
                # Rotation discipline: once something was RE-APPENDED
                # (budget-skipped), the rotation must complete so the
                # deque's relative order is preserved; but when the io
                # CAP stops a pure-prefix admission, the remaining deque
                # is untouched and already in order — stop immediately.
                # A 20k-tiny-leaf restore otherwise pays a full O(n)
                # deque rotation on every wake (measured: most of the
                # admission loop's time).  On the early stop the min
                # watermark keeps its previous value, which remains a
                # valid conservative lower bound of the pending set.
                new_min = None
                reappended = False
                early_stop = False
                for _ in range(len(ready_for_io)):
                    if len(io_tasks) >= io_concurrency and not reappended:
                        early_stop = True
                        break
                    p = ready_for_io.popleft()
                    if len(io_tasks) < io_concurrency and budget.fits(
                        p.admission_cost
                    ):
                        budget.debit(p.admission_cost)
                        _admitted(p)
                        io_tasks.add(asyncio.ensure_future(read_one(p)))
                    else:
                        ready_for_io.append(p)
                        reappended = True
                        if new_min is None or p.admission_cost < new_min:
                            new_min = p.admission_cost
                if not early_stop:
                    min_pending_cost = new_min if new_min is not None else 0
            if ready_for_io and not io_tasks and not consume_tasks:
                p = ready_for_io.popleft()
                budget.debit(p.admission_cost)
                _admitted(p)
                io_tasks.add(asyncio.ensure_future(read_one(p)))
                min_pending_cost = min(
                    (q.admission_cost for q in ready_for_io), default=0
                )
            m_ioq.set(len(ready_for_io))
            if not io_tasks and not consume_tasks:
                continue
            done, _ = await asyncio.wait(
                io_tasks | consume_tasks, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                if task in io_tasks:
                    io_tasks.discard(task)
                    p = task.result()
                    # count ACTUAL bytes, not the consuming-cost estimate
                    # (object entries declare cost 1 before the read —
                    # the estimate would undercount them by orders of
                    # magnitude); p.buf is released by consume_one, so
                    # this is the last cheap place to measure it
                    m_read.inc(_buf_nbytes(p.buf))
                    consume_tasks.add(asyncio.ensure_future(consume_one(p)))
                else:
                    consume_tasks.discard(task)
                    p = task.result()
                    budget.credit(p.admission_cost)
                    m_budget.set(budget.used)
    except BaseException:
        for t in io_tasks | consume_tasks:
            t.cancel()
        raise
    finally:
        for sp in adm_spans.values():
            sp.attrs["error"] = True
            tracer.end(sp, fire_event=True)
        adm_spans.clear()


def sync_execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    codec_tables: Optional[dict] = None,
    cas_reads: Optional[tuple] = None,
    publish_first: Optional[set] = None,
) -> None:
    """Execute read requests under the memory budget (reference
    sync_execute_read_reqs, scheduler.py:449-463).

    ``codec_tables``: location → manifest codec-table entry for objects
    stored as compressed frames (SnapshotMetadata.codecs); reads of
    those locations decode transparently — byte ranges stay RAW
    everywhere above this call.

    ``cas_reads``: ``(ChunkStore, {location → chunk table})`` for
    chunk-ref'd objects (SnapshotMetadata.cas); reads of those
    locations assemble from the shared chunk pool instead of the
    snapshot's own storage — equally transparent.

    ``publish_first``: locations this rank redistributes to fan-out
    siblings (topology/fanout.py) — within each priority class those
    reads execute FIRST, so every sibling's wait for this rank's
    publications is bounded by the designated reads' latency, not by
    wherever they happened to land in the queue."""
    executor = ThreadPoolExecutor(
        max_workers=knobs.get_staging_threads(), thread_name_prefix="tsnp-consume"
    )
    # Restore prioritization (ReadReq.priority): stable sort, so a
    # server's first-requested layers head the admission queue and can
    # start serving before the full snapshot lands.  The common case
    # (all priorities 0, no fan-out) keeps its original order untouched.
    if publish_first:
        read_reqs = sorted(
            read_reqs,
            key=lambda rr: (
                rr.priority, 0 if rr.path in publish_first else 1
            ),
        )
    elif any(rr.priority for rr in read_reqs):
        read_reqs = sorted(read_reqs, key=lambda rr: rr.priority)
    pipelines = [_ReadPipeline(rr) for rr in read_reqs]
    budget = _Budget(memory_budget_bytes)
    loop_thread = _LoopThread(name="tsnp-read-loop")
    t0 = time.monotonic()
    fut = loop_thread.submit(
        _execute_read_pipelines(
            pipelines, storage, budget, executor, codec_tables, cas_reads
        )
    )
    try:
        fut.result()
        # read throughput breadcrumb (reference logs the symmetric
        # number on its read path, scheduler.py:443-444)
        total = sum(p.consuming_cost for p in pipelines)
        dt = max(time.monotonic() - t0, 1e-9)
        if total:
            logger.info(
                "rank %d: read %.2fGB in %.2fs (%.2f GB/s)",
                rank, total / 1e9, dt, total / 1e9 / dt,
            )
    finally:
        executor.shutdown(wait=False)
        loop_thread.shutdown()
