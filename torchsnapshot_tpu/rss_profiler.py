"""RSS sampling for proving bounded-memory behavior in benchmarks.

Reference: torchsnapshot/rss_profiler.py:34-58 — a background thread
samples psutil RSS deltas at a fixed interval while the context is active.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, List

import psutil

_DEFAULT_INTERVAL_S = 0.1


@contextlib.contextmanager
def measure_rss_deltas(
    rss_deltas: List[int], interval_s: float = _DEFAULT_INTERVAL_S
) -> Iterator[None]:
    """Append RSS-minus-baseline samples (bytes) to ``rss_deltas`` while
    the context is active; peak = max(rss_deltas)."""
    proc = psutil.Process()
    baseline = proc.memory_info().rss
    stop = threading.Event()

    def sample() -> None:
        while not stop.is_set():
            rss_deltas.append(proc.memory_info().rss - baseline)
            stop.wait(interval_s)

    thread = threading.Thread(target=sample, name="tsnp-rss", daemon=True)
    thread.start()
    try:
        yield
    finally:
        stop.set()
        thread.join()
        rss_deltas.append(proc.memory_info().rss - baseline)
        # benchmarks read memory and timing through one surface: the
        # observed peak lands in the metrics registry alongside the
        # pipeline counters (obs.metrics_snapshot / BENCH records)
        from .obs import metrics as _metrics

        _metrics.gauge(_metrics.RSS_PEAK_DELTA_BYTES).set(
            max(rss_deltas, default=0)
        )
