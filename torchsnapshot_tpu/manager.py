"""Snapshot lifecycle management: step-indexed saves, retention, GC.

Beyond-parity subsystem.  The reference leaves path bookkeeping to the
user (examples/simple_example.py hand-rolls "which epoch am I on");
every production training loop then reinvents the same four things:
step-numbered snapshot paths, "resume from the newest COMMITTED
snapshot", bounded retention, and garbage collection of evicted
snapshots.  ``SnapshotManager`` packages them on top of the existing
commit protocol (metadata-last, snapshot.py:817-896) — the TPU-ecosystem
analogue is orbax's CheckpointManager, re-designed around this library's
URL-based storage plugins and multi-controller coordination:

- **Discovery is index-first, scan-fallback.**  Cloud stores (the
  primary TPU target) have no cheap directory listing behind the
  ``StoragePlugin`` API, so the manager maintains ``manager_index.json``
  at the root via plain plugin read/write; local ``fs`` roots also get a
  directory scan so snapshots taken without the manager (or an index
  lost to a crash) are still found.
- **GC is metadata-first.**  Deleting ``.snapshot_metadata`` FIRST
  un-commits the snapshot atomically (restore-side contract: no
  metadata == aborted, snapshot.py:645); object deletes that crash
  midway leave an aborted snapshot, never a committed-but-corrupt one.
  Physical objects are enumerated from the manifest's entry locations —
  plugin-agnostic, no listing needed.
- **Multi-controller discipline matches take():** every rank calls
  ``save``/``restore_latest``; only rank 0 mutates the index and runs
  GC, after the commit barrier inside take.
"""

from __future__ import annotations

import json
import logging
import weakref
from typing import Any, Dict, List, Optional, Sequence, Union

from . import knobs, obs
from .coordination import Coordinator, get_default_coordinator
from .event import Event
from .event_handlers import log_event
from .io_types import ReadIO, WriteIO
from .manifest import Entry, SnapshotMetadata
from .snapshot import (
    SNAPSHOT_METADATA_FNAME,
    PendingSnapshot,
    Snapshot,
)
from .storage import url_to_storage_plugin
from .tier import TierConfig

logger = logging.getLogger(__name__)

INDEX_FNAME = "manager_index.json"


def entry_locations(manifest: Dict[str, Entry]) -> List[str]:
    """Every physical storage path a manifest references (relative to the
    snapshot root).  Used by GC to delete a snapshot through the plugin
    API without any directory-listing capability."""
    locs: set = set()
    for entry in manifest.values():
        loc = getattr(entry, "location", None)
        if isinstance(loc, str):
            locs.add(loc)
        for attr in ("shards", "chunks"):
            for shard in getattr(entry, attr, None) or ():
                sloc = getattr(shard, "location", None)
                if isinstance(sloc, str):
                    locs.add(sloc)
    return sorted(locs)


def delete_snapshot(
    path: str,
    manifest: Optional[Dict[str, Entry]] = None,
    metadata: Optional[SnapshotMetadata] = None,
    release_cas: bool = True,
) -> None:
    """Delete one snapshot, committed or aborted, metadata-first.

    Order matters: removing ``.snapshot_metadata`` first flips the
    snapshot to "aborted" for every reader (snapshot.py:645), so a crash
    between here and the last object delete can never be observed as a
    committed snapshot with missing data.

    ``manifest``/``metadata``, when the caller already verified/parsed
    them, skip the metadata re-read (one fewer cloud round-trip per
    eviction); ``metadata`` additionally carries the chunk tables a
    CAS-backed step needs for ref release.

    ``release_cas``: with a chunk store (cas/), drop this step's chunk
    refs from the shared index after the per-step objects go — chunks
    whose refcount hits zero are orphan-marked (and swept past the
    grace window); chunks other steps still reference survive, which is
    what lets ANY step of a chain be deleted.  Pass False for deletes
    of secondary COPIES of a step (fast-tier eviction under a tiered
    manager: the durable step still owns its refs)."""
    with log_event(Event("delete_snapshot", {"path": path})), obs.span(
        "manager/delete_snapshot", path=path
    ):
        _delete_snapshot_impl(path, manifest, metadata, release_cas)


def _delete_snapshot_impl(
    path: str,
    manifest: Optional[Dict[str, Entry]] = None,
    metadata: Optional[SnapshotMetadata] = None,
    release_cas: bool = True,
) -> None:
    storage = url_to_storage_plugin(path)
    try:
        locations: List[str] = []
        if metadata is not None and manifest is None:
            manifest = metadata.manifest
        if manifest is None:
            try:
                read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
                storage.sync_read(read_io)
                metadata = SnapshotMetadata.from_yaml(
                    bytes(read_io.buf).decode()
                )
                manifest = metadata.manifest
            except FileNotFoundError:
                pass  # aborted snapshot: no manifest to enumerate
            except Exception as e:  # noqa: BLE001 — corrupt metadata
                # still delete the metadata below (un-commit the poisoned
                # snapshot); its objects can't be enumerated and leak on
                # stores without listing — say so instead of crashing GC
                logger.warning(
                    "corrupt %s under %r (%r): deleting metadata only; "
                    "data objects may be left behind",
                    SNAPSHOT_METADATA_FNAME, path, e,
                )
        cas_info = (metadata.cas or {}) if metadata is not None else {}
        chunked_locs = set(cas_info.get("chunks") or {})
        if manifest is not None:
            # chunk-ref'd locations have no per-step object to delete —
            # their bytes belong to the shared pool and are handled by
            # the ref release below
            locations = [
                loc
                for loc in entry_locations(manifest)
                if loc not in chunked_locs
            ]
        try:
            storage.sync_delete(SNAPSHOT_METADATA_FNAME)
        except FileNotFoundError:
            pass
        reclaimed = 0
        extents = (
            _expected_extents_safe(manifest) if manifest is not None else {}
        )
        for loc in locations:
            try:
                storage.sync_delete(loc)
                reclaimed += extents.get(loc, 0)
            except FileNotFoundError:
                pass  # idempotent: partial previous GC
        if release_cas and chunked_locs:
            from . import cas as cas_mod

            # strictly AFTER the metadata delete: a crash window leaves
            # dangling refs for an uncommitted step, which the mark
            # phase reclaims — never a committed step with released
            # refs.  Only bytes whose refcount dropped to ZERO count as
            # reclaimed (shared chunks stay, and so do their bytes).
            try:
                reclaimed += cas_mod.release_step(
                    cas_mod.resolve_root(path, str(cas_info.get("root"))),
                    path,
                )
            except Exception as e:  # noqa: BLE001 — refs are reclaimed
                # by the next gc/fsck; the delete itself succeeded
                logger.warning(
                    "chunk-ref release for deleted %r failed (%r); the "
                    "next cas gc/fsck will reconcile", path, e,
                )
        if reclaimed:
            obs.counter(obs.GC_BYTES_RECLAIMED).inc(reclaimed)
    finally:
        storage.sync_close()
    # local fs roots: clear leftover (now-empty) directory skeleton
    if "://" not in path or path.startswith("file://"):
        import shutil

        shutil.rmtree(path.split("://", 1)[-1], ignore_errors=True)


def _expected_extents_safe(manifest: Dict[str, Entry]) -> Dict[str, int]:
    from .verify import _expected_extents

    try:
        return _expected_extents(manifest)
    except Exception:  # noqa: BLE001 — metric only, never fail a delete
        return {}


class SnapshotManager:
    """Step-indexed snapshots under one root with bounded retention.

    >>> mgr = SnapshotManager("/ckpt/run7", keep_last_n=3)
    >>> step = mgr.restore_latest(app_state)   # None on cold start
    >>> for step in range(step or 0, total):
    ...     ...
    ...     if step % 100 == 0:
    ...         mgr.save(app_state, step=step, async_=True)

    ``keep_last_n`` counts COMMITTED snapshots; the newest N survive.
    Retention runs on rank 0 after each committed save (for async saves:
    when the pending snapshot is waited on, or at the next save).
    """

    def __init__(
        self,
        root: str,
        keep_last_n: Optional[int] = None,
        prefix: str = "step_",
        coordinator: Optional[Coordinator] = None,
        tier: Optional[Union[TierConfig, Dict[str, Any]]] = None,
        cas: Optional[Union[bool, str, Dict[str, Any]]] = None,
        publisher: Any = None,
    ) -> None:
        if keep_last_n is not None and keep_last_n < 1:
            raise ValueError(f"keep_last_n must be >= 1, got {keep_last_n}")
        self.root = root.rstrip("/")
        self.keep_last_n = keep_last_n
        self.prefix = prefix
        # content-addressed chunk store (cas/): payload bytes live in a
        # shared per-root pool and every save dedups at chunk level
        # against ALL committed steps; retention releases refs instead
        # of assuming exclusive ownership.  None defers to the
        # TORCHSNAPSHOT_TPU_CAS knob; True places the pool at
        # <root>/cas; a str names the pool root; a dict may add
        # chunk_size_bytes.
        if cas is None:
            cas = knobs.cas_enabled()
        if isinstance(cas, (bool, int)):
            # accept 0/1 too — the knob this mirrors is an int env var
            cas = {} if cas else None
        elif isinstance(cas, str):
            cas = {"root": cas}
        if cas is not None:
            cas = dict(cas)
            cas.setdefault("root", f"{self.root}/cas")
        self.cas: Optional[Dict[str, Any]] = cas
        # tiered storage (tier/): ``root`` names the DURABLE tier; per-
        # step snapshots also land under ``tier.fast_root`` and reads go
        # fast-first.  Fast-tier retention (fast_keep_last_n) runs on
        # EVERY rank against its own local fast root; durable retention
        # stays rank-0-only like the index.
        self.tier = TierConfig(**tier) if isinstance(tier, dict) else tier
        self._coordinator = coordinator
        # rank 0 only: async saves not yet recorded in the index,
        # step -> weakref to its PendingSnapshot.  done() distinguishes
        # "commit still in flight" from "commit thread finished"; a
        # weakref (the commit thread itself keeps the object alive while
        # running) so the sweep list never pins staged buffers after
        # the caller drops its handle
        self._pending_async: Dict[int, "weakref.ref[PendingSnapshot]"] = {}
        # steps whose commit has been verified (commits are immutable,
        # so re-verification per sweep would be wasted cloud reads)
        self._verified: Dict[int, Snapshot] = {}
        # steps the last _verify call could not read metadata for
        # (possible transient outage — kept in the index, not committed)
        self._last_unverifiable: set = set()
        # tiered: steps whose DURABLE commit marker has been observed
        # (durability is monotonic, so each costs at most one cloud
        # metadata read per manager lifetime — fast-retention sweeps
        # would otherwise re-fetch for every old fast step every save)
        self._durable_confirmed: set = set()
        # tiered: the crash-recovery re-promotion sweep runs once, at
        # the first post-commit hook (see repromote)
        self._repromoted = False
        # live-weight publication (publish/): rank 0 publishes every
        # committed save so serving subscribers can delta-swap to it.
        # Best-effort — publication rides behind the commit, never
        # gates or fails it
        self._publisher = publisher

    # ------------------------------------------------------------ paths

    def path_for_step(self, step: int) -> str:
        # fixed-width so lexicographic listing == numeric ordering
        return f"{self.root}/{self.prefix}{step:010d}"

    def fast_path_for_step(self, step: int) -> str:
        assert self.tier is not None
        return (
            f"{self.tier.fast_root.rstrip('/')}/{self.prefix}{step:010d}"
        )

    def _tier_storage_options(
        self, step: int
    ) -> Optional[Dict[str, Any]]:
        """The ``storage_options`` that make this step's Snapshot
        tiered; None for untiered managers."""
        if self.tier is None:
            return None
        t = self.tier
        peer_urls = None
        if t.peer_fast_roots:
            peer_urls = [
                f"{r.rstrip('/')}/{self.prefix}{step:010d}"
                for r in t.peer_fast_roots
            ]
        return {
            "tier": {
                "fast_url": self.fast_path_for_step(step),
                "policy": t.policy,
                "replica_count": t.replica_count,
                "peer_fast_urls": peer_urls,
                "verify_fast_reads": t.verify_fast_reads,
            }
        }

    @property
    def _coord(self) -> Coordinator:
        return self._coordinator or get_default_coordinator()

    # -------------------------------------------------------- discovery

    def _read_index(self) -> List[int]:
        storage = url_to_storage_plugin(self.root)
        try:
            read_io = ReadIO(path=INDEX_FNAME)
            storage.sync_read(read_io)
            data = json.loads(bytes(read_io.buf).decode())
            return sorted(int(s) for s in data.get("steps", []))
        except FileNotFoundError:
            return []
        except Exception as e:  # corrupt index: rebuild from scan
            logger.warning("unreadable %s (%r); falling back to scan",
                           INDEX_FNAME, e)
            return []
        finally:
            storage.sync_close()

    def _write_index(self, steps: Sequence[int]) -> None:
        payload = json.dumps({"steps": sorted(set(steps))}).encode()
        storage = url_to_storage_plugin(self.root)
        try:
            storage.sync_write(WriteIO(path=INDEX_FNAME, buf=payload))
        finally:
            storage.sync_close()

    def _scan_fs(self) -> List[int]:
        """Local-fs fallback: find committed snapshots by directory scan
        (also catches snapshots taken without the manager).  Tiered
        managers additionally scan the fast root — a write-back step
        whose promotion hasn't landed is only discoverable there."""
        steps = set(self._scan_dir(self.root))
        if self.tier is not None:
            steps |= set(self._scan_dir(self.tier.fast_root))
        return sorted(steps)

    def _scan_dir(
        self, root: str, require_metadata: bool = True
    ) -> List[int]:
        """``require_metadata=False`` (fast-tier retention only): count a
        step dir as resident even without its commit marker — a durable
        fallback repairs data objects but deliberately not metadata, and
        those part-repaired dirs must stay evictable."""
        import os
        import re

        if "://" in root and not root.startswith("file://"):
            return []
        base = root.split("://", 1)[-1]
        pat = re.compile(re.escape(self.prefix) + r"(\d+)$")
        steps = []
        try:
            names = os.listdir(base)
        except FileNotFoundError:
            return []
        for name in names:
            m = pat.fullmatch(name)
            if m and (
                not require_metadata
                or os.path.exists(
                    os.path.join(base, name, SNAPSHOT_METADATA_FNAME)
                )
            ):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def _verify(
        self, candidates: set, use_cache: bool = False
    ) -> Dict[int, Snapshot]:
        """step → Snapshot (metadata verified) for committed candidates,
        ascending.  The index is advisory; only the commit protocol is
        trusted — unreadable/corrupt metadata means "not committed" here
        (GC can still evict it), never a crash that bricks resume for
        the snapshots that ARE fine.

        ``use_cache`` (retention sweeps only): commits are immutable, so
        re-verifying every committed step on every save would be wasted
        cloud reads.  Public discovery (steps / restore_latest) always
        verifies fresh — external damage to a snapshot must not hide
        behind the cache when choosing what to restore."""
        committed: Dict[int, Snapshot] = {}
        self._last_unverifiable: set = set()
        for step in sorted(candidates):
            if use_cache and step in self._verified:
                committed[step] = self._verified[step]
                continue
            snap = Snapshot(
                self.path_for_step(step),
                storage_options=self._tier_storage_options(step),
            )
            try:
                snap.metadata
            except FileNotFoundError:
                # definitively uncommitted (the metadata object is absent)
                self._verified.pop(step, None)
                continue
            except Exception as e:  # noqa: BLE001 — corrupt OR transient
                logger.warning(
                    "step %d has unreadable metadata (%r); treating as "
                    "uncommitted for this call", step, e,
                )
                # could be a storage outage: the step must NOT be
                # dropped from the index over this (see _after_commit)
                self._last_unverifiable.add(step)
                self._verified.pop(step, None)
                continue
            self._verified[step] = snap
            committed[step] = snap
        return committed

    def _committed(self, use_cache: bool = False) -> Dict[int, Snapshot]:
        return self._verify(
            set(self._read_index()) | set(self._scan_fs()),
            use_cache=use_cache,
        )

    def steps(self) -> List[int]:
        """Committed steps, ascending (index ∪ local scan)."""
        from .obs import span

        with span("manager/steps", root=self.root):
            return list(self._committed())

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def snapshot(self, step: int) -> Snapshot:
        return Snapshot(
            self.path_for_step(step),
            coordinator=self._coordinator,
            storage_options=self._tier_storage_options(step),
        )

    def durable_steps(self) -> List[int]:
        """Steps whose DURABLE-tier commit marker is readable — the
        steps that would survive losing every fast tier.  A write-back
        step appears in ``steps()`` (restorable from its fast tier) as
        soon as its fast commit lands, but only joins this list once the
        background promoter finished.  Untiered managers: == steps()."""
        with obs.span("manager/durable_steps", root=self.root):
            if self.tier is None:
                return self.steps()
            return [
                step
                for step in sorted(
                    set(self._read_index()) | set(self._scan_fs())
                )
                if self._durable_ok(step)
            ]

    # ------------------------------------------------------- save/load

    def save(
        self,
        app_state: Dict[str, Any],
        step: int,
        replicated: Sequence[str] = (),
        async_: bool = False,
        incremental: bool = False,
        **take_kwargs: Any,
    ) -> Union[Snapshot, "_ManagedPendingSnapshot"]:
        """``**take_kwargs`` forward to ``Snapshot.take``/``async_take``
        (``leaf_transform``, ``storage_options``).

        ``incremental=True`` dedups against the newest committed step:
        objects whose content checksum is unchanged are hardlinked /
        server-side-copied instead of rewritten (Snapshot.take(base=)).
        Cold start (no committed step) degrades to a full save."""
        with log_event(
            Event(
                "manager_save",
                {"root": self.root, "step": step, "async": async_},
            )
        ):
            return self._save_impl(
                app_state, step, replicated, async_, incremental,
                **take_kwargs,
            )

    def _save_impl(
        self,
        app_state: Dict[str, Any],
        step: int,
        replicated: Sequence[str] = (),
        async_: bool = False,
        incremental: bool = False,
        **take_kwargs: Any,
    ) -> Union[Snapshot, "_ManagedPendingSnapshot"]:
        # crash-recovery sweep BEFORE the first take of this process:
        # at that point nothing from this process is in the promotion
        # queue, so only steps orphaned by a previous crash re-enqueue
        if self.tier is not None and not self._repromoted:
            self.repromote()
        path = self.path_for_step(step)
        tier_opts = self._tier_storage_options(step)
        if tier_opts is not None:
            take_kwargs["storage_options"] = {
                **(take_kwargs.get("storage_options") or {}),
                **tier_opts,
            }
        if self.cas is not None:
            take_kwargs["cas"] = self.cas
        base: Optional[str] = None
        if incremental and self.cas is None:
            # the chunk store subsumes whole-object base links: with cas
            # on, EVERY save already dedups against all committed steps
            prev = self._coord.broadcast_object(
                self.latest_step() if self._coord.rank == 0 else None,
                src=0,
            )
            if prev is not None:
                base = self.path_for_step(prev)
        if async_:
            pending = Snapshot.async_take(
                path, app_state, replicated=replicated,
                coordinator=self._coordinator, base=base, **take_kwargs,
            )
            # index/retention must not run from the commit thread (it
            # would race a training-loop save() on the index): they run
            # when the caller joins the pending snapshot, plus at the
            # next sync save as a safety net for never-waited pendings
            if self._coord.rank == 0:
                self._pending_async[step] = weakref.ref(pending)
            return _ManagedPendingSnapshot(pending, self, step)
        snap = Snapshot.take(
            path, app_state, replicated=replicated,
            coordinator=self._coordinator, base=base, **take_kwargs,
        )
        self._after_commit(step)
        self._publish(step, snap)
        return snap

    def restore_latest(
        self,
        app_state: Dict[str, Any],
        strict: bool = True,
        paths: Optional[Sequence[str]] = None,
    ) -> Optional[int]:
        """Restore from the newest committed snapshot.  Returns its step,
        or ``None`` on cold start (nothing committed yet).  All ranks
        agree on the choice: rank 0 resolves, everyone else follows.
        ``paths`` filters to matching leaves (Snapshot.restore)."""
        with log_event(
            Event("manager_restore_latest", {"root": self.root})
        ) as event:
            step = self._coord.broadcast_object(
                self.latest_step() if self._coord.rank == 0 else None, src=0
            )
            event.metadata["step"] = step
            if step is None:
                return None
            self.snapshot(step).restore(app_state, strict=strict, paths=paths)
            return step

    # ------------------------------------------------------- retention

    def repromote(self) -> List[int]:
        """Crash recovery for write-back tiers: re-enqueue promotion for
        every fast-committed step whose durable commit marker is missing
        (the promotion queue is in-memory, so a crash between fast-tier
        commit and durable commit would otherwise leave acked steps
        non-durable forever).  Rank-local — each host contributes the
        objects its own fast root holds; the durable marker is written
        only once every manifest location is durable-resident
        (PromotionGroup.recovery), so partial multi-host recovery can
        never fabricate a committed-but-incomplete durable snapshot.
        Runs automatically once per manager at the first post-commit
        sweep; returns the steps enqueued."""
        with obs.span("manager/repromote", root=self.root):
            self._repromoted = True
            if self.tier is None:
                return []
            from .tier.promoter import PromotionGroup, get_promoter

            enqueued = []
            idx = set(self._read_index())
            for step in self._scan_dir(self.tier.fast_root):
                if self._durable_ok(step):
                    continue
                # same guard as _apply_fast_retention: a step the index
                # no longer lists (with a newer indexed step present)
                # was durably EVICTED by retention — its fast leftovers
                # are garbage, and re-promoting would resurrect a
                # deleted snapshot into the durable tier
                if idx and step not in idx and step < max(idx):
                    continue
                try:
                    fast_md = Snapshot(
                        self.fast_path_for_step(step)
                    ).metadata
                except Exception:  # noqa: BLE001 — not fast-committed
                    continue
                group = PromotionGroup(
                    self.fast_path_for_step(step),
                    self.path_for_step(step),
                )
                # chunk-ref'd locations are NOT per-step objects: their
                # bytes already live in the (durable-rooted) chunk pool,
                # so the promoter copies only what isn't durable yet
                group.paths = set(
                    entry_locations(fast_md.manifest)
                ) - set((fast_md.cas or {}).get("chunks") or {})
                group.recovery = True
                promoter = get_promoter()
                promoter.enqueue_data(group)
                promoter.enqueue_commit(group)
                logger.warning(
                    "re-promoting step %d: fast-committed but no durable "
                    "commit marker (promotion interrupted by a previous "
                    "crash?)", step,
                )
                enqueued.append(step)
            return enqueued

    def _durable_ok(self, step: int) -> bool:
        """Durable commit marker readable? Cached positively (durability
        is monotonic)."""
        if self.tier is None:
            return True
        if step in self._durable_confirmed:
            return True
        try:
            Snapshot(self.path_for_step(step)).metadata  # noqa: B018
        except Exception:  # noqa: BLE001 — absent or unreachable
            return False
        self._durable_confirmed.add(step)
        return True

    def _after_commit(self, step: Optional[int]) -> None:
        # fast-tier retention is rank-LOCAL (each host owns its fast
        # root), so it runs before the rank-0 gate below
        self._apply_fast_retention()
        if self._coord.rank != 0:
            return
        # sweep async saves whose commit has landed by now (index-first
        # stores — cloud — would otherwise never learn about them).
        # done() distinguishes in-flight from finished: an in-flight
        # commit stays queued without a wasted metadata probe; a
        # finished one either committed (index it) or definitively
        # failed (its metadata is absent — drop it).
        candidates = set(self._read_index()) | set(self._scan_fs())
        if step is not None:
            candidates.add(step)
        settled = set()
        for s, ref in self._pending_async.items():
            pending = ref()
            # a dead ref means the commit thread (which holds the object
            # while running) finished and the caller dropped the handle
            if pending is None or pending.done():
                settled.add(s)
        candidates.update(settled)
        committed = self._verify(candidates, use_cache=True)
        for s in settled:
            if s in committed:
                del self._pending_async[s]
            elif s not in self._last_unverifiable:
                logger.warning(
                    "async save for step %d finished without committing; "
                    "dropping it from the sweep list", s,
                )
                del self._pending_async[s]
        # union-preserving index write: a step whose metadata read
        # failed TRANSIENTLY (outage) keeps its index entry — dropping
        # it would orphan a good snapshot forever on stores with no
        # listing; only definitively-absent metadata un-indexes a step
        self._write_index(sorted(set(committed) | self._last_unverifiable))
        self._apply_retention(committed)

    def _publish(self, step: int, snap: Optional[Snapshot]) -> None:
        """Publish a freshly committed step to the live-weight
        publication root (rank 0, best-effort — see __init__)."""
        if self._publisher is None or self._coord.rank != 0:
            return
        try:
            self._publisher.publish_snapshot(
                self.path_for_step(step),
                step,
                metadata=None if snap is None else snap.metadata,
            )
        except Exception as e:  # noqa: BLE001 — publication never
            # fails a committed save; subscribers catch up next step
            obs.swallowed_exception("manager.publish", e)
            logger.warning(
                "publication of committed step %d failed; serving "
                "subscribers stay at the previous published step", step,
            )

    def gc(self) -> None:
        """Apply retention: delete all but the newest ``keep_last_n``
        committed snapshots (rank 0), and — tiered — all but the newest
        ``fast_keep_last_n`` fast-tier copies (every rank, own fast root
        only).  CAS-backed managers additionally run a chunk-pool
        mark+sweep (rank 0).  Safe to call any time."""
        with log_event(Event("manager_gc", {"root": self.root})):
            self._apply_fast_retention()
            if self._coord.rank != 0:
                return
            if self.keep_last_n is not None:
                self._apply_retention(self._committed())
            if self.cas is not None:
                self.cas_gc()

    def cas_gc(
        self, grace_s: Optional[float] = None
    ) -> Optional[Dict[str, Any]]:
        """Two-phase mark+sweep over the shared chunk pool: refs are
        verified against the commit markers, chunks with no committed
        referent are orphan-marked, and marks older than the grace
        window (``TORCHSNAPSHOT_TPU_CAS_GC_GRACE_S`` unless
        ``grace_s``) are re-verified and deleted.  Rank-0 discipline
        like the index.  Returns the sweep summary, or None when CAS is
        off."""
        with obs.span("manager/cas_gc", root=self.root):
            if self.cas is None or self._coord.rank != 0:
                return None
            from . import cas as cas_mod

            steps = sorted(set(self._read_index()) | set(self._scan_fs()))
            return cas_mod.run_gc(
                self.cas["root"],
                [self.path_for_step(s) for s in steps],
                grace_s=grace_s,
            )

    def fsck(self) -> Optional[Dict[str, Any]]:
        """Rebuild the chunk index from this root's committed manifests
        (cas.fsck) — the recovery path after index corruption or a
        crash between a take's index update and its commit marker.
        Returns the rebuild summary, or None when CAS is off."""
        with obs.span("manager/fsck", root=self.root):
            if self.cas is None:
                return None
            from . import cas as cas_mod

            steps = sorted(set(self._read_index()) | set(self._scan_fs()))
            return cas_mod.fsck(
                self.cas["root"],
                [self.path_for_step(s) for s in steps],
            )

    def repair(
        self,
        sources: Sequence[str],
        step: Optional[int] = None,
    ) -> Dict[int, List[str]]:
        """Heal degraded committed snapshots from continuous peer
        stores (``Snapshot.repair_degraded`` — a take that survived a
        rank death may have committed with a ``degraded`` manifest
        section for state only the dead rank held).  ``sources``:
        continuous host roots holding per-rank ``r<d>`` mirrors.
        ``step`` limits the sweep to one step; default = every
        committed step still carrying a degraded section.  Rank-0
        discipline like gc.  Returns ``{step: repaired paths}``.

        Note the other healing path needs no call at all: the NEXT
        committed save is complete by construction, so under retention
        a degraded step simply ages out."""
        with log_event(Event("manager_repair", {"root": self.root})):
            if self._coord.rank != 0:
                return {}
            committed = self._committed()
            targets = (
                [step]
                if step is not None
                else sorted(committed)
            )
            out: Dict[int, List[str]] = {}
            for s in targets:
                snap = committed.get(s) or self.snapshot(s)
                try:
                    degraded = getattr(snap.metadata, "degraded", None)
                except Exception:  # noqa: BLE001 — unreadable: skip
                    continue
                if not degraded:
                    continue
                repaired = snap.repair_degraded(sources)
                if repaired:
                    out[s] = repaired
            return out

    def _apply_retention(self, committed: Dict[int, Snapshot]) -> None:
        if self.keep_last_n is None:
            return
        evict = list(committed)[: -self.keep_last_n]
        for step in evict:
            logger.info("retention: deleting snapshot step %d", step)
            # reuse the just-verified metadata: no re-read, and the
            # chunk tables travel with it so ref release works
            metadata = committed[step].metadata
            delete_snapshot(
                self.path_for_step(step), metadata=metadata
            )
            if self.tier is not None:
                # the evicted step's fast copy goes with it (this rank's
                # fast root; peers evict theirs in their own
                # _apply_fast_retention sweeps).  A degraded fast disk
                # must not fail a save whose checkpoint already
                # committed — the leftover is retried by later sweeps.
                # release_cas=False: the durable delete above already
                # dropped this step's chunk refs; a COPY delete must
                # never double-release them.
                try:
                    delete_snapshot(
                        self.fast_path_for_step(step),
                        metadata=metadata,
                        release_cas=False,
                    )
                except Exception as e:  # noqa: BLE001
                    logger.warning(
                        "fast-tier delete of evicted step %d failed "
                        "(%r); leftover will be retried", step, e,
                    )
            self._verified.pop(step, None)
            self._durable_confirmed.discard(step)
        if evict:
            # keep transiently-unverifiable steps in the index here too
            # (same invariant as _after_commit's union-preserving write)
            self._write_index(
                sorted(
                    (set(committed) - set(evict))
                    | self._last_unverifiable
                )
            )

    def _apply_fast_retention(self) -> None:
        """Evict old fast-tier copies INDEPENDENTLY of durable
        retention: the newest ``fast_keep_last_n`` fast-resident steps
        keep their local copies; older ones are deleted from this
        rank's fast root only — IF the step is safe to lose locally
        (its durable commit marker is readable, or the index shows it
        was evicted entirely).  A write-back step whose promotion
        hasn't landed holds the only copy and is never evicted."""
        if self.tier is None:
            return
        keep = (
            self.tier.fast_keep_last_n
            if self.tier.fast_keep_last_n is not None
            else knobs.get_tier_fast_keep_last_n()
        )
        fast_steps = self._scan_dir(
            self.tier.fast_root, require_metadata=False
        )
        for step in fast_steps[:-keep] if keep else fast_steps:
            # metadata (not just the manifest): the chunk-ref tables
            # travel with it, so the delete skips per-step object
            # deletes for locations that only ever lived in the pool
            metadata = None
            # _durable_ok caches positives, so a step stuck unpromoted
            # (cloud outage) costs ONE metadata probe per sweep and a
            # confirmed-durable step costs none
            durable_ok = self._durable_ok(step)
            if durable_ok:
                try:
                    metadata = Snapshot(
                        self.path_for_step(step)
                    ).metadata
                except Exception as e:  # noqa: BLE001 — fall through below
                    logger.debug(
                        "fast-tier retention: durable manifest read for "
                        "step %d failed (%r); evicting without the "
                        "object list", step, e,
                    )
            if not durable_ok:
                # durable-evicted steps (no longer in the index, and a
                # newer indexed step exists) lost their durable copy on
                # purpose — their fast leftovers are garbage, not the
                # last line of defense
                idx = set(self._read_index())
                if not (idx and step not in idx and step < max(idx)):
                    logger.info(
                        "fast-tier retention: keeping step %d — not "
                        "durably committed yet", step,
                    )
                    continue
                try:
                    metadata = Snapshot(
                        self.fast_path_for_step(step)
                    ).metadata
                except Exception as e:  # noqa: BLE001
                    logger.debug(
                        "fast-tier retention: fast manifest read for "
                        "step %d failed (%r); evicting without the "
                        "object list", step, e,
                    )
                    metadata = None
            logger.info(
                "fast-tier retention: evicting local copy of step %d",
                step,
            )
            try:
                # cross-tier GC is refcount-aware: evicting the FAST
                # copy of a durably-committed step must not release the
                # step's chunk refs — the durable step still owns them
                delete_snapshot(
                    self.fast_path_for_step(step),
                    metadata=metadata,
                    release_cas=False,
                )
            except Exception as e:  # noqa: BLE001 — degraded fast disk
                # must not abort an already-committed save
                logger.warning(
                    "fast-tier eviction of step %d failed (%r); "
                    "leftover will be retried next sweep", step, e,
                )


class _ManagedPendingSnapshot:
    """PendingSnapshot plus the manager's post-commit bookkeeping:
    ``wait()`` joins the background commit, then (rank 0) records the
    step in the index and applies retention — the point at which an
    async save becomes discoverable on stores with no directory
    listing."""

    def __init__(
        self, pending: PendingSnapshot, manager: "SnapshotManager",
        step: int,
    ) -> None:
        self._pending = pending
        self._manager = manager
        self._step = step

    def wait(self) -> Snapshot:
        snap = self._pending.wait()
        self._manager._after_commit(self._step)
        self._manager._publish(self._step, snap)
        return snap

    def done(self) -> bool:
        return self._pending.done()
