"""The checkpointable-object protocol and built-in helpers.

Reference: torchsnapshot/stateful.py:15-23 (duck-typed protocol),
state_dict.py:15-29 (StateDict), rng_state.py:15-47 (RNGState).

JAX is functional, so alongside the mutable-protocol helpers we provide
``PyTreeState``: a wrapper that makes any pytree (flax/optax train states,
raw param dicts, ...) checkpointable by holding it as a replaceable
reference — the idiomatic JAX equivalent of in-place ``load_state_dict``.
"""

from __future__ import annotations

import random
from collections import UserDict
from typing import Any, Dict, Protocol, runtime_checkable

import numpy as np


@runtime_checkable
class Stateful(Protocol):
    def state_dict(self) -> Dict[str, Any]: ...

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None: ...


class StateDict(UserDict):
    """Dict wrapper making plain values checkpointable (reference
    state_dict.py:15-29)."""

    def state_dict(self) -> Dict[str, Any]:
        return self.data

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        self.data.update(state_dict)


class PyTreeState:
    """Checkpointable wrapper around an arbitrary JAX pytree.

    ``state_dict`` flattens the tree to a leaf list (saved leaf-by-leaf, so
    jax.Array leaves keep their shardings as restore templates);
    ``load_state_dict`` rebuilds the tree with the *current* treedef, which
    doubles as a structural-compatibility check on restore.
    """

    def __init__(self, tree: Any) -> None:
        self.tree = tree

    def state_dict(self) -> Dict[str, Any]:
        import jax

        leaves = jax.tree_util.tree_leaves(self.tree)
        return {"leaves": leaves}

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        import jax

        treedef = jax.tree_util.tree_structure(self.tree)
        leaves = state_dict["leaves"]
        if treedef.num_leaves != len(leaves):
            raise ValueError(
                f"cannot load {len(leaves)} leaves into a tree with "
                f"{treedef.num_leaves} leaves"
            )
        self.tree = jax.tree_util.tree_unflatten(treedef, leaves)


class RNGState:
    """Captures/restores host RNG state (python ``random`` + global numpy).

    Reference rng_state.py:15-47 captures torch's global RNG; JAX's RNG is
    explicit (PRNG keys are ordinary arrays in the app state), so only host
    RNGs need capturing here.
    """

    def state_dict(self) -> Dict[str, Any]:
        return {
            "python": random.getstate(),
            "numpy": np.random.get_state(),
        }

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        random.setstate(_as_tuple(state_dict["python"]))
        np.random.set_state(_as_tuple(state_dict["numpy"]))


def _as_tuple(v: Any) -> Any:
    # random.setstate requires tuples incl. nested ones
    if isinstance(v, list):
        return tuple(_as_tuple(x) for x in v)
    if isinstance(v, tuple):
        return tuple(_as_tuple(x) for x in v)
    return v
