"""The checkpointable-object protocol and built-in helpers.

Reference: torchsnapshot/stateful.py:15-23 (duck-typed protocol),
state_dict.py:15-29 (StateDict), rng_state.py:15-47 (RNGState).

JAX is functional, so alongside the mutable-protocol helpers we provide
``PyTreeState``: a wrapper that makes any pytree (flax/optax train states,
raw param dicts, ...) checkpointable by holding it as a replaceable
reference — the idiomatic JAX equivalent of in-place ``load_state_dict``.
"""

from __future__ import annotations

import random
from collections import UserDict
from typing import Any, Dict, Protocol, runtime_checkable

import numpy as np


@runtime_checkable
class Stateful(Protocol):
    def state_dict(self) -> Dict[str, Any]: ...

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None: ...


class StateDict(UserDict):
    """Dict wrapper making plain values checkpointable (reference
    state_dict.py:15-29)."""

    def state_dict(self) -> Dict[str, Any]:
        return self.data

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        self.data.update(state_dict)


_ROOT_LEAF_KEY = "__root__"


def _path_entry_str(entry: Any) -> str:
    """One pytree path entry → manifest path segment.

    DictKey('wq') → 'wq', GetAttrKey('params') → 'params' (flax structs,
    optax states), SequenceKey(3) → '3', FlattenedIndexKey(i) → str(i).
    """
    import jax

    tu = jax.tree_util
    if isinstance(entry, tu.DictKey):
        return str(entry.key)
    if isinstance(entry, tu.GetAttrKey):
        return str(entry.name)
    if isinstance(entry, tu.SequenceKey):
        return str(entry.idx)
    if isinstance(entry, tu.FlattenedIndexKey):
        return str(entry.key)
    return str(entry)


def _tree_path_keys(tree: Any):
    """[(path_key_strings, leaf), ...] in tree_flatten order, plus the
    treedef.  Raises on two paths stringifying identically (e.g. a dict
    with both 0 and "0" as keys) — silent overwrites would corrupt the
    snapshot."""
    import jax

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    seen = set()
    for path, leaf in leaves_with_paths:
        keys = tuple(_path_entry_str(p) for p in path) or (_ROOT_LEAF_KEY,)
        if keys in seen:
            raise ValueError(
                f"pytree paths collide after stringification: {keys!r}"
            )
        seen.add(keys)
        out.append((keys, leaf))
    return out, treedef


def _leaf_paths_of(node: Any, prefix: tuple = ()):
    """Leaf paths of a nested state-dict (dicts/lists as containers)."""
    if isinstance(node, dict):
        for k, v in node.items():
            yield from _leaf_paths_of(v, prefix + (str(k),))
    elif isinstance(node, (list, tuple)):
        for i, v in enumerate(node):
            yield from _leaf_paths_of(v, prefix + (str(i),))
    else:
        yield prefix or (_ROOT_LEAF_KEY,)


class PyTreeState:
    """Checkpointable wrapper around an arbitrary JAX pytree.

    ``state_dict`` renders the tree as a NESTED NAMED dict using
    ``jax.tree_util.tree_flatten_with_path``, so manifests carry real
    names — ``ts/params/layer0/wq`` — making ``read_object`` addressable
    and per-path partial restore meaningful (the role the reference's
    whole flatten layer plays, flatten.py:20).  jax.Array leaves keep
    their shardings as restore templates.

    ``load_state_dict`` maps the named dict back onto the *current*
    tree's structure (a structural-compatibility check), keeping the
    current leaf for paths missing from the snapshot when
    ``strict=False`` (elastic restore).  Snapshots written by older
    versions (flat ``{"leaves": [...]}``) load positionally.
    """

    def __init__(self, tree: Any) -> None:
        self.tree = tree

    def state_dict(self) -> Dict[str, Any]:
        pairs, _ = _tree_path_keys(self.tree)
        out: Dict[str, Any] = {}
        for keys, leaf in pairs:
            node = out
            for k in keys[:-1]:
                node = node.setdefault(k, {})
            node[keys[-1]] = leaf
        return out

    def load_state_dict(
        self, state_dict: Dict[str, Any], strict: bool = True
    ) -> None:
        import jax

        if self._is_legacy_format(state_dict):
            treedef = jax.tree_util.tree_structure(self.tree)
            leaves = state_dict["leaves"]
            if treedef.num_leaves != len(leaves):
                raise ValueError(
                    f"cannot load {len(leaves)} leaves into a tree with "
                    f"{treedef.num_leaves} leaves"
                )
            self.tree = jax.tree_util.tree_unflatten(treedef, leaves)
            return

        pairs, treedef = _tree_path_keys(self.tree)
        new_leaves = []
        missing = []
        consumed = set()
        for keys, current in pairs:
            node: Any = state_dict
            try:
                for k in keys:
                    # sequence nodes appear when a snapshot predates the
                    # dict-rendering of lists (or coincides with it)
                    node = (
                        node[int(k)]
                        if isinstance(node, (list, tuple))
                        else node[k]
                    )
                if isinstance(node, (dict, list, tuple)):
                    # a CONTAINER where the template has a leaf is a
                    # structural mismatch, not a loadable value
                    raise KeyError(keys)
                consumed.add(keys)
            except (KeyError, TypeError, IndexError, ValueError):
                missing.append("/".join(keys))
                node = current  # elastic: keep the template's leaf
            new_leaves.append(node)
        if strict:
            surplus = [
                "/".join(p)
                for p in _leaf_paths_of(state_dict)
                if p not in consumed
            ]
            if missing or surplus:
                raise ValueError(
                    f"structure mismatch (pass strict=False for elastic "
                    f"load): {len(missing)} template path(s) missing from "
                    f"snapshot {missing[:5]}, {len(surplus)} snapshot "
                    f"path(s) absent from template {surplus[:5]}"
                )
        self.tree = jax.tree_util.tree_unflatten(treedef, new_leaves)

    def _is_legacy_format(self, state_dict: Dict[str, Any]) -> bool:
        """Snapshots from the leaf-list era read {"leaves": [...]}; only
        treat that as legacy when the wrapped tree itself doesn't look
        like such a dict (in which case both formats coincide anyway)."""
        if set(state_dict.keys()) != {"leaves"}:
            return False
        if not isinstance(state_dict["leaves"], (list, tuple)):
            return False
        pairs, _ = _tree_path_keys(self.tree)
        return not all(keys[0] == "leaves" for keys, _ in pairs)


class Replicated:
    """Marker wrapper declaring a stateful's entire state replicated
    across ranks.

    The reference auto-infers replication only for DDP-wrapped torch
    modules (snapshot.py:896-918); everything else needs explicit globs.
    On TPU, jax.Array replication is implicit in the sharding, but host
    state (numpy arrays, torch CPU tensors, plain objects) carries no
    sharding metadata — this wrapper is the explicit, type-level way to
    say "every rank holds the same copy; balance the write across ranks
    and persist it once".  ``Snapshot.take`` expands it to a ``key/**``
    replication glob automatically; content verification still applies,
    so a wrong claim demotes to per-rank instead of corrupting the save.
    """

    replicated = True

    def __init__(self, stateful: Any) -> None:
        if isinstance(stateful, RNGState):
            # RNGState gets entry-capture/restore special-casing in
            # Snapshot.take keyed on isinstance; hiding it behind a
            # wrapper would silently break the "take never perturbs RNG"
            # invariant — and replicating RNG streams across ranks is
            # almost never what dp training wants anyway.
            raise ValueError(
                "Replicated(RNGState()) is not supported: pass the "
                "RNGState directly (RNG streams are per-rank state)"
            )
        if not isinstance(stateful, Stateful):
            import collections.abc

            if not isinstance(stateful, collections.abc.MutableMapping):
                raise TypeError(
                    "Replicated(...) takes a Stateful or a mutable mapping; "
                    f"got {type(stateful).__name__}. Wrap leaves in a dict: "
                    "Replicated({'emb': arr})"
                )
            # share the caller's mapping instead of copying it, so a
            # restore through the wrapper is visible in the original dict
            wrapped = StateDict()
            wrapped.data = stateful
            stateful = wrapped
        self.stateful = stateful

    def state_dict(self) -> Dict[str, Any]:
        return self.stateful.state_dict()

    def load_state_dict(
        self, state_dict: Dict[str, Any], strict: bool = True
    ) -> None:
        # ``strict`` declared by name so restore's signature probe sees it
        load_with_strict(self.stateful, state_dict, strict)


def unwrap(stateful: Any) -> Any:
    """The innermost stateful behind any chain of marker wrappers —
    isinstance-keyed special cases (e.g. PyTreeState restore templates)
    must see through ``Replicated``."""
    while isinstance(stateful, Replicated):
        stateful = stateful.stateful
    return stateful


def load_with_strict(stateful: Any, state_dict: Dict[str, Any], strict: bool) -> None:
    """Call ``load_state_dict``, forwarding ``strict`` only when the
    stateful's signature accepts it (reference snapshot.py:775-778 probes
    nn.Module the same way)."""
    import inspect

    try:
        accepts = "strict" in inspect.signature(
            stateful.load_state_dict
        ).parameters
    except (TypeError, ValueError):
        accepts = False
    if accepts:
        stateful.load_state_dict(state_dict, strict=strict)
    else:
        stateful.load_state_dict(state_dict)


class RNGState:
    """Captures/restores host RNG state (python ``random`` + global numpy).

    Reference rng_state.py:15-47 captures torch's global RNG; JAX's RNG is
    explicit (PRNG keys are ordinary arrays in the app state), so only host
    RNGs need capturing here.
    """

    def state_dict(self) -> Dict[str, Any]:
        return {
            "python": random.getstate(),
            "numpy": np.random.get_state(),
        }

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        random.setstate(_as_tuple(state_dict["python"]))
        np.random.set_state(_as_tuple(state_dict["numpy"]))


def _as_tuple(v: Any) -> Any:
    # random.setstate requires tuples incl. nested ones
    if isinstance(v, list):
        return tuple(_as_tuple(x) for x in v)
    if isinstance(v, tuple):
        return tuple(_as_tuple(x) for x in v)
    return v
