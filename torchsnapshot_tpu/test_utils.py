"""Shared test helpers: array-aware state-dict equality, random arrays for
every supported dtype, multi-process launchers.

Reference: torchsnapshot/test_utils.py:52-270 (tensor-aware equality incl.
ShardedTensor, rand_tensor over all dtypes, run_with_pet multi-process
decorators).  The multi-process launcher here spawns plain subprocesses
coordinated through FileCoordinator — no torch-elastic needed.
"""

from __future__ import annotations

import math
import os
import subprocess
import sys
import textwrap
from typing import Any, Dict, List, Optional

import numpy as np


def _is_jax_array(x: Any) -> bool:
    mod = type(x).__module__.split(".")[0]
    if mod not in ("jax", "jaxlib"):
        return False
    import jax

    return isinstance(x, jax.Array)


def _to_numpy(x: Any) -> np.ndarray:
    if _is_jax_array(x):
        return np.asarray(x)
    if type(x).__module__.split(".")[0] == "torch":
        return x.detach().cpu().numpy()
    return np.asarray(x)


def assert_state_dict_eq(a: Any, b: Any, path: str = "") -> None:
    """Structural equality with array-aware leaf comparison (reference
    check_state_dict_eq, test_utils.py:52-126)."""
    arr_a = isinstance(a, np.ndarray) or _is_jax_array(a) or hasattr(a, "detach")
    arr_b = isinstance(b, np.ndarray) or _is_jax_array(b) or hasattr(b, "detach")
    if arr_a or arr_b:
        na, nb = _to_numpy(a), _to_numpy(b)
        assert na.shape == nb.shape, f"{path}: shape {na.shape} != {nb.shape}"
        assert na.dtype == nb.dtype, f"{path}: dtype {na.dtype} != {nb.dtype}"
        if na.dtype.kind == "f" or na.dtype.name in ("bfloat16",):
            np.testing.assert_allclose(
                na.astype(np.float64),
                nb.astype(np.float64),
                rtol=1e-6,
                atol=0,
                err_msg=path,
            )
        else:
            np.testing.assert_array_equal(na, nb, err_msg=path)
        return
    if isinstance(a, dict) and isinstance(b, dict):
        assert a.keys() == b.keys(), f"{path}: keys {a.keys()} != {b.keys()}"
        for k in a:
            assert_state_dict_eq(a[k], b[k], f"{path}/{k}")
        return
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        assert len(a) == len(b), f"{path}: len {len(a)} != {len(b)}"
        for i, (x, y) in enumerate(zip(a, b)):
            assert_state_dict_eq(x, y, f"{path}/{i}")
        return
    if isinstance(a, float) and isinstance(b, float):
        assert math.isclose(a, b, rel_tol=1e-9) or (
            math.isnan(a) and math.isnan(b)
        ), f"{path}: {a} != {b}"
        return
    assert a == b, f"{path}: {a!r} != {b!r}"


def rand_array(shape, dtype, seed: int = 0) -> np.ndarray:
    """Random array valid for any supported dtype (reference rand_tensor,
    test_utils.py:129-169)."""
    rng = np.random.default_rng(seed)
    dt = np.dtype(dtype)
    if dt.kind in "fc" or dt.name.startswith(("bfloat", "float8")):
        return rng.standard_normal(shape).astype(dtype)
    if dt.kind == "b":
        return rng.integers(0, 2, size=shape).astype(bool)
    if dt.kind in "iu":
        info = np.iinfo(dt)
        lo, hi = max(info.min, -1000), min(info.max, 1000)
        return rng.integers(lo, hi + 1, size=shape).astype(dtype)
    raise ValueError(f"unsupported dtype {dtype}")


def run_multiprocess(
    tmp_path,
    world_size: int,
    body: str,
    repo_root: Optional[str] = None,
    timeout_s: float = 120.0,
) -> List[str]:
    """Run ``body`` (python source with rank/world/coord/snap_dir bound) in
    ``world_size`` coordinated subprocesses (reference run_with_pet,
    test_utils.py:232-270)."""
    repo = repo_root or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(str(tmp_path), "mp_worker.py")
    with open(script, "w") as f:
        f.write(
            textwrap.dedent(
                f"""
                import sys
                sys.path.insert(0, {repo!r})
                import numpy as np
                from torchsnapshot_tpu import FileCoordinator, Snapshot, StateDict

                rank = int(sys.argv[1])
                world = int(sys.argv[2])
                coord = FileCoordinator({os.path.join(str(tmp_path), "kv")!r}, rank, world)
                snap_dir = {os.path.join(str(tmp_path), "snap")!r}
                """
            )
            + textwrap.dedent(body)
        )
    env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
    procs = [
        subprocess.Popen(
            [sys.executable, script, str(r), str(world_size)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for r in range(world_size)
    ]
    outs = [p.communicate(timeout=timeout_s)[0].decode() for p in procs]
    for r, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise AssertionError(f"worker {r} failed:\n{out}")
    return outs
